"""GPT-2 decoder-only transformer (BASELINE.json:configs[4]).

Capability parity with the reference's GPT-2 124M example (12L/768H/12
heads, vocab 50257, 1024 positions, tied embeddings, gelu_new, pre-LN),
designed TPU-first rather than translated:

- Attention runs through ``parallel.mesh_attention``: the Pallas flash
  kernel on a single chip, ring/Ulysses context parallelism when the
  mesh's ``context`` axis is real, all under one jitted step.
- QKV/output projections are ``DenseGeneral`` over an explicit
  [heads, head_dim] layout so tensor parallelism is a *sharding rule*
  (heads over the ``model`` mesh axis — see ``GPT2_RULES``), not a
  code path; XLA inserts the Megatron-style collectives.
- Activation shardings are pinned with ``with_sharding_constraint`` at
  the residual stream so the partitioner never wanders.
- ``remat=True`` checkpoints each block (recompute in backward) — the
  HBM/FLOPs trade that makes long-context training fit.
- Decode mode keeps a KV cache (flax ``cache`` collection) with static
  shapes: prefill writes the whole prompt in one call, then single-token
  steps — both compile once per distinct query length.

Weight layout matches HF ``GPT2LMHeadModel`` modulo reshapes so
``models.hf_import`` can load pretrained checkpoints (the reference's
BERT/GPT-2 pretrained-weight restore, SURVEY.md §5d).

``train``/``decode`` are module *fields*, not call arguments: they are
compile-time modes, and as fields they stay static under ``nn.remat``
with no static_argnums bookkeeping.
"""

from __future__ import annotations

import dataclasses
import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tensorflow_examples_tpu.core.mesh import AxisNames
from tensorflow_examples_tpu.core.sharding import ShardingRules
from tensorflow_examples_tpu.ops.attention import NEG_INF
from tensorflow_examples_tpu.ops.decode import decode_attention_reference
from tensorflow_examples_tpu.parallel.attention import mesh_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    max_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int = 0  # 0 → 4 * d_model
    dropout: float = 0.1
    attention: str = "flash"  # flash | xla | ring | ulysses
    remat: bool = False
    remat_policy: str = "none"  # none (recompute all) | dots (save matmul
    #   outputs, recompute elementwise — less recompute, more memory) |
    #   dots_no_batch (save only non-batch-dim dots). Numerics are
    #   identical across policies; only the memory/recompute trade moves.
    # Mixture-of-Experts (parallel/moe.py): 0 = dense MLP everywhere;
    # E > 0 swaps the MLP of every ``moe_every``-th block for a top-1
    # Switch MoE with E experts (sharded over `model` on a mesh = EP).
    moe_experts: int = 0
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_top_k: int = 1
    # Single-program dispatch formulation: "" = backend default
    # (grouped on TPU, scatter elsewhere — parallel/moe.py). Pin
    # "grouped" or "scatter" when a run must compute the SAME
    # function across backends (grouped is dropless; scatter drops
    # at capacity).
    moe_impl: str = ""

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def ff_dim(self) -> int:
        return self.d_ff or 4 * self.d_model


def gpt2_124m(**overrides) -> TransformerConfig:
    return TransformerConfig(**overrides)


# TP/FSDP rules (core.sharding table; axes of size 1 are dropped by the
# mesh filter, so the same table serves pure-DP through full 4D meshes).
_M, _F = AxisNames.MODEL, AxisNames.FSDP
GPT2_RULES = ShardingRules(
    [
        (r"attn/qkv/kernel", P(_F, None, _M, None)),
        (r"attn/qkv/bias", P(None, _M, None)),
        (r"attn/proj/kernel", P(_M, None, _F)),
        (r"mlp_fc/kernel", P(_F, _M)),
        (r"mlp_fc/bias", P(_M)),
        (r"mlp_proj/kernel", P(_M, _F)),
        # MoE expert parallelism: experts ride the `model` axis.
        (r"moe/w_in", P(_M, None, None)),
        (r"moe/b_in", P(_M, None)),
        (r"moe/w_out", P(_M, None, None)),
        (r"moe/b_out", P(_M, None)),
        # Embeddings replicated: the tied head needs full-vocab logits for
        # the fused CE kernel (vocab-sharded CE is a later optimization).
    ]
)


def _shard(x, mesh: Mesh | None, *spec):
    """Pin an activation's sharding when a mesh is provided. A dim whose
    size the spec'd mesh axes don't divide (decode-time batch=1, single-
    token steps) replicates instead — the constraint is an optimization
    hint, not a shape contract."""
    if mesh is None:
        return x
    import math

    from tensorflow_examples_tpu.core.sharding import named_sharding

    fitted = []
    for dim, s in zip(x.shape, spec):
        axes = (s,) if isinstance(s, str) else (s or ())
        n = math.prod(mesh.shape[a] for a in axes)
        fitted.append(s if n and dim % n == 0 else None)
    return jax.lax.with_sharding_constraint(x, named_sharding(mesh, *fitted))


_BATCH = AxisNames.BATCH_AXES


class Attention(nn.Module):
    """Multi-head causal self-attention with optional KV-cache decode."""

    cfg: TransformerConfig
    mesh: Mesh | None
    train: bool
    decode: bool

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h, hd = cfg.num_heads, cfg.head_dim
        qkv = nn.DenseGeneral(
            features=(3, h, hd),
            kernel_init=nn.initializers.normal(0.02),
            dtype=x.dtype,
            name="qkv",
        )(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]

        if self.decode:
            out = self._decode_attend(q, k, v)
        else:
            # [B, S, H, D] → [B, H, S, D] for the kernel.
            swap = lambda t: t.transpose(0, 2, 1, 3)
            out = mesh_attention(
                swap(q), swap(k), swap(v),
                mesh=self.mesh, causal=True, impl=cfg.attention,
            )
            out = out.transpose(0, 2, 1, 3)

        out = nn.DenseGeneral(
            features=cfg.d_model,
            axis=(-2, -1),
            kernel_init=nn.initializers.normal(
                0.02 / (2 * cfg.num_layers) ** 0.5
            ),
            dtype=x.dtype,
            name="proj",
        )(out)
        return nn.Dropout(cfg.dropout, deterministic=not self.train)(out)

    def _decode_attend(self, q, k, v):
        """Append q_len new tokens to the cache and attend over it.

        Static shapes: the cache is [B, H, max_len, D] (heads-major so the
        flash-decode kernel folds batch·head without moving the cache);
        prefill calls pass the whole prompt (q_len = prompt length),
        generation steps pass q_len = 1 — each distinct q_len compiles
        once.

        Attention runs through ``ops.decode.flash_decode_attention``,
        which reads only the populated cache blocks (O(context), not
        O(max_len), HBM traffic per step); ``attention="xla"`` selects
        the plain masked reference instead.
        """
        cfg = self.cfg
        b, q_len, h, hd = q.shape
        swap = lambda t: t.transpose(0, 2, 1, 3)  # [B,S,H,D] → [B,H,S,D]
        ck = self.variable(
            "cache", "key",
            lambda: jnp.zeros((b, h, cfg.max_len, hd), k.dtype),
        )
        cv = self.variable(
            "cache", "value",
            lambda: jnp.zeros((b, h, cfg.max_len, hd), v.dtype),
        )
        idx = self.variable("cache", "index", lambda: jnp.zeros((), jnp.int32))
        i0 = idx.value
        # The cache may have been allocated under a different param dtype
        # (init_cache builds it via eval_shape with f32 init; sampling
        # often runs bf16 params) — store in the cache's dtype.
        ck.value = jax.lax.dynamic_update_slice(
            ck.value, swap(k).astype(ck.value.dtype), (0, 0, i0, 0)
        )
        cv.value = jax.lax.dynamic_update_slice(
            cv.value, swap(v).astype(cv.value.dtype), (0, 0, i0, 0)
        )
        length = i0 + q_len
        idx.value = length

        if cfg.attention == "xla":
            # Dense reference path: XLA's partitioner shards the einsums
            # itself under a mesh, no shard_map needed.
            out = decode_attention_reference(
                swap(q), ck.value, cv.value, length,
                sm_scale=cfg.head_dim**-0.5,
            )
        else:
            from tensorflow_examples_tpu.parallel.attention import (
                mesh_decode_attention,
            )

            out = mesh_decode_attention(
                swap(q), ck.value, cv.value, length,
                mesh=self.mesh, sm_scale=cfg.head_dim**-0.5,
            )
        return swap(out)  # back to [B, S, H, D]


class MoeMlp(nn.Module):
    """Top-k Switch/GShard MoE FFN (parallel/moe.py); aux loss and
    dropped-token fraction sown into the ``intermediates`` collection as
    ``moe_aux`` / ``moe_drop``. On a mesh whose ``model`` axis divides
    the expert count, dispatch runs the explicit all-to-all EP path
    (``moe_ffn_ep``); otherwise the single-program scatter/gather."""

    cfg: TransformerConfig
    train: bool
    mesh: Mesh | None = None

    @nn.compact
    def __call__(self, x):
        from tensorflow_examples_tpu.parallel.moe import moe_ffn, moe_ffn_ep

        cfg = self.cfg
        e, d, ff = cfg.moe_experts, cfg.d_model, cfg.ff_dim
        init = nn.initializers.normal(0.02)
        out_init = nn.initializers.normal(0.02 / (2 * cfg.num_layers) ** 0.5)
        gate = self.param("gate", init, (d, e))
        w_in = self.param("w_in", init, (e, d, ff))
        b_in = self.param("b_in", nn.initializers.zeros, (e, ff))
        w_out = self.param("w_out", out_init, (e, ff, d))
        b_out = self.param("b_out", nn.initializers.zeros, (e, d))
        rng = (
            self.make_rng("dropout")
            if self.train and self.has_rng("dropout")
            else None
        )
        # moe_ffn_ep itself falls back to the single-program path when
        # the mesh's model axis is trivial or doesn't divide E — one
        # predicate, owned by the function that implements it.
        fn = (
            functools.partial(moe_ffn_ep, mesh=self.mesh)
            if self.mesh is not None
            else moe_ffn
        )
        out, aux, drop = fn(
            gate,
            w_in.astype(x.dtype), b_in.astype(x.dtype),
            w_out.astype(x.dtype), b_out.astype(x.dtype),
            x,
            capacity_factor=cfg.moe_capacity_factor,
            top_k=cfg.moe_top_k,
            rng=rng,
            impl=cfg.moe_impl or None,
        )
        self.sow("intermediates", "moe_aux", aux)
        self.sow("intermediates", "moe_drop", drop)
        return out


class Block(nn.Module):
    cfg: TransformerConfig
    mesh: Mesh | None
    train: bool
    decode: bool
    use_moe: bool = False

    @nn.compact
    def __call__(self, x):
        cfg, mesh, decode = self.cfg, self.mesh, self.decode
        ctx = None if decode else AxisNames.CONTEXT
        y = nn.LayerNorm(epsilon=1e-5, dtype=x.dtype, name="ln_1")(x)
        y = Attention(cfg, mesh, self.train, decode, name="attn")(y)
        x = _shard(x + y, mesh, _BATCH, ctx, None)
        y = nn.LayerNorm(epsilon=1e-5, dtype=x.dtype, name="ln_2")(x)
        if self.use_moe:
            y = MoeMlp(cfg, self.train, mesh, name="moe")(y)
        else:
            y = nn.Dense(
                cfg.ff_dim,
                kernel_init=nn.initializers.normal(0.02),
                dtype=x.dtype,
                name="mlp_fc",
            )(y)
            y = nn.gelu(y, approximate=True)
            y = _shard(y, mesh, _BATCH, ctx, AxisNames.MODEL)
            y = nn.Dense(
                cfg.d_model,
                kernel_init=nn.initializers.normal(
                    0.02 / (2 * cfg.num_layers) ** 0.5
                ),
                dtype=x.dtype,
                name="mlp_proj",
            )(y)
        y = nn.Dropout(cfg.dropout, deterministic=not self.train)(y)
        return _shard(x + y, mesh, _BATCH, ctx, None)


class Transformer(nn.Module):
    """GPT-2 style causal LM. ``__call__`` returns logits [B, S, vocab]."""

    cfg: TransformerConfig
    mesh: Mesh | None = None

    @nn.compact
    def __call__(
        self,
        tokens,
        *,
        train: bool = False,
        decode: bool = False,
        return_hidden: bool = False,
    ):
        cfg = self.cfg
        wte = nn.Embed(
            cfg.vocab_size, cfg.d_model,
            embedding_init=nn.initializers.normal(0.02),
            name="wte",
        )
        if decode:
            # Global position rides a top-level cache var so positional
            # embeddings line up with the per-layer KV cache index.
            pos = self.variable(
                "cache", "position", lambda: jnp.zeros((), jnp.int32)
            )
            positions = pos.value + jnp.arange(tokens.shape[1], dtype=jnp.int32)
            pos.value = pos.value + tokens.shape[1]
        else:
            positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)
        wpe = nn.Embed(
            cfg.max_len, cfg.d_model,
            embedding_init=nn.initializers.normal(0.01),
            name="wpe",
        )
        x = wte(tokens) + wpe(positions)[None]
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        ctx = None if decode else AxisNames.CONTEXT
        x = _shard(x, self.mesh, _BATCH, ctx, None)

        block = Block
        if cfg.remat and not decode:
            policies = {
                "none": jax.checkpoint_policies.nothing_saveable,
                "dots": jax.checkpoint_policies.checkpoint_dots,
                "dots_no_batch": (
                    jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
                ),
            }
            if cfg.remat_policy not in policies:
                raise ValueError(
                    f"remat_policy={cfg.remat_policy!r} not in "
                    f"{sorted(policies)}"
                )
            block = nn.remat(
                Block,
                prevent_cse=False,
                policy=policies[cfg.remat_policy],
            )
        for i in range(cfg.num_layers):
            use_moe = (
                cfg.moe_experts > 0 and i % cfg.moe_every == cfg.moe_every - 1
            )
            x = block(cfg, self.mesh, train, decode, use_moe, name=f"h_{i}")(x)

        x = nn.LayerNorm(epsilon=1e-5, dtype=x.dtype, name="ln_f")(x)
        if return_hidden:
            # Caller owns the head (e.g. the vocab-parallel fused CE in
            # ops/cross_entropy.tp_cross_entropy_from_hidden).
            return x
        # Tied LM head: logits = x @ wteᵀ (GPT-2 ties input/output embeds).
        return wte.attend(x)


def sharding_rules(extra: ShardingRules | None = None) -> ShardingRules:
    return GPT2_RULES + extra if extra else GPT2_RULES


# ---------------------------------------------------------------- decoding


def sample_tokens(
    logits: jax.Array,
    rng: jax.Array | None,
    *,
    temperature: float = 1.0,
    top_k: int = 0,
) -> jax.Array:
    """Sample next-token ids from ``logits`` [..., vocab] (greedy when
    ``temperature == 0``; ``top_k > 0`` filters to the k largest logits
    first). The serving engine's ``_sample_row``
    (tensorflow_examples_tpu/serving/engine.py) is the traced-knob
    twin of this math — a batch mixes per-request settings, so the
    static ``if``s become selects. Keep them in lockstep: the tier-1
    batched==unbatched golden pins serving output against
    :func:`generate`, which samples here."""
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def init_cache(model: Transformer, batch_size: int, dtype=None):
    """Allocate an empty KV cache (flax 'cache' collection).

    Built from eval_shape + zeros rather than ``model.init``: a real init
    call *runs* the decode step, which would advance the cache index past
    the dummy token. ``dtype`` overrides the floating leaves (pass the
    params dtype so a bf16 model keeps a bf16 cache — half the HBM).
    """
    tokens = jnp.zeros((batch_size, 1), jnp.int32)
    shapes = jax.eval_shape(
        lambda: model.init({"params": jax.random.PRNGKey(0)}, tokens, decode=True)
    )

    def zeros(s):
        use = (
            dtype
            if dtype is not None and jnp.issubdtype(s.dtype, jnp.floating)
            else s.dtype
        )
        return jnp.zeros(s.shape, use)

    return jax.tree.map(zeros, shapes["cache"])


def generate(
    model: Transformer,
    params,
    prompt: jax.Array,
    *,
    num_tokens: int,
    rng: jax.Array,
    temperature: float = 1.0,
    top_k: int = 0,
) -> jax.Array:
    """Sample ``num_tokens`` continuations of ``prompt`` [B, L] (greedy if
    temperature == 0). Prefill is one call; then a ``lax.scan`` of
    single-token steps over the static-shape cache. Returns [B, L+N]."""
    b, prompt_len = prompt.shape
    if prompt_len + num_tokens > model.cfg.max_len:
        # Past max_len the cache update index clamps and wpe runs out of
        # rows — silently corrupt output, so reject up front.
        raise ValueError(
            f"prompt ({prompt_len}) + num_tokens ({num_tokens}) exceeds "
            f"max_len ({model.cfg.max_len})"
        )
    # Cache dtype follows the token-embedding table — the deliberate
    # compute-dtype anchor (an arbitrary first leaf could be an f32
    # master bias in a mixed-precision tree and double the KV HBM).
    cache = init_cache(model, b, dtype=params["wte"]["embedding"].dtype)
    logits, vars_out = model.apply(
        {"params": params, "cache": cache}, prompt, decode=True,
        mutable=["cache"],
    )
    cache = vars_out["cache"]

    def sample(logits, rng):
        return sample_tokens(
            logits, rng, temperature=temperature, top_k=top_k
        )

    rng, sub = jax.random.split(rng)
    first = sample(logits[:, -1], sub)
    if num_tokens == 1:
        return jnp.concatenate([prompt, first[:, None]], axis=1)

    def step(carry, rng_t):
        cache, tok = carry
        logits, vars_out = model.apply(
            {"params": params, "cache": cache}, tok[:, None], decode=True,
            mutable=["cache"],
        )
        nxt = sample(logits[:, -1], rng_t)
        return (vars_out["cache"], nxt), tok

    (_, last), toks = jax.lax.scan(
        step, (cache, first), jax.random.split(rng, num_tokens - 1)
    )
    gen = jnp.concatenate([toks.transpose(1, 0), last[:, None]], axis=1)
    return jnp.concatenate([prompt, gen], axis=1)


# ------------------------------------------------------- pipeline pieces


class EmbedHead(nn.Module):
    """Embedding-in + tied-head-out halves of the LM, as one module.

    Used by the pipeline-parallel GPT-2 path (workloads/gpt2.py +
    parallel/pipeline.py): the block stack between ``encode`` and
    ``logits`` lives as a [layers]-stacked param tree sharded over the
    ``pipe`` mesh axis, while these (small) params stay replicated.
    Param names match ``Transformer`` (wte/wpe/ln_f).
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens):  # init-time: touch every param once
        return self.logits(self.encode(tokens))

    @nn.compact
    def encode(self, tokens, train: bool = False):
        cfg = self.cfg
        wte = nn.Embed(
            cfg.vocab_size, cfg.d_model,
            embedding_init=nn.initializers.normal(0.02), name="wte",
        )
        wpe = nn.Embed(
            cfg.max_len, cfg.d_model,
            embedding_init=nn.initializers.normal(0.01), name="wpe",
        )
        positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)
        x = wte(tokens) + wpe(positions)[None]
        # Same embedding dropout as Transformer.__call__ — the PP and
        # non-PP paths must train the same effective model.
        return nn.Dropout(cfg.dropout, deterministic=not train)(x)

    @nn.compact
    def logits(self, x):
        cfg = self.cfg
        wte = nn.Embed(
            cfg.vocab_size, cfg.d_model,
            embedding_init=nn.initializers.normal(0.02), name="wte",
        )
        x = nn.LayerNorm(epsilon=1e-5, dtype=x.dtype, name="ln_f")(x)
        return wte.attend(x)


def init_stacked_blocks(cfg: TransformerConfig, rng, *, train: bool = False):
    """[num_layers]-stacked Block params (for the pipeline path)."""
    block = Block(cfg, None, train, False)
    dummy = jnp.zeros((1, cfg.max_len, cfg.d_model), jnp.float32)
    keys = jax.random.split(rng, cfg.num_layers)
    return jax.vmap(lambda k: block.init({"params": k}, dummy)["params"])(keys)


def apply_stacked_blocks(
    cfg: TransformerConfig, params, x, *, train: bool = False, rng=None
):
    """Sequentially apply a [k]-stacked Block param tree to x.

    ``rng``: dropout key when ``train`` and ``cfg.dropout > 0`` — folded
    per layer so each block in the stack drops independently."""
    block = Block(cfg, None, train, False)
    k = jax.tree.leaves(params)[0].shape[0]
    use_rng = rng is not None and train and cfg.dropout > 0

    def one(carry, pi):
        p, i = pi
        rngs = {"dropout": jax.random.fold_in(rng, i)} if use_rng else None
        return block.apply({"params": p}, carry, rngs=rngs), None

    y, _ = jax.lax.scan(one, x, (params, jnp.arange(k)))
    return y


def stack_params_for_pipeline(params, num_layers: int):
    """Convert a standard ``Transformer`` param tree (wte/wpe/h_i/ln_f —
    e.g. from models/hf_import.import_gpt2) into the pipeline layout:
    ``{"embed": {wte, wpe, ln_f}, "blocks": [L]-stacked h_i}``.
    ``EmbedHead`` uses the same param names, so embed slots in as-is."""
    embed = {k: params[k] for k in ("wte", "wpe", "ln_f")}
    blocks = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[params[f"h_{i}"] for i in range(num_layers)],
    )
    return {"embed": embed, "blocks": blocks}
