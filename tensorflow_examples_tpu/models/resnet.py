"""ResNet family: ResNet-20 (CIFAR) and ResNet-50 (ImageNet).

Capability parity with the reference's CIFAR-10 ResNet-20 example
(BASELINE.json:configs[1]: "3 stages × n blocks" builder) and the
ResNet-50 ImageNet throughput workload (BASELINE.json:configs[2]).

TPU-native choices:
- NHWC layout end-to-end (XLA:TPU's preferred conv layout; channels land
  on the 128-wide lane dimension of the MXU).
- BatchNorm under ``jax.jit`` with a batch-sharded input IS sync-BN: the
  batch is one global logical array, so XLA computes cross-replica moments
  with an all-reduce it fuses into the normalization — no wrapper like
  tf.keras SyncBatchNormalization needed.
- Zero-init of each residual branch's last BN scale (the standard "zero
  gamma" trick) so deep nets start as identity maps.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any

_conv_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (ResNet-20/-18/-34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), (self.strides, self.strides), name="proj"
            )(residual)
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 → 3x3 → 1x1(×4) bottleneck block (ResNet-50/-101/-152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), (self.strides, self.strides), name="proj"
            )(residual)
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Generic staged ResNet.

    ``stem='cifar'``: single 3x3 conv (32x32 inputs).
    ``stem='imagenet'``: 7x7/2 conv + 3x3/2 maxpool (224x224 inputs).
    """

    stage_sizes: Sequence[int]
    block_cls: Callable[..., nn.Module]
    num_classes: int
    num_filters: int = 64
    stem: str = "imagenet"
    bn_momentum: float = 0.9

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = functools.partial(
            nn.Conv, use_bias=False, padding="SAME", kernel_init=_conv_init
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=self.bn_momentum,
            epsilon=1e-5,
        )

        if self.stem == "cifar":
            x = conv(self.num_filters, (3, 3), name="stem_conv")(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="stem_conv")(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2**stage,
                    conv=conv,
                    norm=norm,
                    strides=strides,
                    name=f"stage{stage}_block{block}",
                )(x)

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        # Classifier in f32: the tiny matmul costs nothing and keeps the
        # logits/loss numerics exact under bf16 compute.
        x = nn.Dense(
            self.num_classes,
            name="head",
            dtype=jnp.float32,
            kernel_init=nn.initializers.variance_scaling(1.0, "fan_in", "normal"),
        )(x.astype(jnp.float32))
        return x


def resnet20(num_classes: int = 10) -> ResNet:
    """CIFAR ResNet-20: 3 stages × 3 basic blocks, 16/32/64 filters."""
    return ResNet(
        stage_sizes=(3, 3, 3),
        block_cls=BasicBlock,
        num_classes=num_classes,
        num_filters=16,
        stem="cifar",
    )


def resnet50(num_classes: int = 1000) -> ResNet:
    """ImageNet ResNet-50: 3/4/6/3 bottleneck blocks, 64-filter stem."""
    return ResNet(
        stage_sizes=(3, 4, 6, 3),
        block_cls=BottleneckBlock,
        num_classes=num_classes,
        num_filters=64,
        stem="imagenet",
    )


def resnet18(num_classes: int = 1000) -> ResNet:
    return ResNet(
        stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock,
        num_classes=num_classes, num_filters=64, stem="imagenet",
    )


def resnet34(num_classes: int = 1000) -> ResNet:
    return ResNet(
        stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock,
        num_classes=num_classes, num_filters=64, stem="imagenet",
    )


def resnet101(num_classes: int = 1000) -> ResNet:
    return ResNet(
        stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock,
        num_classes=num_classes, num_filters=64, stem="imagenet",
    )


def resnet152(num_classes: int = 1000) -> ResNet:
    return ResNet(
        stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock,
        num_classes=num_classes, num_filters=64, stem="imagenet",
    )
