"""tensorflow_examples_tpu — a TPU-native training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
repo ``manigoswami/tensorflow-examples`` (see SURVEY.md; the reference's
capability spec is BASELINE.json): five end-to-end workloads — MNIST MLP,
CIFAR-10 ResNet-20, ImageNet ResNet-50, BERT-base GLUE, GPT-2 124M — on a
shared layered core:

- ``core``     — device mesh + sharding rules + precision policy + RNG
- ``ops``      — Pallas TPU kernels (fused cross-entropy, flash attention)
- ``parallel`` — collectives, ring attention, tensor parallelism
- ``data``     — grain/tf.data input pipelines with device prefetch
- ``train``    — the single shared training loop (jit step, ckpt, metrics)
- ``models``   — flax model definitions + HF weight importers
- ``utils``    — profiling, logging, failure handling

Where the reference used ``tf.distribute`` + NCCL all-reduce, this framework
uses ``jax.jit`` over a ``jax.sharding.Mesh`` and lets XLA emit collectives
over ICI/DCN. Where the reference used CUDA custom ops, this framework uses
Pallas (Mosaic) TPU kernels. Where the reference used the tf.data C++
runtime, this framework uses grain plus a native C++ prefetching loader
(``native/``).
"""

__version__ = "0.1.0"
