"""The Telemetry object the trainer owns (ISSUE 2 tentpole).

One instance per ``Trainer.fit`` drives everything observable about the
run: it snapshots the process-local registry (counters/gauges/
histograms) into a schema-versioned line per log window, derives the
accounting numbers (throughput, step-time percentiles, MFU, goodput),
fans the line out to the configured sinks, and exports the span
timeline as Chrome-trace JSON on close.

Abnormal-exit contract (satellite): the JSONL sink flushes per line, so
completed windows are always durable; ``final_window`` additionally
emits the partial in-flight window with an ``exit_reason`` on
preemption/abort, and ``emergency_flush`` is the watchdog-fatal hook —
called from the watchdog thread right before ``os._exit(87)`` — that
pushes sinks and the trace to disk while the main thread is wedged.

Cross-host: most counters are incremented by every process for the SAME
global event (the loop is SPMD — steps, checkpoint saves, bad steps are
replicated), so their local value already IS the global truth and
summing them would inflate by process_count. Only the counters in
``HOST_LOCAL_COUNTERS`` — events each host observes independently — are
summed over processes (a fixed name set, so the collective has
identical shape on every host); every host then computes the identical
line and process 0's JSONL is the run record.

Fleet layer (ISSUE 4): every line carries a ``host`` field (schema v3),
every cadenced window the attached ``FleetMonitor`` allgathers the
per-host health vector and the summary lands as a ``kind="fleet"`` line
right after the window line, and an attached ``MetricsServer`` exposes
/metrics, /health, and /window live (the hub keeps ``last_line`` for
it). The emergency path additionally snapshots the fleet state
(collective-free) and closes the server before exit 87.
"""

from __future__ import annotations

import logging
import time
from typing import Mapping

from tensorflow_examples_tpu.telemetry import accounting
from tensorflow_examples_tpu.telemetry import registry as registry_mod
from tensorflow_examples_tpu.telemetry import schema
from tensorflow_examples_tpu.telemetry import sinks as sinks_mod
from tensorflow_examples_tpu.telemetry import spans as spans_mod

log = logging.getLogger(__name__)

# Counters summed across hosts at each cadenced window: ONLY events each
# host observes independently (its own flaky reads, its own poisoned
# local batches). Everything else (train/steps_total, checkpoint/saves,
# resilience/*) is SPMD-replicated — each host's value already equals
# the global truth, so those pass through unreduced. Fixed set: the
# collective must have identical shape on every process.
HOST_LOCAL_COUNTERS = (
    "io/retries",
    "data/batches_skipped",
)


class Telemetry:
    def __init__(
        self,
        sinks: list,
        *,
        registry=None,
        tracer=None,
        flops_per_step: float = 0.0,
        peak_flops_total: float = 0.0,
        peak_is_estimate: bool = True,
        tokens_per_example: int = 1,
        trace_file: str | None = None,
        flush_every: int = 1,
        memory=None,
        fleet=None,
        host: int | None = None,
    ):
        self.sinks = sinks
        self.registry = (
            registry
            if registry is not None
            else registry_mod.default_registry()
        )
        self.tracer = (
            tracer if tracer is not None else spans_mod.default_tracer()
        )
        self.flops_per_step = float(flops_per_step)
        self.peak_flops_total = float(peak_flops_total)
        self.peak_is_estimate = bool(peak_is_estimate)
        self.tokens_per_example = max(int(tokens_per_example), 1)
        self.trace_file = trace_file
        self.flush_every = max(int(flush_every), 1)
        # Device-side observability (ISSUE 3): the per-fit memory
        # monitor (None = no memory fields on lines) and the profiler-
        # window cross-link carried on the final line.
        self.memory = memory
        # Fleet observability (ISSUE 4): the per-host skew monitor (None
        # = no fleet lines), the host index stamped on every line, the
        # latest emitted line (for the /window endpoint), and the
        # optional live-metrics server closed on the emergency path.
        self.fleet = fleet
        if host is None:
            try:
                import jax

                host = jax.process_index()
            except Exception:  # pragma: no cover - pre-init edge
                host = 0
        self.host = int(host)
        # last_line carries the latest NON-fleet line (the /window
        # endpoint's payload — a fleet line right after every window
        # would otherwise hide the metrics a watcher wants); the fleet
        # stream gets its own slot for /fleet.
        self.last_line: dict | None = None
        self.last_fleet_line: dict | None = None
        self.server = None  # MetricsServer, attached by the trainer
        self.profile_info: dict | None = None
        # Placement provenance (ISSUE 7, schema v5): set by the trainer
        # ({"mesh_shape", "param_sharding_digest", "zero1"}); rides the
        # kind="final" line so a run record names the layout it ran on.
        self.sharding_info: dict | None = None
        # Observed duty cycle is PER FIT (set by this fit's profiler
        # window, never read from the process-global gauge: a later fit
        # in the same process must not inherit an earlier fit's
        # measurement as its own).
        self.observed_duty_cycle: float | None = None
        self._emergency = False  # watchdog-fatal: cached-only sampling
        self._windows_since_flush = 0
        self._last_step = 0  # most recent log_window step (fatal marker)
        self._closed = False
        # Counters are process-global and a process may run several
        # fits; every line this Telemetry emits carries DELTAS from the
        # fit-start snapshot, so each fit is a self-contained session
        # and offline aggregation can simply sum sessions.
        self._counter_base = dict(self.registry.counter_values())
        self._session_start = time.time()  # session id in every line
        if self.flops_per_step > 0:
            self.registry.gauge("telemetry/flops_per_step").set(
                self.flops_per_step
            )
        if self.peak_flops_total > 0:
            self.registry.gauge("telemetry/peak_flops_total").set(
                self.peak_flops_total
            )
            self.registry.gauge("telemetry/peak_is_estimate").set(
                1.0 if self.peak_is_estimate else 0.0
            )

    @classmethod
    def from_config(cls, cfg, *, n_params: int = 0) -> "Telemetry":
        """Build from TrainConfig knobs (sink spec, trace toggle, flush
        cadence, peak override) + the workload's size numbers."""
        import jax

        sinks = sinks_mod.make_sinks(
            getattr(cfg, "telemetry_sinks", "console"), cfg.workdir
        )
        # Processed tokens per example: seq_len for token workloads
        # (GPT-2 feeds tokens[:, :-1] — seq_len positions; BERT pads to
        # seq_len), 1 for per-example workloads (images).
        tokens = int(getattr(cfg, "seq_len", 0) or 0) or 1
        flops = accounting.train_step_flops(
            n_params, cfg.global_batch_size, tokens
        )
        peak_tflops = float(getattr(cfg, "telemetry_peak_tflops", 0.0) or 0.0)
        if peak_tflops > 0:
            peak, known = peak_tflops * 1e12, True
        else:
            peak, known = accounting.peak_flops_per_device(
                getattr(jax.devices()[0], "device_kind", "")
            )
        trace_file = (
            sinks_mod.trace_path(cfg.workdir)
            if cfg.workdir
            and getattr(cfg, "telemetry_trace", True)
            and jax.process_index() == 0
            else None
        )
        from tensorflow_examples_tpu.telemetry import fleet as fleet_mod
        from tensorflow_examples_tpu.telemetry import memory as memory_mod

        return cls(
            sinks,
            flops_per_step=flops,
            peak_flops_total=peak * jax.device_count(),
            peak_is_estimate=not known,
            tokens_per_example=tokens,
            trace_file=trace_file,
            flush_every=getattr(cfg, "telemetry_flush_every", 1),
            memory=memory_mod.MemoryMonitor(),
            fleet=fleet_mod.FleetMonitor.from_config(cfg),
        )

    # ------------------------------------------------------------ intake

    def span(self, name: str, **args):
        return self.tracer.span(name, **args)

    def note_steps(self, n: int) -> None:
        """Count completed device steps (INCLUDING skipped bad steps and
        rollback replays — goodput's denominator is total stepped work)."""
        self.registry.counter("train/steps_total").inc(n)

    def record_step_time(self, seconds: float, k: int = 1) -> None:
        """One loop-iteration wall time; bundles amortize over k steps."""
        self.registry.histogram("step_time").record(seconds / max(k, 1))

    # ----------------------------------------------------------- windows

    def _fit_counters(self) -> dict[str, int]:
        """This fit's counters: deltas from the fit-start snapshot."""
        base = self._counter_base
        return {
            k: max(v - base.get(k, 0), 0)
            for k, v in self.registry.counter_values().items()
        }

    def _reduced_counters(self, values=None) -> dict[str, int]:
        values = (
            dict(values) if values is not None else self._fit_counters()
        )
        import jax

        if jax.process_count() == 1:
            return values
        import numpy as np
        from jax.experimental import multihost_utils

        vec = np.asarray(
            [values.get(n, 0) for n in HOST_LOCAL_COUNTERS], np.int64
        )
        summed = multihost_utils.process_allgather(vec).sum(axis=0)
        values.update(
            {n: int(v) for n, v in zip(HOST_LOCAL_COUNTERS, summed)}
        )
        return values

    def _derived(
        self, window_metrics: Mapping[str, float], counters: Mapping[str, int]
    ) -> dict:
        steps_per_sec = window_metrics.get("steps_per_sec")
        examples_per_sec = window_metrics.get("examples_per_sec")
        # One summary() pass: a single lock acquisition + sort of the
        # sample window, instead of one per percentile.
        step_summary = self.registry.histogram("step_time").summary()
        derived = {
            "examples_per_sec": examples_per_sec,
            "tokens_per_sec": (
                examples_per_sec * self.tokens_per_example
                if examples_per_sec is not None
                and self.tokens_per_example > 1
                else None
            ),
            "step_time_p50": step_summary["p50"],
            "step_time_p95": step_summary["p95"],
            "goodput": accounting.goodput(counters),
        }
        # Analytic 6ND MFU + the observed device duty cycle when THIS
        # fit's profiler window measured one (telemetry/profiling.py).
        derived.update(
            accounting.mfu_fields(
                self.flops_per_step,
                steps_per_sec,
                self.peak_flops_total,
                duty_cycle=self.observed_duty_cycle,
            )
        )
        return derived

    def log_window(
        self,
        step: int,
        metrics: Mapping[str, float],
        *,
        prefix: str = "train",
        kind: str = "window",
        exit_reason: str | None = None,
        reduce: bool = True,
        extra: Mapping | None = None,
    ) -> dict:
        """Emit one window line to every sink; returns the line.

        ``reduce=False`` skips the cross-host counter reduction — REQUIRED
        on abnormal-exit paths (preemption, abort), where peer processes
        may never reach the matching collective and the reduction would
        deadlock the dying process.

        ``extra`` merges additional schema-v2 objects into the line
        (the ``"compile"`` payload of a compile_warning, the
        ``"memory"`` breakdown of a memory snapshot line).
        """
        # Local fit-delta counters are captured BEFORE the cross-host
        # reduction: the fleet vector must carry each host's OWN
        # io/batch-skip numbers (the reduction replaces them with fleet
        # sums — identical on every host, useless for localization).
        local_counters = self._fit_counters()
        counters = (
            self._reduced_counters(local_counters)
            if reduce
            else local_counters
        )
        line = {
            "schema_version": schema.SCHEMA_VERSION,
            "kind": kind,
            "host": self.host,
            "step": int(step),
            "time_unix": time.time(),
            "session_start_unix": self._session_start,
            "metrics": {
                (f"{prefix}/{k}" if prefix else k): (
                    float(v) if v is not None else None
                )
                for k, v in metrics.items()
            },
            "counters": counters,
            "gauges": self.registry.gauge_values(),
            "derived": self._derived(metrics, counters),
        }
        if kind == "final":
            line["exit_reason"] = exit_reason or "complete"
            if self.profile_info is not None:
                line["profile"] = dict(self.profile_info)
            if self.sharding_info is not None:
                line["sharding"] = dict(self.sharding_info)
        # Memory watermark fields ride every cadenced/final line (the
        # kind="memory" init snapshot carries its own via ``extra``).
        # On the watchdog-fatal path only CACHED values are used: a
        # fresh live-array/PJRT poll from the watchdog thread could
        # block behind the wedged main thread.
        if self.memory is not None and kind in ("window", "final"):
            try:
                if not self._emergency:
                    self.memory.sample()
                line["memory"] = self.memory.window_fields()
            except Exception:  # pragma: no cover - accounting best effort
                log.exception("memory sampling failed (continuing)")
        if extra:
            line.update(extra)
        self._last_step = int(step)
        for sink in self.sinks:
            try:
                sink.write(line)
            except Exception:
                log.exception(
                    "telemetry sink %s failed to write (continuing)",
                    type(sink).__name__,
                )
        if kind == "fleet":
            self.last_fleet_line = line
        elif kind in ("window", "eval", "final"):
            # /window's contract: the latest SCALAR line. Memory and
            # compile_warning snapshots are JSONL-record material and
            # must not displace the window a watcher reads loss from.
            self.last_line = line
        # Fleet summary rides every cadenced window (ISSUE 4): the
        # gather is a collective, so it runs ONLY on the reduce=True
        # window path — the same place the counter reduction already
        # synchronizes every host. LOCAL counters: the vector's
        # io/skip entries are per-host evidence, not the fleet sums.
        if kind == "window" and reduce and self.fleet is not None:
            self._emit_fleet(step, local_counters)
        # Flush accounting AFTER the fleet emission, and never for the
        # fleet line itself: it rides every window, so counting it
        # would silently halve a configured telemetry_flush_every —
        # instead the window's own flush (below) covers both lines.
        if kind != "fleet":
            self._windows_since_flush += 1
            if self._windows_since_flush >= self.flush_every:
                self.flush()
        return line

    def _emit_fleet(self, step: int, counters: Mapping[str, int]) -> None:
        try:
            payload = self.fleet.gather(counters)
        except Exception:  # pragma: no cover - collective teardown races
            log.exception("fleet gather failed (continuing)")
            return
        self.log_window(
            step, {}, kind="fleet", reduce=False, extra={"fleet": payload}
        )

    def last_window_age(self) -> float | None:
        """Seconds since the last emitted line (the /health signal)."""
        if self.last_line is None:
            return None
        return max(time.time() - self.last_line["time_unix"], 0.0)

    def final_window(
        self,
        step: int,
        metrics: Mapping[str, float],
        *,
        prefix: str = "train",
        exit_reason: str,
    ) -> dict:
        """The partial in-flight window on an exit path (no collective:
        peers may already be gone)."""
        return self.log_window(
            step, metrics, prefix=prefix, kind="final",
            exit_reason=exit_reason, reduce=False,
        )

    # ------------------------------------- device-side lines (ISSUE 3)

    def note_memory_init(self, state, step: int = 0) -> dict | None:
        """The fit-start memory snapshot: params/opt/model-state/other
        breakdown as a ``kind="memory"`` line (telemetry/memory.py)."""
        if self.memory is None:
            return None
        try:
            breakdown = self.memory.init_breakdown(state)
        except Exception:  # pragma: no cover - accounting best effort
            log.exception("memory init snapshot failed (continuing)")
            return None
        return self.log_window(
            step, {}, kind="memory", reduce=False,
            extra={"memory": breakdown},
        )

    def compile_warning(self, event: Mapping) -> dict:
        """A post-warmup recompilation (telemetry/compilation.py):
        lands as a ``kind="compile_warning"`` line naming the shape/
        dtype delta. No collective — every SPMD process sees the same
        recompile, and a mid-step collective outside the program is a
        deadlock risk."""
        event = dict(event)
        step = int(event.pop("step", self._last_step))
        return self.log_window(
            step, {}, kind="compile_warning", reduce=False,
            extra={"compile": event},
        )

    def note_profile(self, info: Mapping) -> None:
        """Cross-link a completed profiler window from the final line."""
        self.profile_info = dict(info)

    # ------------------------------------------------------------- flush

    def flush(self) -> None:
        self._windows_since_flush = 0
        for sink in self.sinks:
            try:
                sink.flush()
            except Exception:  # pragma: no cover - sink teardown races
                log.exception("telemetry sink flush failed (continuing)")

    def write_trace(self) -> None:
        if self.trace_file:
            try:
                self.tracer.write_chrome_trace(self.trace_file)
            except Exception:  # pragma: no cover - disk-full etc.
                log.exception("chrome trace export failed (continuing)")

    def emergency_flush(self) -> None:
        """Watchdog-fatal path: called from the WATCHDOG thread right
        before ``os._exit(87)`` while the main thread is wedged. Lands a
        fleet snapshot (cached — NO collective: peers may be past their
        own matching point) and a final marker line (local counters
        only, no loop state: the partial window lives on the wedged
        thread), then closes the metrics server and pushes the trace
        and sinks to disk. Must never block on the main thread."""
        self._emergency = True  # memory fields come from cache only
        if self.fleet is not None:
            # The hung run's last known fleet state (ISSUE 4 satellite):
            # which host was straggling when everything stopped is
            # exactly the forensics the postmortem needs.
            try:
                self.log_window(
                    self._last_step, {}, kind="fleet", reduce=False,
                    extra={
                        "fleet": self.fleet.snapshot(self._fit_counters())
                    },
                )
            except Exception:  # pragma: no cover - dying anyway
                log.exception("watchdog-fatal fleet snapshot failed")
        try:
            self.final_window(
                self._last_step, {}, exit_reason="watchdog_fatal"
            )
        except Exception:  # pragma: no cover - dying anyway; best effort
            log.exception("watchdog-fatal final line failed")
        self.close_server()
        self.write_trace()
        self.flush()

    def close_server(self) -> None:
        """Shut the /metrics endpoint down (idempotent; all exit paths —
        a dead run must not keep answering scrapes as if live)."""
        server, self.server = self.server, None
        if server is not None:
            try:
                server.close()
            except Exception:  # pragma: no cover - socket teardown races
                log.exception("metrics server close failed (continuing)")

    def close(self) -> None:
        """Flush everything and write the trace; idempotent (the loop's
        ``finally`` calls this after any earlier abnormal-exit flush)."""
        if self._closed:
            return
        self._closed = True
        self.close_server()
        self.write_trace()
        for sink in self.sinks:
            try:
                sink.close()
            except Exception:  # pragma: no cover - sink teardown races
                log.exception("telemetry sink close failed (continuing)")
