"""Recompilation sentinel (ISSUE 3 tentpole (1)).

A silent recompilation is the classic "mysteriously slow run": a shape
or dtype that drifts mid-run (ragged final batch, a resumed run with a
different bundle size, a config knob that changes an aval) makes XLA
retrace + recompile the step — seconds to minutes of dead time that
shows up nowhere except a step-time spike. The repo's own history
(BASELINE.md round-4 sub-floor readings, diagnosed only by the
out-of-band ``tools/hlo_fingerprint.py``) is the motivating incident.

``CompilationSentinel`` wraps each jitted step function the trainer
builds (train step, bundled train step per K, eval step) and tracks the
**abstract input signature** — the ``(path, shape, dtype)`` tuple of
every array leaf — of each call:

* a call with a NEW signature is a compilation: its host wall time is
  bracketed by a ``compile`` span (Chrome trace + ``span/compile``
  histogram) and counted in ``compile/count``;
* after a configurable warmup (``TrainConfig.compile_warmup`` expected
  compilations per wrapped function — 1 covers the normal one-compile
  life of a step), any further compile is a **recompile**: counted in
  ``compile/recompiles``, logged at WARNING with the exact shape/dtype
  delta vs. the previous signature (down to the changed axis), and —
  when a ``Telemetry`` object is bound — emitted as a
  ``kind="compile_warning"`` schema-v2 JSONL line so the run record
  carries the evidence.

The wrapper forwards attribute access to the underlying jitted
callable, so AOT consumers (``trainer._train_step.lower(...)`` in
bench.py and the diagnostics tools) are unaffected.

Signature tracking is host-side bookkeeping only (one pytree flatten of
the already-on-host arg structure per *launch*, amortized by
``steps_per_launch``); it cannot see cache evictions or persistent-
cache hits, but every aval-driven retrace — the failure mode that
matters — is exactly a new signature.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable

from tensorflow_examples_tpu.telemetry import registry as registry_mod
from tensorflow_examples_tpu.telemetry import spans as spans_mod

log = logging.getLogger(__name__)

# Cap the delta text: a giant param tree diff must not balloon the JSONL
# line (the first few entries name the culprit; the rest repeat it).
_MAX_DELTA_CHARS = 600
_MAX_DELTA_LEAVES = 8


def fast_signature(args: tuple, kwargs: dict) -> tuple:
    """The cheap per-launch aval fingerprint: (treedef, ((shape, dtype),
    ...)). No per-leaf string formatting — this runs on EVERY launch,
    including inside bench.py's timed loops, so it must stay a plain
    flatten plus tuple build. PyTreeDefs are hashable, and a differing
    tuple is exactly the condition under which jit retraces (modulo
    weak types, which step inputs don't carry)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (
        treedef,
        tuple(
            (getattr(leaf, "shape", ()), getattr(leaf, "dtype", None))
            for leaf in leaves
        ),
    )


def abstract_signature(args: tuple, kwargs: dict) -> tuple:
    """The path-annotated aval signature: (path, shape, dtype) per leaf.
    Costs a keystr per leaf, so it is computed only when a NEW
    ``fast_signature`` appears and a human-readable delta is needed."""
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_flatten_with_path((args, kwargs))[0]
    out = []
    for path, leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is None:
            shape = np.shape(leaf)
        dtype = getattr(leaf, "dtype", None)
        out.append(
            (
                jax.tree_util.keystr(path),
                tuple(int(d) for d in shape),
                str(dtype) if dtype is not None else type(leaf).__name__,
            )
        )
    return tuple(out)


def describe_delta(old: tuple | None, new: tuple) -> str:
    """Human-readable shape/dtype diff between two signatures, naming
    the changed axis — the line an operator reads to find the ragged
    batch."""
    if old is None:
        return "first compilation"
    old_map = {p: (s, d) for p, s, d in old}
    new_map = {p: (s, d) for p, s, d in new}
    parts: list[str] = []
    for path, (shape, dtype) in new_map.items():
        prev = old_map.get(path)
        if prev is None:
            parts.append(f"{path}: new input {shape} {dtype}")
            continue
        pshape, pdtype = prev
        if shape != pshape:
            if len(shape) == len(pshape):
                axes = ", ".join(
                    f"axis {i}: {pshape[i]}->{shape[i]}"
                    for i in range(len(shape))
                    if shape[i] != pshape[i]
                )
            else:
                axes = f"rank {len(pshape)}->{len(shape)}"
            parts.append(f"{path}: shape {pshape}->{shape} ({axes})")
        if dtype != pdtype:
            parts.append(f"{path}: dtype {pdtype}->{dtype}")
    for path in old_map.keys() - new_map.keys():
        parts.append(f"{path}: input removed")
    if not parts:
        # Same avals but a new tuple can only mean structure-level drift
        # (ordering); name it rather than emitting an empty delta.
        return "input tree structure changed (identical leaf avals)"
    shown = parts[:_MAX_DELTA_LEAVES]
    if len(parts) > len(shown):
        shown.append(f"... and {len(parts) - len(shown)} more leaves")
    return "; ".join(shown)[:_MAX_DELTA_CHARS]


class _FnRecord:
    __slots__ = ("name", "seen", "last_sig", "compiles")

    def __init__(self, name: str):
        self.name = name
        self.seen: set = set()
        self.last_sig: tuple | None = None
        self.compiles = 0


class SentinelWrapped:
    """A jitted callable under sentinel observation. Transparent:
    ``__getattr__`` forwards ``lower`` / ``trace`` / anything else to
    the wrapped function."""

    def __init__(self, sentinel: "CompilationSentinel", fn: Callable,
                 name: str):
        self._sentinel = sentinel
        self._fn = fn
        self._name = name

    def __call__(self, *args, **kwargs):
        return self._sentinel._observed_call(
            self._fn, self._name, args, kwargs
        )

    def __getattr__(self, item):
        return getattr(self._fn, item)

    def __repr__(self):  # pragma: no cover - debugging nicety
        return f"SentinelWrapped({self._name}, {self._fn!r})"


class CompilationSentinel:
    """Per-Trainer compile observer; ``bind`` a per-fit Telemetry to
    turn post-warmup recompiles into JSONL warning lines."""

    def __init__(self, *, warmup: int = 1, registry=None, tracer=None):
        self.warmup = max(int(warmup), 0)
        self._registry = registry
        self._tracer = tracer
        self._fns: dict[str, _FnRecord] = {}
        self.events: list[dict] = []  # every compile event, introspectable
        self.step: int = 0  # maintained by the loop: labels warning lines
        self.on_recompile: Callable[[dict], None] | None = None

    @classmethod
    def from_config(cls, cfg) -> "CompilationSentinel":
        return cls(warmup=int(getattr(cfg, "compile_warmup", 1) or 0))

    # ------------------------------------------------------------ wiring

    def wrap(self, fn: Callable | None, name: str):
        """Wrap a jitted callable; None passes through (eval-less tasks)."""
        if fn is None:
            return None
        self._fns.setdefault(name, _FnRecord(name))
        return SentinelWrapped(self, fn, name)

    def bind(self, telemetry) -> None:
        """Route post-warmup recompile events into a fit's Telemetry
        (which emits the ``compile_warning`` JSONL line)."""
        self.on_recompile = telemetry.compile_warning

    def unbind(self) -> None:
        self.on_recompile = None

    # ----------------------------------------------------------- observe

    def _reg(self):
        return (
            self._registry
            if self._registry is not None
            else registry_mod.default_registry()
        )

    def _span(self, name: str, **args):
        tracer = (
            self._tracer
            if self._tracer is not None
            else spans_mod.default_tracer()
        )
        return tracer.span(name, **args)

    def _observed_call(self, fn, name, args, kwargs):
        rec = self._fns.setdefault(name, _FnRecord(name))
        sig = fast_signature(args, kwargs)
        if sig in rec.seen:
            return fn(*args, **kwargs)
        # New signature: this call pays trace + compile. Host wall time
        # around the (synchronous-until-compiled) dispatch is the
        # compile cost an operator experiences. The path-annotated
        # signature (keystr per leaf) is only computed here, off the
        # per-launch hot path.
        path_sig = abstract_signature(args, kwargs)
        t0 = time.perf_counter()
        with self._span("compile", fn=name):
            out = fn(*args, **kwargs)
        wall = time.perf_counter() - t0
        delta = describe_delta(rec.last_sig, path_sig)
        rec.seen.add(sig)
        rec.last_sig = path_sig
        rec.compiles += 1
        reg = self._reg()
        reg.counter("compile/count").inc()
        reg.gauge("compile/last_wall_secs").set(wall)
        event = {
            "fn": name,
            "count": rec.compiles,
            "wall_secs": round(wall, 6),
            "delta": delta,
        }
        self.events.append(event)
        if rec.compiles > self.warmup:
            reg.counter("compile/recompiles").inc()
            log.warning(
                "RECOMPILATION of %s at step %d (compile #%d for this fn, "
                "%.2fs): %s",
                name, self.step, rec.compiles, wall, delta,
            )
            if self.on_recompile is not None:
                try:
                    self.on_recompile(dict(event, step=self.step))
                except Exception:  # pragma: no cover - telemetry best effort
                    log.exception("recompile warning emission failed")
        else:
            log.info(
                "compiled %s (#%d, %.2fs): %s", name, rec.compiles, wall,
                delta,
            )
        return out

    # ----------------------------------------------------------- inspect

    def compile_counts(self) -> dict[str, int]:
        return {name: r.compiles for name, r in self._fns.items()}

    def post_warmup_recompiles(self) -> int:
        """Total compiles beyond each wrapped fn's warmup allowance —
        the number CI asserts to be 0 in steady state (the serving
        engine's zero-recompile contract, and the sharded-training
        smoke in tests/test_sharding.py)."""
        return sum(
            max(0, r.compiles - self.warmup) for r in self._fns.values()
        )
