"""Derived accounting: throughput, model-FLOPs MFU, and goodput.

The numbers the paper's tuning loop actually optimizes (ISSUE 2
tentpole (d); arXiv:1909.09756 reports exactly these for the TPU-v3 pod
runs):

* **examples/sec, tokens/sec** — window throughput, computed by the
  loop from wall time and ``global_batch_size``.
* **MFU** — model FLOPs utilization: achieved model FLOPs/sec over the
  accelerator's peak. Model FLOPs use the standard ``6 * N * D``
  estimate (2ND forward + 4ND backward for N params over D processed
  examples·tokens — the PaLM appendix-B convention), NOT the XLA cost
  analysis: MFU is meant to be comparable across implementations, so
  rematerialization or a fused kernel must not change the numerator.
* **goodput** — productive steps over total stepped work: steps whose
  update survived into the final params, vs. work burned by bad-step
  skips and rollback replays (fed by the PR 1 guard counters).

Peak FLOPs come from a device-kind table (bf16 peak per chip); unknown
kinds (CPU test runs, new TPU generations) fall back to a deliberately
round 1 TFLOP/s so the MFU *pipeline* stays exercised end-to-end — the
reported value is then explicitly labeled by ``peak_is_estimate``.
"""

from __future__ import annotations

from typing import Mapping

# bf16 peak FLOPs/sec per chip by PJRT device_kind substring (first
# match wins — order matters for "v5"/"v5 lite").
PEAK_FLOPS_BY_DEVICE_KIND: tuple[tuple[str, float], ...] = (
    ("v6e", 918e12),
    ("v5 lite", 197e12),  # v5e reports "TPU v5 lite"
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4 lite", 138e12),  # v4i
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

# Unknown device kind (CPU CI, future chips): keep the MFU pipeline
# alive with an explicit, obviously-synthetic 1 TFLOP/s peak.
DEFAULT_PEAK_FLOPS = 1e12


def peak_flops_per_device(device_kind: str = "") -> tuple[float, bool]:
    """(peak bf16 FLOPs/sec for one device, known?) for a PJRT kind."""
    kind = (device_kind or "").lower()
    for sub, peak in PEAK_FLOPS_BY_DEVICE_KIND:
        if sub in kind:
            return peak, True
    return DEFAULT_PEAK_FLOPS, False


def train_step_flops(
    n_params: int, examples_per_step: int, tokens_per_example: int = 1
) -> float:
    """Model FLOPs for ONE optimizer step: 6 * N * (examples * tokens).

    ``tokens_per_example`` is 1 for per-example workloads (image
    classification) and the sequence length for token workloads (LM,
    BERT) — the D in 6ND is *processed tokens*.
    """
    return 6.0 * float(n_params) * float(examples_per_step) * float(
        max(tokens_per_example, 1)
    )


def mfu(
    flops_per_step: float, steps_per_sec: float, peak_flops_total: float
) -> float | None:
    """Achieved model FLOPs/sec over total peak; None if peak unknown."""
    if peak_flops_total <= 0 or flops_per_step <= 0 or steps_per_sec <= 0:
        return None
    return flops_per_step * steps_per_sec / peak_flops_total


def mfu_fields(
    flops_per_step: float,
    steps_per_sec: float | None,
    peak_flops_total: float,
    duty_cycle: float | None = None,
) -> dict:
    """The MFU block for a derived section: the analytic 6ND figure
    plus — when an in-loop profiler window measured one
    (telemetry/profiling.py) — the observed device duty cycle alongside
    it (VERDICT r4 weak #5: never report the analytic number as if it
    were a measurement). The two are deliberately separate keys: duty
    cycle is "fraction of wall time the device was busy", an upper
    bound on where MFU can go, not an MFU itself."""
    out: dict[str, float | None] = {
        "mfu": (
            mfu(flops_per_step, steps_per_sec, peak_flops_total)
            if steps_per_sec is not None
            else None
        )
    }
    if duty_cycle is not None:
        out["device_duty_cycle"] = float(duty_cycle)
    return out


def goodput(counters: Mapping[str, int]) -> float | None:
    """Productive fraction of stepped work.

    ``train/steps_total`` counts every device step the loop ran —
    including skipped bad steps, executions a rollback later discarded,
    and their replays; ``resilience/bad_steps`` is work whose update
    was dropped on device; ``resilience/steps_lost`` is the
    rollback-discarded work NET of those bad steps (the two loss terms
    are disjoint by construction, see BadStepGuard.note_rollback).
    Productive = total - bad - lost.
    """
    total = counters.get("train/steps_total", 0)
    if total <= 0:
        return None
    lost = counters.get("resilience/bad_steps", 0) + counters.get(
        "resilience/steps_lost", 0
    )
    return max(total - lost, 0) / total
