"""Live run endpoints: /metrics, /health, /window (ISSUE 4 tentpole).

Until now a live run could only be observed by tailing its JSONL — fine
for one process on one box, useless for a pod behind a scheduler. This
module adds an opt-in (``TrainConfig.metrics_port > 0``) stdlib
``http.server`` thread per process serving:

* ``/metrics`` — the full registry (counters, gauges, time-histograms)
  rendered as Prometheus text exposition format: counters as
  ``counter``, gauges as ``gauge``, histograms as ``summary`` (p50/p95/
  p99 quantiles + ``_sum``/``_count``). Metric names are sanitized
  (``train/steps_total`` -> ``train_steps_total``) and every sample
  carries a ``host`` label, so one Prometheus scrape config covers the
  whole fleet.
* ``/health`` — JSON: watchdog phase + stall age (when a watchdog is
  attached), the age of the last telemetry window, host index. Status
  200 while the loop is making progress; 503 once the watchdog reports
  a stall older than its timeout (a scrape-friendly liveness signal).
* ``/window`` — the latest window/eval/final line verbatim (the same
  schema-v3 object the sinks got), 404 before the first window.
* ``/fleet`` — the latest ``kind="fleet"`` line (per-host skew +
  straggler verdict), 404 before the first fleet summary.

Design constraints:

* **Stdlib only** (the image is pip-install-free): ``ThreadingHTTPServer``
  with daemon threads, so a wedged scraper can never wedge the trainer.
* **Read-only and lock-light**: handlers read registry snapshots and the
  hub's ``last_line`` reference; they never enter a collective and never
  touch device state.
* **Closed on every exit path**: ``Trainer.fit``'s finally closes it
  (complete/preempt/error), and the watchdog-fatal hook
  (``Telemetry.emergency_flush``) closes it right before ``os._exit(87)``
  so the port is released even on a hard kill.
"""

from __future__ import annotations

import http.server
import json
import logging
import math
import re
import threading
import time
from typing import Mapping

from tensorflow_examples_tpu.telemetry import registry as registry_mod

log = logging.getLogger(__name__)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

# A histogram rendered as a Prometheus summary exposes these quantiles.
_QUANTILES = ((50, "0.5"), (95, "0.95"), (99, "0.99"))


def json_safe(obj):
    """Non-finite floats -> null, recursively. ``json.dumps`` would
    happily emit literal ``NaN`` tokens (not RFC-8259 JSON) and break
    strict consumers (jq, fetch().json(), Grafana) the first time a
    diverged run puts a NaN loss on the window line."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


def sanitize_metric_name(name: str) -> str:
    """Registry name -> Prometheus metric name (``a/b-c`` -> ``a_b_c``;
    a leading digit gets an underscore prefix)."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out or "_"


def _fmt_value(v: float) -> str:
    """Prometheus sample value: floats repr-style, NaN/Inf spelled out."""
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


# Latency histograms that additionally render per-quantile GAUGE
# samples (ISSUE 18 satellite): a summary's quantile label is easy to
# misuse in alert expressions, so the per-SLO-class serving latencies
# also surface as plain ``<name>_seconds_p99``-style gauges an operator
# can threshold directly. Matched by prefix so new SLO classes appear
# without touching this module.
_CLASS_GAUGE_PREFIXES = ("serving/ttft_", "serving/e2e_")


def render_prometheus(registry, *, host: int = 0, exemplars=None) -> str:
    """The registry as Prometheus text exposition format (version 0.0.4:
    ``# TYPE`` comments + ``name{labels} value`` samples).

    ``exemplars`` (ISSUE 18): an ``ExemplarStore`` whose worst recent
    observation per histogram renders as a ``<name>_seconds_worst``
    gauge carrying a ``trace_id`` label — the scrape-time bridge from
    "p99 spiked" to the exact trace to pull from ``/trace/{id}``."""
    label = f'{{host="{int(host)}"}}'
    lines: list[str] = []
    for name, value in sorted(registry.counter_values().items()):
        n = sanitize_metric_name(name)
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n}{label} {_fmt_value(value)}")
    for name, value in sorted(registry.gauge_values().items()):
        n = sanitize_metric_name(name)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n}{label} {_fmt_value(value)}")
    for name, summary in sorted(registry.histogram_summaries().items()):
        if not summary["count"]:
            continue
        n = sanitize_metric_name(name) + "_seconds"
        lines.append(f"# TYPE {n} summary")
        for q, q_label in _QUANTILES:
            v = summary[f"p{q}"]
            if v is not None:
                lines.append(
                    f'{n}{{host="{int(host)}",quantile="{q_label}"}} '
                    f"{_fmt_value(v)}"
                )
        lines.append(f"{n}_sum{label} {_fmt_value(summary['total'])}")
        lines.append(f"{n}_count{label} {_fmt_value(summary['count'])}")
        if name.startswith(_CLASS_GAUGE_PREFIXES):
            for q, _ in _QUANTILES:
                v = summary[f"p{q}"]
                if v is not None:
                    lines.append(f"# TYPE {n}_p{q} gauge")
                    lines.append(
                        f"{n}_p{q}{label} {_fmt_value(v)}"
                    )
    if exemplars is not None:
        for name, (value, trace_id) in sorted(exemplars.worst().items()):
            n = sanitize_metric_name(name) + "_seconds_worst"
            lines.append(f"# TYPE {n} gauge")
            lines.append(
                f'{n}{{host="{int(host)}",trace_id="{trace_id}"}} '
                f"{_fmt_value(value)}"
            )
    return "\n".join(lines) + "\n"


class MetricsServer:
    """The per-process observability endpoint. ``start()`` binds and
    serves on a daemon thread; ``close()`` is idempotent and safe from
    any thread (including the watchdog's fatal path)."""

    def __init__(
        self,
        registry=None,
        *,
        port: int = 0,
        bind_host: str = "",
        telemetry=None,
        watchdog=None,
        process_index: int | None = None,
    ):
        self.registry = (
            registry
            if registry is not None
            else registry_mod.default_registry()
        )
        self.requested_port = int(port)
        self.bind_host = bind_host
        self.telemetry = telemetry
        self.watchdog = watchdog
        self._process_index = process_index
        self.port: int | None = None  # actual bound port after start()
        self._httpd: http.server.ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    @classmethod
    def from_config(cls, cfg, *, telemetry=None, watchdog=None):
        """None when ``metrics_port`` is unset — the caller wires the
        server only when the config opts in."""
        port = int(getattr(cfg, "metrics_port", 0) or 0)
        if port <= 0:
            return None
        return cls(
            telemetry.registry if telemetry is not None else None,
            port=port,
            telemetry=telemetry,
            watchdog=watchdog,
        )

    # ------------------------------------------------------------ payloads

    def _host_index(self) -> int:
        if self._process_index is not None:
            return self._process_index
        if self.telemetry is not None and hasattr(self.telemetry, "host"):
            return int(self.telemetry.host)
        return 0

    def metrics_payload(self) -> str:
        return render_prometheus(self.registry, host=self._host_index())

    def health_payload(self) -> tuple[int, dict]:
        """(http status, body). 503 = the watchdog sees a stall past its
        timeout; 200 otherwise (including watchdog-less runs, where the
        endpoint can only attest the process is serving)."""
        body: dict = {"host": self._host_index(), "ok": True}
        tel = self.telemetry
        if tel is not None:
            age = tel.last_window_age()
            body["last_window_age_secs"] = age
            last = getattr(tel, "last_line", None)
            if last is not None:
                body["last_step"] = last.get("step")
                body["last_kind"] = last.get("kind")
        wd = self.watchdog
        if wd is not None:
            status = wd.status()
            body.update(
                phase=status["phase"],
                phase_age_secs=status["phase_age_secs"],
                stalled_secs=status["stalled_secs"],
                watchdog_paused=status["paused"],
            )
            if (
                not status["paused"]
                and status["timeout_secs"] > 0
                and status["stalled_secs"] >= status["timeout_secs"]
            ):
                body["ok"] = False
        return (200 if body["ok"] else 503), body

    def window_payload(self) -> Mapping | None:
        return getattr(self.telemetry, "last_line", None)

    def fleet_payload(self) -> Mapping | None:
        return getattr(self.telemetry, "last_fleet_line", None)

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "MetricsServer":
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, status, content_type, body: bytes):
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - http.server contract
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        self._send(
                            200,
                            "text/plain; version=0.0.4; charset=utf-8",
                            server.metrics_payload().encode(),
                        )
                    elif path == "/health":
                        status, body = server.health_payload()
                        self._send(
                            status,
                            "application/json",
                            (json.dumps(json_safe(body)) + "\n").encode(),
                        )
                    elif path in ("/window", "/fleet"):
                        line = (
                            server.window_payload()
                            if path == "/window"
                            else server.fleet_payload()
                        )
                        if line is None:
                            self._send(
                                404,
                                "application/json",
                                b'{"error": "nothing emitted yet"}\n',
                            )
                        else:
                            self._send(
                                200,
                                "application/json",
                                (json.dumps(json_safe(line)) + "\n")
                                .encode(),
                            )
                    else:
                        self._send(
                            404,
                            "text/plain; charset=utf-8",
                            b"endpoints: /metrics /health /window /fleet\n",
                        )
                except ConnectionError:  # scraper went away mid-write
                    pass  # (broken pipe or reset — not worth a traceback)

            def log_message(self, fmt, *args):  # quiet: scrapes per window
                log.debug("metrics server: " + fmt, *args)

        self._httpd = http.server.ThreadingHTTPServer(
            (self.bind_host, self.requested_port), Handler
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="telemetry-metrics-server",
            daemon=True,
        )
        self._thread.start()
        log.info(
            "telemetry endpoints live on port %d "
            "(/metrics /health /window /fleet)",
            self.port,
        )
        return self

    def url(self, path: str = "/metrics") -> str:
        host = self.bind_host or "127.0.0.1"
        return f"http://{host}:{self.port}{path}"

    def close(self) -> None:
        """Idempotent; callable from the watchdog thread on the fatal
        path (shutdown() only flags the serve loop — it cannot block on
        the wedged main thread)."""
        with self._lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5)
