"""Self-describing JSONL metrics schema (ISSUE 2 CI satellite).

Every line the JSONL sink emits carries ``schema_version`` so offline
consumers (tools/telemetry_report.py, future BENCH_* harvesters) can
evolve without guessing. ``validate_line`` is the single source of truth
for what a line must look like — the tier-1 test validates every emitted
line through it, and the report CLI refuses lines it cannot validate
rather than mis-aggregating them.

Hand-rolled (no jsonschema dependency — the image is pip-install-free);
the structure is small enough that explicit checks read better anyway.

Line shape (version 1)::

    {
      "schema_version": 1,
      "kind": "window" | "eval" | "final",
      "step": <int >= 0>,            # loop step the line was emitted at
      "time_unix": <float>,          # wall clock at emission
      "session_start_unix": <float>, # constant per fit-session: the
                                     #   boundary marker for resumed runs
      "metrics": {"train/loss": 1.2, ...},      # window means
      "counters": {"data/batches_fetched": 10, ...},  # cumulative
                                     #   WITHIN the session (fit deltas)
      "gauges": {...},                          # instantaneous values
      "derived": {"examples_per_sec": ..., "step_time_p50": ...,
                  "mfu": ..., "goodput": ...},  # may hold nulls
      "exit_reason": "preempt" | ...  # kind == "final" only
    }
"""

from __future__ import annotations

import numbers
from typing import Any

SCHEMA_VERSION = 1

KINDS = ("window", "eval", "final")

_REQUIRED = ("schema_version", "kind", "step", "time_unix",
             "session_start_unix", "metrics", "counters", "gauges",
             "derived")


def _is_number(v: Any) -> bool:
    return isinstance(v, numbers.Real) and not isinstance(v, bool)


def validate_line(obj: Any) -> list[str]:
    """Return the list of schema violations (empty = valid)."""
    if not isinstance(obj, dict):
        return [f"line is {type(obj).__name__}, not an object"]
    problems = []
    for key in _REQUIRED:
        if key not in obj:
            problems.append(f"missing required field {key!r}")
    if problems:
        return problems
    if obj["schema_version"] != SCHEMA_VERSION:
        problems.append(
            f"schema_version {obj['schema_version']!r} != {SCHEMA_VERSION}"
        )
    if obj["kind"] not in KINDS:
        problems.append(f"kind {obj['kind']!r} not in {KINDS}")
    if not isinstance(obj["step"], int) or isinstance(obj["step"], bool) \
            or obj["step"] < 0:
        problems.append(f"step {obj['step']!r} is not a non-negative int")
    for key in ("time_unix", "session_start_unix"):
        if not _is_number(obj[key]):
            problems.append(f"{key} {obj[key]!r} is not a number")
    for section in ("metrics", "gauges"):
        sec = obj[section]
        if not isinstance(sec, dict):
            problems.append(f"{section} is not an object")
            continue
        for k, v in sec.items():
            if not isinstance(k, str):
                problems.append(f"{section} key {k!r} is not a string")
            # NaN/Inf pass through json.dumps as bare tokens; numeric or
            # null is the contract (a NaN loss window is still a number).
            if v is not None and not _is_number(v):
                problems.append(f"{section}[{k!r}] = {v!r} is not numeric")
    counters = obj["counters"]
    if not isinstance(counters, dict):
        problems.append("counters is not an object")
    else:
        for k, v in counters.items():
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(
                    f"counters[{k!r}] = {v!r} is not a non-negative int"
                )
    derived = obj["derived"]
    if not isinstance(derived, dict):
        problems.append("derived is not an object")
    else:
        for k, v in derived.items():
            if v is not None and not _is_number(v):
                problems.append(f"derived[{k!r}] = {v!r} is not numeric")
    if obj["kind"] == "final" and not isinstance(
        obj.get("exit_reason"), str
    ):
        problems.append("final line is missing a string exit_reason")
    if obj["kind"] != "final" and "exit_reason" in obj:
        problems.append("exit_reason on a non-final line")
    return problems


def validate(obj: Any) -> None:
    """Raise ValueError listing every violation (empty = returns None)."""
    problems = validate_line(obj)
    if problems:
        raise ValueError(
            "telemetry line violates schema v%d:\n  %s"
            % (SCHEMA_VERSION, "\n  ".join(problems))
        )
