"""Self-describing JSONL metrics schema (ISSUE 2 CI satellite; v2 in
ISSUE 3).

Every line the JSONL sink emits carries ``schema_version`` so offline
consumers (tools/telemetry_report.py, tools/bench_gate.py, future
BENCH_* harvesters) can evolve without guessing. ``validate_line`` is
the single source of truth for what a line must look like — the tier-1
test validates every emitted line through it, and the report CLI
refuses lines it cannot validate rather than mis-aggregating them.

Hand-rolled (no jsonschema dependency — the image is pip-install-free);
the structure is small enough that explicit checks read better anyway.

Line shape (version 2; version-1 lines remain valid input)::

    {
      "schema_version": 2,
      "kind": "window" | "eval" | "final" | "memory" | "compile_warning",
      "step": <int >= 0>,            # loop step the line was emitted at
      "time_unix": <float>,          # wall clock at emission
      "session_start_unix": <float>, # constant per fit-session: the
                                     #   boundary marker for resumed runs
      "metrics": {"train/loss": 1.2, ...},      # window means
      "counters": {"data/batches_fetched": 10, ...},  # cumulative
                                     #   WITHIN the session (fit deltas)
      "gauges": {...},                          # instantaneous values
      "derived": {"examples_per_sec": ..., "step_time_p50": ...,
                  "mfu": ..., "goodput": ...},  # may hold nulls
      "exit_reason": "preempt" | ...  # kind == "final" only

      # --- version 2 additions (telemetry/memory.py, compilation.py,
      #     profiling.py) ---
      "memory": {"live_bytes": ..., "peak_live_bytes": ...,
                 "params_bytes": ..., ...},  # numeric|null; REQUIRED on
                                     #   kind == "memory" (the init
                                     #   breakdown snapshot), optional
                                     #   on window/final lines
      "compile": {"fn": "train_step", "delta": "...axis 0: 64->32...",
                  "count": 2, "wall_secs": 0.4},  # REQUIRED on (and
                                     #   exclusive to) compile_warning
      "profile": {"dir": "...", "start_step": 10, "num_steps": 10,
                  "wall_secs": 1.2}  # final lines only: cross-link to
                                     #   the in-loop profiler window
    }

Version-1 lines (the pre-ISSUE-3 stream) carry none of the v2 fields
and only the v1 kinds; they still validate, so old run dirs keep
reporting.
"""

from __future__ import annotations

import numbers
from typing import Any

SCHEMA_VERSION = 2

SUPPORTED_VERSIONS = (1, 2)

KINDS_V1 = ("window", "eval", "final")
KINDS = KINDS_V1 + ("memory", "compile_warning")

_REQUIRED = ("schema_version", "kind", "step", "time_unix",
             "session_start_unix", "metrics", "counters", "gauges",
             "derived")

# v2-only top-level objects: forbidden on v1 lines (a "v1" line carrying
# them is a mislabeled v2 line — flag it instead of half-validating).
_V2_FIELDS = ("memory", "compile", "profile")


def _is_number(v: Any) -> bool:
    return isinstance(v, numbers.Real) and not isinstance(v, bool)


def _check_numeric_map(obj: dict, section: str, problems: list[str]) -> None:
    sec = obj.get(section)
    if not isinstance(sec, dict):
        problems.append(f"{section} is not an object")
        return
    for k, v in sec.items():
        if not isinstance(k, str):
            problems.append(f"{section} key {k!r} is not a string")
        # NaN/Inf pass through json.dumps as bare tokens; numeric or
        # null is the contract (a NaN loss window is still a number).
        if v is not None and not _is_number(v):
            problems.append(f"{section}[{k!r}] = {v!r} is not numeric")


def validate_line(obj: Any) -> list[str]:
    """Return the list of schema violations (empty = valid)."""
    if not isinstance(obj, dict):
        return [f"line is {type(obj).__name__}, not an object"]
    problems: list[str] = []
    for key in _REQUIRED:
        if key not in obj:
            problems.append(f"missing required field {key!r}")
    if problems:
        return problems
    version = obj["schema_version"]
    if version not in SUPPORTED_VERSIONS:
        problems.append(
            f"schema_version {version!r} not in {SUPPORTED_VERSIONS}"
        )
        return problems
    kinds = KINDS_V1 if version == 1 else KINDS
    if obj["kind"] not in kinds:
        problems.append(f"kind {obj['kind']!r} not in {kinds}")
    if not isinstance(obj["step"], int) or isinstance(obj["step"], bool) \
            or obj["step"] < 0:
        problems.append(f"step {obj['step']!r} is not a non-negative int")
    for key in ("time_unix", "session_start_unix"):
        if not _is_number(obj[key]):
            problems.append(f"{key} {obj[key]!r} is not a number")
    for section in ("metrics", "gauges"):
        _check_numeric_map(obj, section, problems)
    counters = obj["counters"]
    if not isinstance(counters, dict):
        problems.append("counters is not an object")
    else:
        for k, v in counters.items():
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(
                    f"counters[{k!r}] = {v!r} is not a non-negative int"
                )
    derived = obj["derived"]
    if not isinstance(derived, dict):
        problems.append("derived is not an object")
    else:
        for k, v in derived.items():
            if v is not None and not _is_number(v):
                problems.append(f"derived[{k!r}] = {v!r} is not numeric")
    if obj["kind"] == "final" and not isinstance(
        obj.get("exit_reason"), str
    ):
        problems.append("final line is missing a string exit_reason")
    if obj["kind"] != "final" and "exit_reason" in obj:
        problems.append("exit_reason on a non-final line")

    if version == 1:
        for key in _V2_FIELDS:
            if key in obj:
                problems.append(f"v2 field {key!r} on a schema-v1 line")
        return problems

    # ------------------------------------------------- v2 additions
    if "memory" in obj:
        _check_numeric_map(obj, "memory", problems)
    if obj["kind"] == "memory" and "memory" not in obj:
        problems.append("memory line is missing the memory object")

    if obj["kind"] == "compile_warning":
        comp = obj.get("compile")
        if not isinstance(comp, dict):
            problems.append(
                "compile_warning line is missing the compile object"
            )
        else:
            for key in ("fn", "delta"):
                if not isinstance(comp.get(key), str):
                    problems.append(
                        f"compile[{key!r}] = {comp.get(key)!r} is not a "
                        "string"
                    )
            if "count" in comp and (
                not isinstance(comp["count"], int)
                or isinstance(comp["count"], bool)
                or comp["count"] < 0
            ):
                problems.append(
                    f"compile['count'] = {comp['count']!r} is not a "
                    "non-negative int"
                )
            if "wall_secs" in comp and not _is_number(comp["wall_secs"]):
                problems.append(
                    f"compile['wall_secs'] = {comp['wall_secs']!r} is not "
                    "a number"
                )
    elif "compile" in obj:
        problems.append("compile object on a non-compile_warning line")

    if "profile" in obj:
        if obj["kind"] != "final":
            problems.append("profile object on a non-final line")
        elif not isinstance(obj["profile"], dict):
            problems.append("profile is not an object")
        else:
            prof = obj["profile"]
            if not isinstance(prof.get("dir"), str):
                problems.append("profile['dir'] is not a string")
            for key in ("start_step", "num_steps"):
                v = prof.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    problems.append(
                        f"profile[{key!r}] = {v!r} is not a non-negative "
                        "int"
                    )
    return problems


def validate(obj: Any) -> None:
    """Raise ValueError listing every violation (empty = returns None)."""
    problems = validate_line(obj)
    if problems:
        raise ValueError(
            "telemetry line violates schema v%d:\n  %s"
            % (SCHEMA_VERSION, "\n  ".join(problems))
        )
