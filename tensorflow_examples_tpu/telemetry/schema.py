"""Self-describing JSONL metrics schema (ISSUE 2 CI satellite; v2 in
ISSUE 3; v3 in ISSUE 4; v4 in ISSUE 5; v5 in ISSUE 7; v6 in ISSUE 8 —
paged-KV block/prefix-cache fields and router-tier fields on the
``serving`` object, see ``SERVING_KEYS_V6``; v7 in ISSUE 10 —
fault-tolerance counters on the router's ``serving`` object, see
``SERVING_KEYS_V7``; v8 in ISSUE 11 — speculative-decoding measurement
keys on the batcher's ``serving`` object, see ``SERVING_KEYS_V8``; v9
in ISSUE 12 — the prefix-cache summary behind cache-aware fleet
scheduling, see ``SERVING_KEYS_V9``; v10 in ISSUE 13 — SLO-class
admission, brownout, and digest-truncation observability, see
``SERVING_KEYS_V10``; v11 in ISSUE 15 — the weight-quantization
story behind int8/fp8 end-to-end serving, see ``SERVING_KEYS_V11``).

Every line the JSONL sink emits carries ``schema_version`` so offline
consumers (tools/telemetry_report.py, tools/bench_gate.py, future
BENCH_* harvesters) can evolve without guessing. ``validate_line`` is
the single source of truth for what a line must look like — the tier-1
test validates every emitted line through it, and the report CLI
refuses lines it cannot validate rather than mis-aggregating them.

Hand-rolled (no jsonschema dependency — the image is pip-install-free);
the structure is small enough that explicit checks read better anyway.

Line shape (version 3; version-1/-2 lines remain valid input)::

    {
      "schema_version": 3,
      "kind": "window" | "eval" | "final" | "memory" | "compile_warning"
              | "fleet",
      "step": <int >= 0>,            # loop step the line was emitted at
      "time_unix": <float>,          # wall clock at emission
      "session_start_unix": <float>, # constant per fit-session: the
                                     #   boundary marker for resumed runs
      "metrics": {"train/loss": 1.2, ...},      # window means
      "counters": {"data/batches_fetched": 10, ...},  # cumulative
                                     #   WITHIN the session (fit deltas)
      "gauges": {...},                          # instantaneous values
      "derived": {"examples_per_sec": ..., "step_time_p50": ...,
                  "mfu": ..., "goodput": ...},  # may hold nulls
      "exit_reason": "preempt" | ...  # kind == "final" only

      # --- version 2 additions (telemetry/memory.py, compilation.py,
      #     profiling.py) ---
      "memory": {"live_bytes": ..., "peak_live_bytes": ...,
                 "params_bytes": ..., ...},  # numeric|null; REQUIRED on
                                     #   kind == "memory" (the init
                                     #   breakdown snapshot), optional
                                     #   on window/final lines
      "compile": {"fn": "train_step", "delta": "...axis 0: 64->32...",
                  "count": 2, "wall_secs": 0.4},  # REQUIRED on (and
                                     #   exclusive to) compile_warning
      "profile": {"dir": "...", "start_step": 10, "num_steps": 10,
                  "wall_secs": 1.2}  # final lines only: cross-link to
                                     #   the in-loop profiler window

      # --- version 3 additions (telemetry/fleet.py) ---
      "host": 0,                     # REQUIRED on every v3 line: the
                                     #   jax.process_index() that wrote it
      "fleet": {                     # REQUIRED on (and exclusive to)
                                     #   kind == "fleet" lines
        "hosts": [{"host": 0, "step_time_p50": 0.01,
                   "step_time_p95": 0.02, "data_fetch_p95": 0.001,
                   "steps_lost": 0, "peak_live_bytes": 1024,
                   "data_work_p95": 0.001}, ...],  # data_work_p95:
                                     #   additive (ISSUE 6), optional
                                     #   on read
        "slowest_host": 1,           # int|null: p95 argmax
        "skew": 3.2,                 # slowest p95 / fleet median p95
        "side": "input",             # "compute"|"input"|null: where the
                                     #   straggler's excess time sits
        "straggler": true,           # skew crossed straggler_skew_factor
        "emergency": true            # optional: cached snapshot from the
                                     #   watchdog-fatal path (no collective)
      }

      # --- version 4 additions (serving/batcher.py stats lines) ---
      "serving": {                   # REQUIRED on (and exclusive to)
                                     #   kind == "serving" lines; all
                                     #   numeric
        "active_requests": 3, "queue_depth": 0, "slots": 8,
        "kv_occupancy": 0.375, "post_warmup_recompiles": 0,
        "draining": 0
      }

      # --- version 5 additions (sharding/; train/loop.py) ---
      "sharding": {                  # OPTIONAL, kind == "final" only:
                                     #   placement provenance
        "mesh_shape": {"data": 2, "model": 4, ...},  # axis -> size
        "param_sharding_digest": "1f2e3d...",  # sharding/resolve.py
                                     #   digest: mesh-shape independent,
                                     #   rule-table sensitive
        "zero1": false               # optional bool
      }
    }

Version-1/-2 lines (the pre-ISSUE-3/-4 streams) carry none of the later
fields and only their own kinds; they still validate, so old run dirs
keep reporting.
"""

from __future__ import annotations

import numbers
from typing import Any

# Version 5 (ISSUE 7): additive — training lines may carry a
# "sharding" object on kind="final" (mesh shape + param-sharding
# digest). SCHEMA_VERSION is what the trainer hub stamps.
SCHEMA_VERSION = 5

# Version 6 (ISSUE 8): additive — the serving object may carry
# paged-KV fields (block_size / blocks_total / blocks_used /
# kv_block_occupancy / kv_slot_occupancy / prefix_hits /
# prefix_misses / prefix_hit_rate / kv_bits) and router-tier fields
# (replicas / router_dispatched / router_retries / router_no_replica),
# all numeric. serving/batcher.py and serving/router.py stamp
# SERVING_SCHEMA_VERSION on their ``kind="serving"`` stats lines (a
# v3-shaped line plus the required "serving" object introduced in v4:
# active_requests / queue_depth / slots / kv_occupancy /
# post_warmup_recompiles / draining).
#
# Version 7 (ISSUE 10): additive — the router's serving object may
# carry the fault-tolerance counters (router_ejections /
# router_readmits / router_hedges / router_failovers /
# router_restarts), all numeric; forbidden on v4-v6 serving lines.
#
# Version 8 (ISSUE 11): additive — a speculative-decoding serving line
# may carry spec_k (the configured draft window), draft_hit_rate
# (accepted drafts / offered drafts) and accepted_per_step (mean
# committed tokens per request verify step), all numeric; forbidden on
# v4-v7 serving lines, same mislabeling rule as every earlier bump.
#
# Version 9 (ISSUE 12): additive — a cache-aware serving line may
# carry prefix_blocks (published prefix-cache blocks; the affinity
# digest's size) and prefix_chains (distinct chain heads), both
# numeric. The batcher stamps a paged replica's own counts; the router
# stamps the probe-summed fleet totals. Forbidden on v4-v8 serving
# lines, same mislabeling rule as every earlier bump.
#
# Version 10 (ISSUE 13): additive — an overload-aware serving line may
# carry the SLO-class split (per-class queue-wait/TTFT/TPOT p95s and
# shed counters, batch preemptions), the brownout controller's state
# (brownout_level / brownout_transitions), and the paged pool's
# digest_truncated flag (0/1 — the affinity digest hit its cap, so
# affinity misses on very large caches are diagnosable). The batcher
# stamps its own numbers; the router stamps the fleet view (max
# brownout level, summed transitions). Forbidden on v4-v9 serving
# lines, same mislabeling rule as every earlier bump.
#
# Version 11 (ISSUE 15): additive — a weight-quantized serving line
# may carry the precision registry's facts (weight_bits /
# param_bytes / param_bytes_f32 / quantized_params — what precision
# the replica is ACTUALLY serving at, and what it costs in HBM
# versus f32). All numeric; optional on write (an unquantized line
# carries none), FORBIDDEN on v4-v10 serving lines, same mislabeling
# rule as every earlier bump.
#
# Version 12 (ISSUE 16): additive — a control-plane-resilient serving
# line may carry the router journal/takeover facts (journal_appends /
# takeover_total / resumed_streams / dedup_hits — counters — and
# takeover_latency_s, the last promotion's detect-to-serving wall
# time). Stamped by the router only; FORBIDDEN on v4-v11 serving
# lines, same mislabeling rule as every earlier bump.
#
# Version 13 (ISSUE 18): a new line KIND — ``kind="trace"`` carries one
# completed per-request trace tree (top-level "trace" object:
# trace_id, SLO class, final status, client-visible e2e seconds, the
# tail-sampler's keep_reason, and the span list — each span a
# span_id/name/start_unix/dur_s record with optional parent_id and
# tags). Written by telemetry/tracing.py with the PR-2 sink discipline
# (one line per trace, flushed per append, torn-tail-tolerant read).
# Both the kind and the object are FORBIDDEN on v4-v12 lines. The
# serving object gains the trace-accounting keys (traces_kept /
# traces_dropped / trace_coverage / slow_trace_count — stamped by the
# router only), FORBIDDEN on v4-v12 serving lines, same mislabeling
# rule as every earlier bump.
#
# Version 14 (ISSUE 19): a new line KIND — ``kind="alert"`` carries one
# SLO alert transition (top-level "alert" object: rule name, SLO
# class, state — firing or resolved — severity, the burn rate and
# error budget remaining at transition time, and optionally the
# offending replica, the observed value vs objective, and the
# worst-offender exemplar ``trace_id`` that joins the alert to its
# ISSUE-18 trace). Written by telemetry/slo.py with the PR-2 sink
# discipline. Both the kind and the object are FORBIDDEN on v4-v13
# lines. The serving object gains the alerting summary keys
# (alerts_firing / error_budget_remaining / probe_success_rate /
# alert_count — stamped by the router only), FORBIDDEN on v4-v13
# serving lines, same mislabeling rule as every earlier bump.
SERVING_SCHEMA_VERSION = 14

SUPPORTED_VERSIONS = (1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14)

KINDS_V1 = ("window", "eval", "final")
KINDS_V2 = KINDS_V1 + ("memory", "compile_warning")
KINDS_V3 = KINDS_V2 + ("fleet",)
KINDS_V12 = KINDS_V3 + ("serving",)
KINDS_V13 = KINDS_V12 + ("trace",)
KINDS = KINDS_V13 + ("alert",)

_REQUIRED = ("schema_version", "kind", "step", "time_unix",
             "session_start_unix", "metrics", "counters", "gauges",
             "derived")

# v2-only top-level objects: forbidden on v1 lines (a "v1" line carrying
# them is a mislabeled v2 line — flag it instead of half-validating).
_V2_FIELDS = ("memory", "compile", "profile")

# v3-only top-level fields, same rule for v1/v2 lines.
_V3_FIELDS = ("host", "fleet")

# v4-only top-level objects, same rule for v1/v2/v3 lines.
_V4_FIELDS = ("serving",)

# v5-only top-level objects, forbidden on earlier versions.
_V5_FIELDS = ("sharding",)

# v13-only top-level objects, forbidden on earlier versions (a line
# carrying a trace tree without the v13 stamp is mislabeled).
_V13_FIELDS = ("trace",)

# v14-only top-level objects, same mislabeling rule.
_V14_FIELDS = ("alert",)

# Required keys of a v5 sharding object (writer: train/loop.py via
# telemetry/hub.py sharding_info).
SHARDING_KEYS = ("mesh_shape", "param_sharding_digest")

# Required keys of a v4 serving object (the writer is
# serving/batcher.py stats_line; every one is numeric).
SERVING_KEYS = ("active_requests", "queue_depth", "slots",
                "kv_occupancy", "post_warmup_recompiles", "draining")

# v6-only serving-object keys (optional on write — a dense-pool line
# carries none of the paged fields, a single-engine line none of the
# router fields — but FORBIDDEN on v4/v5 serving lines: a "v4" line
# carrying them is a mislabeled v6 line, same rule as every earlier
# version bump's top-level objects).
SERVING_KEYS_V6 = ("block_size", "blocks_total", "blocks_used",
                   "kv_block_occupancy", "kv_slot_occupancy",
                   "prefix_hits", "prefix_misses", "prefix_hit_rate",
                   "kv_bits", "replicas", "router_dispatched",
                   "router_retries", "router_no_replica")

# v7-only serving-object keys (ISSUE 10): the router's fault-tolerance
# counters — circuit-breaker ejections/readmits, hedged dispatches,
# in-flight failovers, and supervisor restart cycles. Optional on
# write (a single-engine line carries none), FORBIDDEN on v4-v6
# serving lines, same mislabeling rule as every earlier bump.
SERVING_KEYS_V7 = ("router_ejections", "router_readmits",
                   "router_hedges", "router_failovers",
                   "router_restarts")

# v8-only serving-object keys (ISSUE 11): the speculative-decoding
# measurement trio the batcher stamps when spec_decode_k > 0. Optional
# on write (a non-speculative line carries none), FORBIDDEN on v4-v7
# serving lines.
SERVING_KEYS_V8 = ("accepted_per_step", "draft_hit_rate", "spec_k")

# v9-only serving-object keys (ISSUE 12): the prefix-cache summary
# behind cache-aware fleet scheduling — published blocks (the affinity
# digest's size) and distinct chain heads. Optional on write (a
# dense-pool line carries neither), FORBIDDEN on v4-v8 serving lines.
SERVING_KEYS_V9 = ("prefix_blocks", "prefix_chains")

# v10-only serving-object keys (ISSUE 13): the overload story — the
# SLO-class split (interactive vs batch latency p95s, per-class shed
# counters, batch preemptions), the brownout ladder's state, and the
# paged pool's digest-truncation flag. All numeric; optional on write
# (a pre-overload line carries none), FORBIDDEN on v4-v9 serving
# lines, same mislabeling rule as every earlier bump.
SERVING_KEYS_V10 = (
    "queue_wait_p95_interactive", "queue_wait_p95_batch",
    "ttft_p95_interactive", "ttft_p95_batch",
    "tpot_p95_interactive", "tpot_p95_batch",
    "shed_interactive", "shed_batch", "preempted_batch",
    "brownout_level", "brownout_transitions", "digest_truncated",
)

# v11-only serving-object keys (ISSUE 15): the precision registry's
# serving facts — weight payload bits, param bytes as stored vs what
# the same tree costs at f32, and the quantized-leaf count. Stamped by
# the batcher only when the engine serves quantized weights; FORBIDDEN
# on v4-v10 serving lines.
SERVING_KEYS_V11 = ("weight_bits", "param_bytes", "param_bytes_f32",
                    "quantized_params")

# v12-only serving-object keys (ISSUE 16): the control-plane
# resilience story — durable-journal appends, standby promotions and
# the last takeover's detect-to-serving latency, client streams
# resumed mid-generation, and idempotent-retry dedupe hits. All
# numeric; optional on write (a journal-less router carries none),
# FORBIDDEN on v4-v11 serving lines, same mislabeling rule as every
# earlier bump.
SERVING_KEYS_V12 = ("journal_appends", "takeover_total",
                    "resumed_streams", "dedup_hits",
                    "takeover_latency_s")

# v13-only serving-object keys (ISSUE 18): the router's per-request
# tracing accounting — traces the tail sampler kept vs dropped, the
# kept fraction, and how many kept traces were slow for their SLO
# class. All numeric; stamped by the router only (a replica line
# carries none), FORBIDDEN on v4-v12 serving lines, same mislabeling
# rule as every earlier bump.
SERVING_KEYS_V13 = ("traces_kept", "traces_dropped", "trace_coverage",
                    "slow_trace_count")

# v14-only serving-object keys (ISSUE 19): the SLO engine's summary —
# alerts currently firing, the worst rule's error budget remaining
# (fraction, 1.0 = untouched), the synthetic canary prober's rolling
# success rate, and the cumulative firing-transition count. All
# numeric; stamped by the router only (a replica line carries none),
# FORBIDDEN on v4-v13 serving lines, same mislabeling rule as every
# earlier bump.
SERVING_KEYS_V14 = ("alerts_firing", "error_budget_remaining",
                    "probe_success_rate", "alert_count")

# Required keys of a v13 trace object (writer: telemetry/tracing.py
# TraceRecorder.finish) and of each entry in its "spans" list.
TRACE_KEYS = ("trace_id", "slo", "status", "e2e_s", "keep_reason",
              "spans")
TRACE_SPAN_KEYS = ("span_id", "name", "start_unix", "dur_s")

# Required keys of a v14 alert object (writer: telemetry/slo.py
# AlertEngine). Optional extras — "replica" (string), "value" /
# "threshold" / "window_s" (numbers), "trace_id" (the worst-offender
# exemplar, string) — are typed-checked when present.
ALERT_KEYS = ("name", "slo", "state", "severity", "burn_rate",
              "budget_remaining", "since_unix")
ALERT_STATES = ("firing", "resolved")

# Instrument namespaces of the serving tier whose counter/gauge/
# histogram registrations the graftlint drift pass cross-checks
# against the docs catalog (ISSUE 15 satellite: the pass LEARNS this
# list from here — adding a namespace is a schema-module edit, not a
# lint-pass edit).
INSTRUMENT_PREFIXES = ("serving/", "router/", "autoscaler/",
                       "precision/", "trace/", "alert/", "probe/")

# The per-host entry of a fleet line's "hosts" list: "host" is a
# required int, and each of these is required numeric-or-null (the
# writer side, fleet.VECTOR_KEYS, aliases FLEET_VECTOR_KEYS below — the
# allgathered vector and the validated line cannot drift apart).
# io_retries and batches_skipped are each host's OWN pre-reduction
# numbers — the line-level counters carry the fleet sums, so these
# entries are the only place a flaky host's IO churn stays localizable.
FLEET_HOST_KEYS = ("step_time_p50", "step_time_p95", "data_fetch_p95",
                   "steps_lost", "peak_live_bytes", "io_retries",
                   "batches_skipped")

# Additive (optional-on-read) host keys: written by every current fleet
# line but NOT required by the validator, so v3 lines from runs that
# predate them keep validating. data_work_p95 (ISSUE 6) is host time
# actually spent PRODUCING batches (the ``data_work`` span) — the
# straggler input-side verdict reads it instead of data_fetch_p95,
# which also counts queue back-pressure wait and would misreport a
# fast host blocked on the device as input-bound. Values present in a
# hosts entry are still numeric-or-null checked.
FLEET_HOST_KEYS_OPTIONAL = ("data_work_p95",)

# The full allgathered per-host vector, in wire order.
FLEET_VECTOR_KEYS = FLEET_HOST_KEYS + FLEET_HOST_KEYS_OPTIONAL


def _is_number(v: Any) -> bool:
    return isinstance(v, numbers.Real) and not isinstance(v, bool)


def _check_numeric_map(obj: dict, section: str, problems: list[str]) -> None:
    sec = obj.get(section)
    if not isinstance(sec, dict):
        problems.append(f"{section} is not an object")
        return
    for k, v in sec.items():
        if not isinstance(k, str):
            problems.append(f"{section} key {k!r} is not a string")
        # NaN/Inf pass through json.dumps as bare tokens; numeric or
        # null is the contract (a NaN loss window is still a number).
        if v is not None and not _is_number(v):
            problems.append(f"{section}[{k!r}] = {v!r} is not numeric")


def validate_line(obj: Any) -> list[str]:
    """Return the list of schema violations (empty = valid)."""
    if not isinstance(obj, dict):
        return [f"line is {type(obj).__name__}, not an object"]
    problems: list[str] = []
    for key in _REQUIRED:
        if key not in obj:
            problems.append(f"missing required field {key!r}")
    if problems:
        return problems
    version = obj["schema_version"]
    if version not in SUPPORTED_VERSIONS:
        problems.append(
            f"schema_version {version!r} not in {SUPPORTED_VERSIONS}"
        )
        return problems
    kinds = {1: KINDS_V1, 2: KINDS_V2, 3: KINDS_V3}.get(
        version,
        KINDS_V12 if version < 13
        else (KINDS_V13 if version < 14 else KINDS),
    )
    if obj["kind"] not in kinds:
        problems.append(f"kind {obj['kind']!r} not in {kinds}")
    if not isinstance(obj["step"], int) or isinstance(obj["step"], bool) \
            or obj["step"] < 0:
        problems.append(f"step {obj['step']!r} is not a non-negative int")
    for key in ("time_unix", "session_start_unix"):
        if not _is_number(obj[key]):
            problems.append(f"{key} {obj[key]!r} is not a number")
    for section in ("metrics", "gauges"):
        _check_numeric_map(obj, section, problems)
    counters = obj["counters"]
    if not isinstance(counters, dict):
        problems.append("counters is not an object")
    else:
        for k, v in counters.items():
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                problems.append(
                    f"counters[{k!r}] = {v!r} is not a non-negative int"
                )
    derived = obj["derived"]
    if not isinstance(derived, dict):
        problems.append("derived is not an object")
    else:
        for k, v in derived.items():
            if v is not None and not _is_number(v):
                problems.append(f"derived[{k!r}] = {v!r} is not numeric")
    if obj["kind"] == "final" and not isinstance(
        obj.get("exit_reason"), str
    ):
        problems.append("final line is missing a string exit_reason")
    if obj["kind"] != "final" and "exit_reason" in obj:
        problems.append("exit_reason on a non-final line")

    if version == 1:
        for fields, v in ((_V2_FIELDS, 2), (_V3_FIELDS, 3),
                          (_V4_FIELDS, 4), (_V5_FIELDS, 5),
                          (_V13_FIELDS, 13), (_V14_FIELDS, 14)):
            for key in fields:
                if key in obj:
                    problems.append(
                        f"v{v} field {key!r} on a schema-v1 line"
                    )
        return problems

    # ------------------------------------------------- v2 additions
    if "memory" in obj:
        _check_numeric_map(obj, "memory", problems)
    if obj["kind"] == "memory" and "memory" not in obj:
        problems.append("memory line is missing the memory object")

    if obj["kind"] == "compile_warning":
        comp = obj.get("compile")
        if not isinstance(comp, dict):
            problems.append(
                "compile_warning line is missing the compile object"
            )
        else:
            for key in ("fn", "delta"):
                if not isinstance(comp.get(key), str):
                    problems.append(
                        f"compile[{key!r}] = {comp.get(key)!r} is not a "
                        "string"
                    )
            if "count" in comp and (
                not isinstance(comp["count"], int)
                or isinstance(comp["count"], bool)
                or comp["count"] < 0
            ):
                problems.append(
                    f"compile['count'] = {comp['count']!r} is not a "
                    "non-negative int"
                )
            if "wall_secs" in comp and not _is_number(comp["wall_secs"]):
                problems.append(
                    f"compile['wall_secs'] = {comp['wall_secs']!r} is not "
                    "a number"
                )
    elif "compile" in obj:
        problems.append("compile object on a non-compile_warning line")

    if "profile" in obj:
        if obj["kind"] != "final":
            problems.append("profile object on a non-final line")
        elif not isinstance(obj["profile"], dict):
            problems.append("profile is not an object")
        else:
            prof = obj["profile"]
            if not isinstance(prof.get("dir"), str):
                problems.append("profile['dir'] is not a string")
            for key in ("start_step", "num_steps"):
                v = prof.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    problems.append(
                        f"profile[{key!r}] = {v!r} is not a non-negative "
                        "int"
                    )

    if version == 2:
        for fields, v in ((_V3_FIELDS, 3), (_V4_FIELDS, 4),
                          (_V5_FIELDS, 5), (_V13_FIELDS, 13),
                          (_V14_FIELDS, 14)):
            for key in fields:
                if key in obj:
                    problems.append(
                        f"v{v} field {key!r} on a schema-v2 line"
                    )
        return problems

    # ------------------------------------------------- v3 additions
    host = obj.get("host")
    if not isinstance(host, int) or isinstance(host, bool) or host < 0:
        problems.append(f"host {host!r} is not a non-negative int")

    if obj["kind"] == "fleet":
        fleet = obj.get("fleet")
        if not isinstance(fleet, dict):
            problems.append("fleet line is missing the fleet object")
        else:
            hosts = fleet.get("hosts")
            if not isinstance(hosts, list) or not hosts:
                problems.append(
                    f"fleet['hosts'] = {hosts!r} is not a non-empty list"
                )
            else:
                for i, entry in enumerate(hosts):
                    if not isinstance(entry, dict):
                        problems.append(
                            f"fleet['hosts'][{i}] is not an object"
                        )
                        continue
                    h = entry.get("host")
                    if not isinstance(h, int) or isinstance(h, bool) \
                            or h < 0:
                        problems.append(
                            f"fleet['hosts'][{i}]['host'] = {h!r} is not "
                            "a non-negative int"
                        )
                    for key in FLEET_HOST_KEYS:
                        if key not in entry:
                            problems.append(
                                f"fleet['hosts'][{i}] is missing {key!r}"
                            )
                    for k, v in entry.items():
                        if k != "host" and v is not None \
                                and not _is_number(v):
                            problems.append(
                                f"fleet['hosts'][{i}][{k!r}] = {v!r} is "
                                "not numeric"
                            )
            slowest = fleet.get("slowest_host")
            if slowest is not None and (
                not isinstance(slowest, int) or isinstance(slowest, bool)
                or slowest < 0
            ):
                problems.append(
                    f"fleet['slowest_host'] = {slowest!r} is not a "
                    "non-negative int or null"
                )
            skew = fleet.get("skew")
            if skew is not None and not _is_number(skew):
                problems.append(
                    f"fleet['skew'] = {skew!r} is not numeric or null"
                )
            side = fleet.get("side")
            if side not in (None, "compute", "input"):
                problems.append(
                    f"fleet['side'] = {side!r} is not 'compute'/'input'/"
                    "null"
                )
            if not isinstance(fleet.get("straggler", False), bool):
                problems.append(
                    f"fleet['straggler'] = {fleet['straggler']!r} is not "
                    "a bool"
                )
    elif "fleet" in obj:
        problems.append("fleet object on a non-fleet line")

    if version == 3:
        if "serving" in obj:
            problems.append("v4 field 'serving' on a schema-v3 line")
        if "sharding" in obj:
            problems.append("v5 field 'sharding' on a schema-v3 line")
        if "trace" in obj:
            problems.append("v13 field 'trace' on a schema-v3 line")
        if "alert" in obj:
            problems.append("v14 field 'alert' on a schema-v3 line")
        return problems

    # ------------------------------------------------- v4 additions
    if obj["kind"] == "serving":
        if not isinstance(obj.get("serving"), dict):
            problems.append("serving line is missing the serving object")
        else:
            _check_numeric_map(obj, "serving", problems)
            for key in SERVING_KEYS:
                if key not in obj["serving"]:
                    problems.append(
                        f"serving object is missing required key {key!r}"
                    )
            if version < 6:
                for key in SERVING_KEYS_V6:
                    if key in obj["serving"]:
                        problems.append(
                            f"v6 serving key {key!r} on a schema-v"
                            f"{version} line"
                        )
            if version < 7:
                for key in SERVING_KEYS_V7:
                    if key in obj["serving"]:
                        problems.append(
                            f"v7 serving key {key!r} on a schema-v"
                            f"{version} line"
                        )
            if version < 8:
                for key in SERVING_KEYS_V8:
                    if key in obj["serving"]:
                        problems.append(
                            f"v8 serving key {key!r} on a schema-v"
                            f"{version} line"
                        )
            if version < 9:
                for key in SERVING_KEYS_V9:
                    if key in obj["serving"]:
                        problems.append(
                            f"v9 serving key {key!r} on a schema-v"
                            f"{version} line"
                        )
            if version < 10:
                for key in SERVING_KEYS_V10:
                    if key in obj["serving"]:
                        problems.append(
                            f"v10 serving key {key!r} on a schema-v"
                            f"{version} line"
                        )
            if version < 11:
                for key in SERVING_KEYS_V11:
                    if key in obj["serving"]:
                        problems.append(
                            f"v11 serving key {key!r} on a schema-v"
                            f"{version} line"
                        )
            if version < 12:
                for key in SERVING_KEYS_V12:
                    if key in obj["serving"]:
                        problems.append(
                            f"v12 serving key {key!r} on a schema-v"
                            f"{version} line"
                        )
            if version < 13:
                for key in SERVING_KEYS_V13:
                    if key in obj["serving"]:
                        problems.append(
                            f"v13 serving key {key!r} on a schema-v"
                            f"{version} line"
                        )
            if version < 14:
                for key in SERVING_KEYS_V14:
                    if key in obj["serving"]:
                        problems.append(
                            f"v14 serving key {key!r} on a schema-v"
                            f"{version} line"
                        )
    elif "serving" in obj:
        problems.append("serving object on a non-serving line")

    # ------------------------------------------------ v13 trace lines
    if obj["kind"] == "trace":
        trace = obj.get("trace")
        if not isinstance(trace, dict):
            problems.append("trace line is missing the trace object")
        else:
            for key in TRACE_KEYS:
                if key not in trace:
                    problems.append(
                        f"trace object is missing required key {key!r}"
                    )
            for key in ("trace_id", "slo", "keep_reason"):
                v = trace.get(key)
                if key in trace and not isinstance(v, str):
                    problems.append(
                        f"trace[{key!r}] = {v!r} is not a string"
                    )
            status = trace.get("status")
            if "status" in trace and (
                not isinstance(status, int) or isinstance(status, bool)
            ):
                problems.append(
                    f"trace['status'] = {status!r} is not an int"
                )
            if "e2e_s" in trace and not _is_number(trace["e2e_s"]):
                problems.append(
                    f"trace['e2e_s'] = {trace['e2e_s']!r} is not a number"
                )
            spans = trace.get("spans")
            if "spans" in trace and not isinstance(spans, list):
                problems.append(
                    f"trace['spans'] = {spans!r} is not a list"
                )
            for i, sp in enumerate(spans if isinstance(spans, list)
                                   else ()):
                if not isinstance(sp, dict):
                    problems.append(f"trace['spans'][{i}] is not an object")
                    continue
                for key in TRACE_SPAN_KEYS:
                    if key not in sp:
                        problems.append(
                            f"trace['spans'][{i}] is missing {key!r}"
                        )
                for key in ("span_id", "name"):
                    if key in sp and not isinstance(sp[key], str):
                        problems.append(
                            f"trace['spans'][{i}][{key!r}] = "
                            f"{sp[key]!r} is not a string"
                        )
                for key in ("start_unix", "dur_s"):
                    if key in sp and not _is_number(sp[key]):
                        problems.append(
                            f"trace['spans'][{i}][{key!r}] = "
                            f"{sp[key]!r} is not a number"
                        )
                parent = sp.get("parent_id")
                if parent is not None and not isinstance(parent, str):
                    problems.append(
                        f"trace['spans'][{i}]['parent_id'] = {parent!r} "
                        "is not a string or null"
                    )
                tags = sp.get("tags")
                if tags is not None and not isinstance(tags, dict):
                    problems.append(
                        f"trace['spans'][{i}]['tags'] = {tags!r} is not "
                        "an object"
                    )
    elif "trace" in obj:
        problems.append("trace object on a non-trace line")

    # ------------------------------------------------ v14 alert lines
    if obj["kind"] == "alert":
        alert = obj.get("alert")
        if not isinstance(alert, dict):
            problems.append("alert line is missing the alert object")
        else:
            for key in ALERT_KEYS:
                if key not in alert:
                    problems.append(
                        f"alert object is missing required key {key!r}"
                    )
            for key in ("name", "slo", "severity"):
                v = alert.get(key)
                if key in alert and not isinstance(v, str):
                    problems.append(
                        f"alert[{key!r}] = {v!r} is not a string"
                    )
            state = alert.get("state")
            if "state" in alert and state not in ALERT_STATES:
                problems.append(
                    f"alert['state'] = {state!r} not in {ALERT_STATES}"
                )
            for key in ("burn_rate", "budget_remaining", "since_unix",
                        "value", "threshold", "window_s"):
                if key in alert and not _is_number(alert[key]):
                    problems.append(
                        f"alert[{key!r}] = {alert[key]!r} is not a number"
                    )
            for key in ("replica", "trace_id"):
                v = alert.get(key)
                if v is not None and not isinstance(v, str):
                    problems.append(
                        f"alert[{key!r}] = {v!r} is not a string or null"
                    )
    elif "alert" in obj:
        problems.append("alert object on a non-alert line")

    if version == 4:
        if "sharding" in obj:
            problems.append("v5 field 'sharding' on a schema-v4 line")
        return problems

    # ------------------------------------------------- v5 additions
    if "sharding" in obj:
        if obj["kind"] != "final":
            problems.append("sharding object on a non-final line")
        elif not isinstance(obj["sharding"], dict):
            problems.append("sharding is not an object")
        else:
            sh = obj["sharding"]
            for key in SHARDING_KEYS:
                if key not in sh:
                    problems.append(
                        f"sharding object is missing required key {key!r}"
                    )
            mesh = sh.get("mesh_shape")
            if mesh is not None:
                if not isinstance(mesh, dict) or not mesh:
                    problems.append(
                        "sharding['mesh_shape'] is not a non-empty object"
                    )
                else:
                    for axis, size in mesh.items():
                        if (
                            not isinstance(axis, str)
                            or not isinstance(size, int)
                            or isinstance(size, bool)
                            or size < 1
                        ):
                            problems.append(
                                f"sharding['mesh_shape'][{axis!r}] = "
                                f"{size!r} is not a positive int"
                            )
            digest = sh.get("param_sharding_digest")
            if digest is not None and not isinstance(digest, str):
                problems.append(
                    f"sharding['param_sharding_digest'] = {digest!r} is "
                    "not a string"
                )
            if "zero1" in sh and not isinstance(sh["zero1"], bool):
                problems.append(
                    f"sharding['zero1'] = {sh['zero1']!r} is not a bool"
                )
    return problems


def validate(obj: Any) -> None:
    """Raise ValueError listing every violation (empty = returns None)."""
    problems = validate_line(obj)
    if problems:
        raise ValueError(
            "telemetry line violates schema v%d:\n  %s"
            % (SCHEMA_VERSION, "\n  ".join(problems))
        )
