"""HBM / host memory accounting (ISSUE 3 tentpole (2)).

The second device-side blind spot after recompilation: an HBM blow-up
surfaces as an opaque ``RESOURCE_EXHAUSTED`` with no record of *what*
was resident. This module makes every run account for its memory:

* ``MemoryMonitor.init_breakdown(state)`` — at fit start (post-restore,
  post-init) snapshot ``jax.live_arrays()`` + per-device allocator
  stats and attribute live bytes to **params vs. optimizer state vs.
  non-trainable model state vs. other** (prefetch buffers, RNG keys,
  eval copies). Emitted as a ``kind="memory"`` schema-v2 JSONL line.
* ``MemoryMonitor.sample()`` — cheap live-byte poll at every log
  window; maintains the run's **peak watermark** gauge
  (``memory/peak_live_bytes``) and the per-window fields every
  window/final line carries under ``"memory"``.
* ``oom_report()`` — allocation forensics on the way down: the top live
  arrays by size, the component breakdown, and allocator stats, logged
  BEFORE the OOM re-raises so the evidence lands even when the process
  dies (``train/loop.py`` fit's teardown calls it via ``is_oom``).

Byte accounting uses array ``nbytes`` over ``jax.live_arrays()`` — the
process-local view, exact on single-host runs and a per-host lower
bound on multi-host ones. Device allocator stats
(``Device.memory_stats()``) are included when the backend reports them
(TPU/GPU; CPU returns None, which is why the live-array path is the
portable backbone and the CPU tests still see a nonzero watermark).
"""

from __future__ import annotations

import logging
from typing import Any, Mapping

from tensorflow_examples_tpu.telemetry import registry as registry_mod

log = logging.getLogger(__name__)

# Patterns that identify an out-of-device-memory failure across
# backends (XLA's RESOURCE_EXHAUSTED, PJRT OOM messages, allocator
# text) — matched case-insensitively against the exception repr.
# "oom" needs word boundaries (it is a substring of ordinary words).
_OOM_PATTERNS = (
    "resource_exhausted",
    "out of memory",
    r"\boom\b",
    "memory_limit",
    "allocation failure",
)


def tree_bytes(tree: Any, *, per_device: bool = False) -> int:
    """Total array bytes of a pytree (0 for empty/None leaves).

    ``per_device=True`` counts each leaf's bytes ON ONE DEVICE — a
    leaf sharded N ways contributes 1/N of its global bytes, a
    replicated leaf its full size (the ZeRO-1 memory claim is stated
    in this unit: per-device optimizer bytes scale down with the
    replica count; ISSUE 7). Sharding is read from the leaf's
    ``.sharding`` when present (concrete jax.Arrays and abstract
    eval_shape trees carrying shardings alike); shardless leaves count
    full size.
    """
    import jax

    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            # Abstract leaves (ShapeDtypeStruct) carry shape/dtype only.
            itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 0)
            if shape is None or not itemsize:
                continue
            nbytes = int(np.prod(shape, dtype=np.int64)) * int(itemsize)
        nbytes = int(nbytes)
        if per_device and shape is not None:
            sharding = getattr(leaf, "sharding", None)
            shard_shape = getattr(sharding, "shard_shape", None)
            if shard_shape is not None:
                try:
                    local = int(
                        np.prod(shard_shape(tuple(shape)), dtype=np.int64)
                    )
                    size = int(np.prod(shape, dtype=np.int64))
                    if size:
                        nbytes = nbytes * local // size
                except Exception:  # pragma: no cover - exotic shardings
                    pass
        total += nbytes
    return total


def live_array_bytes() -> int:
    """Bytes of every live jax array in this process."""
    import jax

    return sum(int(getattr(a, "nbytes", 0)) for a in jax.live_arrays())


def device_memory_stats() -> dict[str, int] | None:
    """Allocator stats of local device 0 (None on backends without
    them — CPU). Keys pass through from PJRT (``bytes_in_use``,
    ``peak_bytes_in_use``, ``bytes_limit``, ...)."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # pragma: no cover - backend-specific failures
        return None
    if not stats:
        return None
    return {k: int(v) for k, v in stats.items() if isinstance(v, int)}


def is_oom(exc: BaseException) -> bool:
    """Does this exception look like a device/host OOM?"""
    import re

    text = f"{type(exc).__name__}: {exc}".lower()
    return any(re.search(pat, text) for pat in _OOM_PATTERNS)


class MemoryMonitor:
    """Per-fit memory bookkeeping: breakdown at init, watermark per
    window, forensics on OOM."""

    def __init__(self, registry=None):
        self._registry = registry
        self._peak_live = 0
        self._last_live = 0
        self._last_device_stats: dict[str, int] | None = None
        self._breakdown: dict[str, int] = {}

    def _reg(self):
        return (
            self._registry
            if self._registry is not None
            else registry_mod.default_registry()
        )

    # ------------------------------------------------------------ intake

    def sample(self) -> int:
        """Poll live bytes; update the last/peak gauges. Called per log
        window (and anywhere a fresh reading is wanted)."""
        live = live_array_bytes()
        self._last_live = live
        self._peak_live = max(self._peak_live, live)
        reg = self._reg()
        reg.gauge("memory/live_bytes").set(live)
        reg.gauge("memory/peak_live_bytes").set(self._peak_live)
        stats = device_memory_stats()
        self._last_device_stats = stats
        if stats:
            if "bytes_in_use" in stats:
                reg.gauge("memory/device_bytes_in_use").set(
                    stats["bytes_in_use"]
                )
            if "peak_bytes_in_use" in stats:
                reg.gauge("memory/device_peak_bytes_in_use").set(
                    stats["peak_bytes_in_use"]
                )
        return live

    def init_breakdown(self, state) -> dict[str, int]:
        """Attribute live bytes at fit start: model vs. optimizer vs.
        non-trainable state vs. everything else."""
        sizes = (
            state.byte_breakdown()
            if hasattr(state, "byte_breakdown")
            else {
                "params": tree_bytes(getattr(state, "params", None)),
                "opt_state": tree_bytes(getattr(state, "opt_state", None)),
                "model_state": tree_bytes(
                    getattr(state, "model_state", None)
                ),
            }
        )
        live = self.sample()
        accounted = sum(sizes.values())
        breakdown = {
            "params_bytes": sizes.get("params", 0),
            "opt_bytes": sizes.get("opt_state", 0),
            "model_state_bytes": sizes.get("model_state", 0),
            "other_bytes": max(live - accounted, 0),
            "live_bytes": live,
        }
        stats = device_memory_stats()
        if stats:
            if "bytes_in_use" in stats:
                breakdown["device_bytes_in_use"] = stats["bytes_in_use"]
            if "bytes_limit" in stats:
                breakdown["device_bytes_limit"] = stats["bytes_limit"]
        self._breakdown = breakdown
        reg = self._reg()
        for key in ("params_bytes", "opt_bytes", "model_state_bytes"):
            reg.gauge(f"memory/{key}").set(breakdown[key])
        log.info(
            "memory at fit start: %.1f MiB live (params %.1f, opt %.1f, "
            "model_state %.1f, other %.1f)",
            live / 2**20,
            breakdown["params_bytes"] / 2**20,
            breakdown["opt_bytes"] / 2**20,
            breakdown["model_state_bytes"] / 2**20,
            breakdown["other_bytes"] / 2**20,
        )
        return breakdown

    # ----------------------------------------------------------- outputs

    @property
    def peak_live_bytes(self) -> int:
        return self._peak_live

    def window_fields(self) -> dict[str, int]:
        """The ``"memory"`` object for a window/final JSONL line.

        Purely cached (last ``sample()``): safe to call from the
        watchdog thread on the emergency-flush path, where a fresh
        ``jax.live_arrays()``/PJRT call could block behind the wedged
        main thread."""
        fields = {
            "live_bytes": self._last_live,
            "peak_live_bytes": self._peak_live,
        }
        stats = self._last_device_stats
        if stats and "bytes_in_use" in stats:
            fields["device_bytes_in_use"] = stats["bytes_in_use"]
        if stats and "peak_bytes_in_use" in stats:
            fields["device_peak_bytes_in_use"] = stats["peak_bytes_in_use"]
        return fields

    def oom_report(self, top: int = 15) -> str:
        """Allocation forensics: who holds the memory, right now."""
        import jax

        lines = ["== OOM allocation forensics =="]
        live = sorted(
            jax.live_arrays(),
            key=lambda a: -int(getattr(a, "nbytes", 0)),
        )
        total = sum(int(getattr(a, "nbytes", 0)) for a in live)
        lines.append(
            f"live arrays: {len(live)} holding {total / 2**20:,.1f} MiB "
            f"(run peak watermark {self._peak_live / 2**20:,.1f} MiB)"
        )
        if self._breakdown:
            b = self._breakdown
            lines.append(
                "fit-start breakdown: params %.1f MiB / opt %.1f MiB / "
                "model_state %.1f MiB / other %.1f MiB"
                % (
                    b.get("params_bytes", 0) / 2**20,
                    b.get("opt_bytes", 0) / 2**20,
                    b.get("model_state_bytes", 0) / 2**20,
                    b.get("other_bytes", 0) / 2**20,
                )
            )
        stats = device_memory_stats()
        if stats:
            lines.append(
                "device allocator: "
                + ", ".join(f"{k}={v:,}" for k, v in sorted(stats.items()))
            )
        lines.append(f"top {min(top, len(live))} live arrays by size:")
        for a in live[:top]:
            nbytes = int(getattr(a, "nbytes", 0))
            lines.append(
                f"  {nbytes / 2**20:>10,.2f} MiB  "
                f"{str(getattr(a, 'dtype', '?')):>10}  "
                f"shape {tuple(getattr(a, 'shape', ()))}"
            )
        return "\n".join(lines)


def maybe_log_oom_report(
    exc: BaseException | None, monitor: "MemoryMonitor | None"
) -> bool:
    """Fit-teardown hook: if ``exc`` is an OOM, log the forensics report
    (the exception re-raises naturally afterwards). Returns whether a
    report was logged."""
    if exc is None or monitor is None or not is_oom(exc):
        return False
    try:
        log.error("%s", monitor.oom_report())
    except Exception:  # pragma: no cover - dying anyway; best effort
        log.exception("OOM forensics report failed")
    return True
