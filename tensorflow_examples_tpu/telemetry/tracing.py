"""Per-request distributed tracing for the serving tier (ISSUE 18).

The PR-2 span machinery (telemetry/spans.py) answers "where did the
HOST loop's time go" in aggregate; this module answers it for ONE
request as it crosses processes: a ``traceparent``-style
:class:`TraceContext` (trace_id, parent span_id, sampled flag) is
minted at the router — or accepted from the client — and rides the
HTTP body of every internal leg (``/generate`` ``/prefill``
``/resume``) under the ``"trace"`` key. Each hop contributes **span
dicts** (span_id / name / start_unix / dur_s / parent_id / tags):

* the router records a root ``request`` span plus one span per
  dispatch attempt (retries, hedges, failovers — outcome-tagged) and
  per disaggregated handoff leg;
* a replica collects its per-request spans (queue_wait, prefill
  chunks, resume import, decode segments, preemptions) on the
  in-flight record and RETURNS them in the HTTP reply under
  ``"trace_spans"`` — no shared-memory assumption, so in-proc and
  process fleets stitch identically;
* the engine's compiled-step dispatches are host-side wall-clock
  spans (no device sync — the zero-recompile/zero-sync contract is
  golden-pinned).

The router's :class:`TraceRecorder` assembles the tree and applies
**tail-based sampling** at finish: every trace that is slow for its
SLO class, errored, retried, failed-over, hedged, preempted, deduped,
resumed, or brownout-capped is kept, plus a seeded deterministic
fraction of normal traffic. Kept traces land as schema-v13
``kind="trace"`` JSONL lines (one line per trace, flushed+fsynced per
append, torn-tail-tolerant read — the PR-2 sink discipline) and every
finished trace stays queryable in a bounded LRU (``GET /trace/{id}``
on the router frontend). Finishing an already-finished trace_id
MERGES spans into the stored tree — that is how a takeover-survived
request's dedupe fast path on the successor router stitches onto the
original trace via the journal-stamped trace_id.

:class:`ExemplarStore` links the aggregate view back to the causal
one: TTFT/e2e histogram observations record their trace_id, and
``/metrics`` exposes each metric's worst recent observation with its
trace_id label — from a p99 bump straight to the span tree.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
import zlib

# HTTP wire keys: the trace context rides request bodies under
# TRACE_WIRE_FIELD; a replica returns its per-request spans under
# REPLY_SPANS_FIELD (the router pops them into its recorder).
TRACE_WIRE_FIELD = "trace"
REPLY_SPANS_FIELD = "trace_spans"

# Forced-keep flags in keep_reason priority order (first present flag
# names the reason); "slow" and "seeded" are computed at finish.
KEEP_FLAGS = ("error", "failover", "retried", "hedged", "preempted",
              "deduped", "resumed", "brownout", "slow", "seeded")

# Tail-sampling slow thresholds per SLO class (seconds, client-visible
# e2e). Anything at/over its class threshold is kept.
DEFAULT_SLOW_S = {"interactive": 1.0, "batch": 30.0}

# Per-trace span cap: a runaway decode cannot grow a trace without
# bound; overflow is counted, never silently lost.
MAX_SPANS_PER_TRACE = 512


def new_trace_id() -> str:
    return os.urandom(8).hex()


def new_span_id() -> str:
    return os.urandom(4).hex()


def make_span(name: str, *, start_unix: float, dur_s: float,
              parent_id: str | None = None, span_id: str | None = None,
              tags: dict | None = None) -> dict:
    span = {
        "span_id": span_id or new_span_id(),
        "parent_id": parent_id,
        "name": str(name),
        "start_unix": float(start_unix),
        "dur_s": float(dur_s),
    }
    if tags:
        span["tags"] = dict(tags)
    return span


def close_span(name: str, t0_monotonic: float, *,
               parent_id: str | None = None, span_id: str | None = None,
               tags: dict | None = None) -> dict:
    """Span from a ``time.monotonic()`` start mark, ending NOW. The
    epoch placement back-dates ``time.time()`` by the measured
    duration, so hot paths need only the one monotonic read they
    already take."""
    dur = max(0.0, time.monotonic() - t0_monotonic)
    return make_span(name, start_unix=time.time() - dur, dur_s=dur,
                     parent_id=parent_id, span_id=span_id, tags=tags)


class TraceContext:
    """What crosses the wire: which trace, which parent span, and the
    head-sampling hint (the tail sampler has the final word)."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = bool(sampled)

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "parent_span_id": self.span_id,
                "sampled": self.sampled}

    def child(self, span_id: str) -> "TraceContext":
        """The context a callee sees: same trace, parented under the
        caller-side span that covers the call."""
        return TraceContext(self.trace_id, span_id, self.sampled)

    @classmethod
    def from_wire(cls, obj) -> "TraceContext | None":
        """Parse a body's ``"trace"`` value; None on anything malformed
        (an unparseable context must never fail the request)."""
        if not isinstance(obj, dict):
            return None
        trace_id = obj.get("trace_id")
        if not isinstance(trace_id, str) or not trace_id:
            return None
        parent = obj.get("parent_span_id")
        if parent is not None and not isinstance(parent, str):
            return None
        return cls(trace_id, parent or new_span_id(),
                   bool(obj.get("sampled", True)))


class ExemplarStore:
    """Worst-recent exemplars: per metric name, a bounded ring of
    (value, trace_id) observations; ``worst()`` is the max over the
    ring — "the slowest TTFT lately, and the trace that explains it"."""

    def __init__(self, keep: int = 128):
        self._keep = int(keep)
        self._lock = threading.Lock()
        self._recent: dict[str, collections.deque] = {}

    def record(self, name: str, value: float, trace_id: str) -> None:
        with self._lock:
            ring = self._recent.get(name)
            if ring is None:
                ring = self._recent[name] = collections.deque(
                    maxlen=self._keep
                )
            ring.append((float(value), str(trace_id)))

    def worst(self) -> dict:
        """{metric name: (value, trace_id)} — each name's worst recent
        observation."""
        with self._lock:
            return {
                name: max(ring)
                for name, ring in self._recent.items() if ring
            }


class TraceRecorder:
    """Per-process trace assembly + tail sampling + the v13 sink.

    One recorder lives wherever traces FINISH (the router; serve_bench
    when it drives replicas directly). Replicas don't need one — they
    return span dicts in their replies.
    """

    def __init__(self, *, registry=None, path: str | None = None,
                 sample_fraction: float = 0.01, slow_s: dict | None = None,
                 seed: int = 0, keep_traces: int = 256,
                 max_spans: int = MAX_SPANS_PER_TRACE):
        # None = resolve default_registry() per record (Tracer's rule),
        # so a recorder made before reset_default_registry() still
        # lands in the live one.
        self._registry = registry
        self.sample_fraction = float(sample_fraction)
        self.slow_s = dict(DEFAULT_SLOW_S)
        if slow_s:
            self.slow_s.update(slow_s)
        self.seed = int(seed)
        self._max_spans = int(max_spans)
        self._lock = threading.Lock()
        # open traces: trace_id -> {"spans": [...], "dropped": n}
        self._open: dict[str, dict] = {}
        # finished traces, merged by trace_id (the /trace/{id} window
        # and the dedupe/takeover stitch point).
        self._done: collections.OrderedDict[str, dict] = (
            collections.OrderedDict()
        )
        self._keep_traces = int(keep_traces)
        self.exemplars = ExemplarStore()
        self._t_session = time.time()
        self.path = path
        self._file = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._file = open(path, "a")

    # ------------------------------------------------------------ registry

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from tensorflow_examples_tpu.telemetry import registry as _reg

        return _reg.default_registry()

    # ------------------------------------------------------------- record

    def new_context(self, wire=None) -> TraceContext:
        """Accept a client-supplied wire context, or mint a fresh one;
        either way the trace is now OPEN here and the returned
        context's span_id is the root ``request`` span's id."""
        ctx = TraceContext.from_wire(wire) if wire is not None else None
        if ctx is None:
            ctx = TraceContext(new_trace_id(), new_span_id(), True)
        with self._lock:
            self._open.setdefault(
                ctx.trace_id, {"spans": [], "dropped": 0}
            )
        self._reg().counter("trace/started_total").inc(1)
        return ctx

    def add_span(self, trace_id: str, span: dict) -> None:
        with self._lock:
            rec = self._open.setdefault(
                trace_id, {"spans": [], "dropped": 0}
            )
            if len(rec["spans"]) >= self._max_spans:
                rec["dropped"] += 1
                overflowed = True
            else:
                rec["spans"].append(span)
                overflowed = False
        if overflowed:
            self._reg().counter("trace/spans_dropped_total").inc(1)

    @contextlib.contextmanager
    def span(self, trace_id: str, name: str, *,
             parent_id: str | None = None, tags: dict | None = None):
        """Measure a router-side leg; yields the span dict so the body
        can set outcome tags (``span['tags']['status'] = ...``) before
        it is recorded."""
        span = {
            "span_id": new_span_id(),
            "parent_id": parent_id,
            "name": str(name),
            "start_unix": time.time(),
            "dur_s": 0.0,
            "tags": dict(tags or {}),
        }
        t0 = time.monotonic()
        try:
            yield span
        finally:
            span["dur_s"] = max(0.0, time.monotonic() - t0)
            if not span["tags"]:
                span.pop("tags")
            self.add_span(trace_id, span)

    def adopt(self, old_id: str, new_id: str) -> None:
        """Re-key an OPEN trace: move its collected spans under
        ``new_id`` and drop the old entry. The dedupe fast path uses
        this — a duplicate request opened its own fresh trace before
        the journal revealed the original's trace_id, and its spans
        belong on the ORIGINAL tree, not a fork."""
        if old_id == new_id:
            return
        with self._lock:
            rec = self._open.pop(old_id, None)
            if rec is None:
                return
            dst = self._open.setdefault(
                new_id, {"spans": [], "dropped": 0}
            )
            dst["spans"].extend(rec["spans"])
            dst["dropped"] += rec["dropped"]

    def ingest(self, trace_id: str, spans, *,
               parent_id: str | None = None) -> int:
        """Adopt span dicts returned by a replica reply; top-level ones
        (no parent) are parented under the dispatch span that carried
        them. Malformed entries are dropped, never raised — a bad
        reply field must not fail the request."""
        added = 0
        for span in spans if isinstance(spans, (list, tuple)) else ():
            if not isinstance(span, dict):
                continue
            if not isinstance(span.get("span_id"), str) \
                    or not isinstance(span.get("name"), str):
                continue
            try:
                start = float(span["start_unix"])
                dur = float(span["dur_s"])
            except (KeyError, TypeError, ValueError):
                continue
            adopted = make_span(
                span["name"], start_unix=start, dur_s=dur,
                parent_id=span.get("parent_id") or parent_id,
                span_id=span["span_id"],
                tags=span.get("tags")
                if isinstance(span.get("tags"), dict) else None,
            )
            self.add_span(trace_id, adopted)
            added += 1
        return added

    # ------------------------------------------------------------- finish

    def _seeded_keep(self, trace_id: str) -> bool:
        """Deterministic head-fraction: hash of (trace_id, seed) — the
        same trace keeps or drops identically on every router that
        finishes it."""
        if self.sample_fraction >= 1.0:
            return True
        if self.sample_fraction <= 0.0:
            return False
        h = zlib.crc32(f"{trace_id}:{self.seed}".encode()) % 1_000_000
        return h < self.sample_fraction * 1_000_000

    def finish(self, trace_id: str, *, slo: str = "interactive",
               status: int = 200, e2e_s: float = 0.0, flags=()) -> dict:
        """Close the trace: tail-sample, merge into any earlier
        finish of the same trace_id (the stitch), bank the v13 line
        when kept, and return the merged trace doc."""
        with self._lock:
            rec = self._open.pop(trace_id, None)
        spans = list(rec["spans"]) if rec else []
        dropped_spans = rec["dropped"] if rec else 0
        flags = set(flags)
        if status != 200:
            flags.add("error")
        for span in spans:
            tags = span.get("tags") or {}
            if tags.get("preempted"):
                flags.add("preempted")
            if tags.get("brownout_level"):
                flags.add("brownout")
        slow_at = self.slow_s.get(slo, max(self.slow_s.values()))
        if e2e_s >= slow_at:
            flags.add("slow")
        if self._seeded_keep(trace_id):
            flags.add("seeded")
        keep = bool(flags)
        keep_reason = next(
            (f for f in KEEP_FLAGS if f in flags), "sampled_out"
        )
        with self._lock:
            prior = self._done.pop(trace_id, None)
            if prior is not None:
                # The stitch: a later finish of the same trace_id (a
                # dedupe hit on the successor router, a resumed
                # stream) joins the stored tree instead of forking it.
                seen = {s["span_id"] for s in prior["spans"]}
                spans = prior["spans"] + [
                    s for s in spans if s["span_id"] not in seen
                ]
                flags |= set(prior.get("flags", ()))
                e2e_s = max(e2e_s, prior.get("e2e_s", 0.0))
                status = prior["status"] if prior["status"] != 200 \
                    else status
                dropped_spans += prior.get("spans_dropped", 0)
                keep = keep or prior.get("kept", False)
                keep_reason = next(
                    (f for f in KEEP_FLAGS if f in flags), keep_reason
                )
            spans.sort(key=lambda s: s["start_unix"])
            doc = {
                "trace_id": trace_id,
                "slo": str(slo),
                "status": int(status),
                "e2e_s": float(e2e_s),
                "keep_reason": keep_reason,
                "flags": sorted(flags),
                "kept": keep,
                "spans": spans,
            }
            if dropped_spans:
                doc["spans_dropped"] = dropped_spans
            self._done[trace_id] = doc
            while len(self._done) > self._keep_traces:
                self._done.popitem(last=False)
        reg = self._reg()
        if keep:
            reg.counter("trace/kept_total").inc(1)
            self._write_line(doc)
        else:
            reg.counter("trace/dropped_total").inc(1)
        if "slow" in flags:
            reg.counter("trace/slow_total").inc(1)
        return doc

    def _write_line(self, doc: dict) -> None:
        if self._file is None:
            return
        from tensorflow_examples_tpu.telemetry import schema

        line = {
            "schema_version": schema.SERVING_SCHEMA_VERSION,
            "kind": "trace",
            "step": 0,
            "time_unix": time.time(),
            "session_start_unix": self._t_session,
            "host": 0,
            "metrics": {},
            "counters": {},
            "gauges": {},
            "derived": {},
            "trace": {k: v for k, v in doc.items() if k != "kept"},
        }
        with self._lock:
            if self._file is None:
                return
            # One trace per line, flushed and fsynced per append (the
            # PR-2 sink discipline): a crash tears at most the tail
            # line, which readers tolerate.
            self._file.write(json.dumps(line) + "\n")
            self._file.flush()
            os.fsync(self._file.fileno())

    # ------------------------------------------------------------ inspect

    def get(self, trace_id: str) -> dict | None:
        """The finished (merged) trace doc, or an ``"open": True``
        partial for a request still in flight, or None."""
        with self._lock:
            doc = self._done.get(trace_id)
            if doc is not None:
                return json.loads(json.dumps(doc))
            rec = self._open.get(trace_id)
            if rec is not None:
                return {
                    "trace_id": trace_id,
                    "open": True,
                    "spans": json.loads(json.dumps(rec["spans"])),
                }
        return None

    def stats(self) -> dict:
        """The v13 serving-line keys (the router's stats_line stamps
        exactly these)."""
        counters = self._reg().counter_values()
        kept = int(counters.get("trace/kept_total", 0))
        dropped = int(counters.get("trace/dropped_total", 0))
        total = kept + dropped
        return {
            "traces_kept": kept,
            "traces_dropped": dropped,
            "trace_coverage": (kept / total) if total else 0.0,
            "slow_trace_count": int(counters.get("trace/slow_total", 0)),
        }

    def close(self) -> None:
        with self._lock:
            f, self._file = self._file, None
        if f is not None:
            f.close()


def read_traces(path: str) -> dict[str, dict]:
    """Load a traces JSONL file into {trace_id: merged trace doc}.

    Torn-tail tolerant (an unparseable line — the one a crash can
    tear — is skipped, never raised) and MERGES lines sharing a
    trace_id: a takeover-survived request leaves one line from each
    router, and the reader is where they become one tree."""
    merged: dict[str, dict] = {}
    try:
        f = open(path)
    except OSError:
        return merged
    with f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                line = json.loads(raw)
            except ValueError:
                continue
            if not isinstance(line, dict) or line.get("kind") != "trace":
                continue
            trace = line.get("trace")
            if not isinstance(trace, dict) or not isinstance(
                trace.get("trace_id"), str
            ):
                continue
            tid = trace["trace_id"]
            prior = merged.get(tid)
            if prior is None:
                merged[tid] = dict(
                    trace, spans=list(trace.get("spans") or [])
                )
                continue
            seen = {
                s.get("span_id") for s in prior["spans"]
                if isinstance(s, dict)
            }
            for span in trace.get("spans") or []:
                if isinstance(span, dict) \
                        and span.get("span_id") not in seen:
                    prior["spans"].append(span)
            prior["e2e_s"] = max(
                prior.get("e2e_s", 0.0), trace.get("e2e_s", 0.0)
            )
            if prior.get("status", 200) == 200:
                prior["status"] = trace.get("status", 200)
            prior["spans"].sort(
                key=lambda s: s.get("start_unix", 0.0)
            )
    return merged
