"""Fleet observability: per-host skew, straggler attribution (ISSUE 4).

At pod scale the failure mode that matters is *one slow host*, not a
slow mean (arXiv:1909.09756: per-replica skew and input-pipeline
stragglers dominate TPU-v3 pod scaling). The single-process telemetry
stack (hub.py) can only see this host; everything cross-host it had was
a counter sum. This module adds the fleet view:

* Every log window, each host builds a SMALL FIXED VECTOR of its own
  health numbers — step-time p50/p95, data-fetch p95, steps lost,
  live-memory peak watermark — and the fleet allgathers them
  (``multihost_utils.process_allgather``; fixed shape on every process,
  so the collective can never diverge).
* Every host then derives the identical fleet summary: per-host
  breakdown, the slowest host (step-time p95 argmax), the **skew
  ratio** (slowest p95 / fleet median p95), and — when the ratio
  crosses ``TrainConfig.straggler_skew_factor`` — a straggler verdict
  with **side attribution**: input-side if the host's input-pipeline
  excess explains its step-time excess, compute-side otherwise (slow
  chip, thermal throttle, a host busy elsewhere). The input signal is
  ``data_work`` p95 — host time actually spent producing batches
  (ISSUE 6) — not ``data_fetch``, which also counts queue
  back-pressure wait and would blame a fast host blocked on the
  device; ``data_fetch`` p95 remains in the vector as the legacy
  fallback for peers that predate the split.
* The summary lands as a ``kind="fleet"`` schema-v3 JSONL line (host
  0's metrics.jsonl is the run record; every host's shard carries it
  too), and the straggler verdict is logged at WARNING on host 0
  naming the host and the side.

Single-process runs emit the same line with a one-host fleet — the
whole path (vector, summary, schema, report rendering) stays exercised
in CPU CI, and the collective is skipped entirely.

The watchdog-fatal path calls ``snapshot()`` instead of ``gather()``:
the dying host must never enter a collective its peers may not reach,
so the emergency fleet line replays the last gathered summary (marked
``"emergency": true``) with no cross-host traffic.
"""

from __future__ import annotations

import logging
import math
from typing import Callable, Mapping

import numpy as np

from tensorflow_examples_tpu.telemetry import registry as registry_mod
from tensorflow_examples_tpu.telemetry import schema

log = logging.getLogger(__name__)

# The allgathered per-host vector, in order. FIXED SET: the collective
# must have identical shape on every process (same rule as
# hub.HOST_LOCAL_COUNTERS). Absent values travel as NaN. Aliases the
# schema's per-host key contract so writer and validator cannot drift.
VECTOR_KEYS = schema.FLEET_VECTOR_KEYS

# Side attribution: the straggler is input-side when its input-pipeline
# excess (vs the fleet median) covers at least this fraction of its
# step-time excess — the input side IS the stall; otherwise
# compute-side. The input signal is ``data_work`` p95 (host time
# actually spent producing batches, ISSUE 6) when the host reported
# one, falling back to ``data_fetch`` p95 for pre-ISSUE-6 peers —
# data_fetch also counts queue back-pressure wait, which used to tag a
# fast host blocked on the device as "input-side".
INPUT_SIDE_FRACTION = 0.5


def _finite_median(vals: np.ndarray) -> float:
    finite = vals[np.isfinite(vals)]
    return float(np.median(finite)) if finite.size else float("nan")


def _num(v: float) -> float | int | None:
    """NaN (the wire encoding of 'absent') -> None for the JSONL line."""
    if not math.isfinite(v):
        return None
    return int(v) if float(v).is_integer() else float(v)


class FleetMonitor:
    """Per-fit fleet bookkeeping: one ``gather()`` per log window, a
    collective-free cached ``snapshot()`` for emergency paths."""

    def __init__(
        self,
        *,
        skew_factor: float = 2.0,
        registry=None,
        allgather: Callable[[np.ndarray], np.ndarray] | None = None,
        process_index: int | None = None,
        process_count: int | None = None,
    ):
        self.skew_factor = float(skew_factor)
        self._registry = registry
        # Injectable for the mocked-allgather tests; None = the real
        # multihost_utils collective (resolved lazily — single-process
        # runs never import it).
        self._allgather = allgather
        self._process_index = process_index
        self._process_count = process_count
        self._last: dict | None = None  # cached summary (emergency path)
        self._warned_hosts: set[int] = set()  # one warning per straggler

    @classmethod
    def from_config(cls, cfg) -> "FleetMonitor":
        return cls(
            skew_factor=float(
                getattr(cfg, "straggler_skew_factor", 2.0) or 0.0
            ),
        )

    # ------------------------------------------------------------- intake

    def _reg(self):
        return (
            self._registry
            if self._registry is not None
            else registry_mod.default_registry()
        )

    def _topology(self) -> tuple[int, int]:
        if self._process_index is not None and self._process_count is not None:
            return self._process_index, self._process_count
        import jax

        return jax.process_index(), jax.process_count()

    def local_vector(self, counters: Mapping[str, int]) -> np.ndarray:
        """This host's health vector (``VECTOR_KEYS`` order, NaN =
        absent). ``counters`` must be the LOCAL (pre-reduction)
        fit-delta counters: io_retries and batches_skipped are exactly
        the entries the cross-host reduction replaces with fleet sums,
        and their per-host values are what localizes a flaky host."""
        reg = self._reg()
        step_p50, step_p95 = reg.histogram("step_time").percentiles(50, 95)
        (fetch_p95,) = reg.histogram("span/data_fetch").percentiles(95)
        (work_p95,) = reg.histogram("span/data_work").percentiles(95)
        peak = reg.gauge("memory/peak_live_bytes").value
        nan = float("nan")
        # float32: the collective goes through jnp, and the default JAX
        # config silently downcasts f64 anyway — be explicit. Watermark
        # bytes lose sub-KiB precision at GiB scale, which is noise at
        # the granularity skew attribution works at.
        return np.asarray(
            [
                step_p50 if step_p50 is not None else nan,
                step_p95 if step_p95 is not None else nan,
                fetch_p95 if fetch_p95 is not None else nan,
                float(counters.get("resilience/steps_lost", 0)),
                float(peak) if peak is not None else nan,
                float(counters.get("io/retries", 0)),
                float(counters.get("data/batches_skipped", 0)),
                work_p95 if work_p95 is not None else nan,
            ],
            np.float32,
        )

    # ------------------------------------------------------------ summary

    def gather(self, counters: Mapping[str, int]) -> dict:
        """Allgather every host's vector and derive the fleet summary.

        COLLECTIVE (when process_count > 1): must be called at the same
        point on every process — the cadenced window path only, never an
        abnormal-exit path (use ``snapshot()`` there).
        """
        vec = self.local_vector(counters)
        index, count = self._topology()
        if count > 1:
            gather = self._allgather
            if gather is None:
                from jax.experimental import multihost_utils

                gather = multihost_utils.process_allgather
            matrix = np.asarray(gather(vec), np.float64).reshape(
                count, len(VECTOR_KEYS)
            )
        else:
            matrix = vec[None, :]
        summary = self._summarize(matrix)
        self._last = summary
        if summary["straggler"] and index == 0:
            self._warn(summary)
        return summary

    def _summarize(self, matrix: np.ndarray) -> dict:
        hosts = [
            {"host": h, **{k: _num(row[i]) for i, k in enumerate(VECTOR_KEYS)}}
            for h, row in enumerate(matrix)
        ]
        p95 = matrix[:, VECTOR_KEYS.index("step_time_p95")]
        fetch = matrix[:, VECTOR_KEYS.index("data_fetch_p95")]
        work = matrix[:, VECTOR_KEYS.index("data_work_p95")]
        # Input-side evidence per host: time actually spent PRODUCING
        # batches (data_work) when reported; data_fetch (which also
        # counts queue back-pressure wait) only as the pre-ISSUE-6
        # fallback — a fast host blocked on the device must not read
        # as input-bound.
        input_sig = np.where(np.isfinite(work), work, fetch)
        summary: dict = {
            "hosts": hosts,
            "slowest_host": None,
            "skew": None,
            "side": None,
            "straggler": False,
        }
        if not np.isfinite(p95).any():
            return summary  # pre-first-window: no step times yet
        slowest = int(np.nanargmax(p95))
        # The skew baseline EXCLUDES the slowest host: in a small fleet
        # the straggler would otherwise dilute its own denominator (a
        # 5x-slow host in a 2-host fleet reads as 1.7x against the
        # all-host median). One-host fleets fall back to themselves.
        others = np.delete(p95, slowest)
        median_p95 = _finite_median(others if others.size else p95)
        summary["slowest_host"] = slowest
        if median_p95 > 0 and math.isfinite(p95[slowest]):
            skew = float(p95[slowest] / median_p95)
            summary["skew"] = skew
            others_sig = np.delete(input_sig, slowest)
            summary["side"] = self._attribute_side(
                p95[slowest], median_p95, input_sig[slowest],
                _finite_median(
                    others_sig if others_sig.size else input_sig
                ),
            )
            summary["straggler"] = (
                self.skew_factor > 0
                and len(hosts) > 1
                and skew >= self.skew_factor
            )
        return summary

    @staticmethod
    def _attribute_side(
        host_p95: float,
        median_p95: float,
        host_input: float,
        median_input: float,
    ) -> str:
        """Compute- vs input-side: does the host's input-pipeline excess
        explain its step-time excess? The input signal is data_work p95
        (host time producing batches) with data_fetch p95 as the legacy
        fallback — see ``input_sig`` in ``_summarize``. An input-starved
        host inflates BOTH the step clock and its input signal; a slow
        chip inflates only the step time."""
        step_excess = max(host_p95 - median_p95, 0.0)
        if not math.isfinite(host_input):
            return "compute"  # no input evidence: blame the device side
        base_input = median_input if math.isfinite(median_input) else 0.0
        input_excess = max(host_input - base_input, 0.0)
        if step_excess <= 0:
            return "compute"
        return (
            "input"
            if input_excess >= INPUT_SIDE_FRACTION * step_excess
            else "compute"
        )

    def _warn(self, summary: dict) -> None:
        host = summary["slowest_host"]
        if host in self._warned_hosts:
            return  # one warning per straggling host per fit
        self._warned_hosts.add(host)
        entry = summary["hosts"][host]
        work = entry.get("data_work_p95")
        log.warning(
            "FLEET STRAGGLER: host %d step-time p95 %.4fs is %.2fx the "
            "fleet median (skew threshold %.2f) — %s-side (data-work "
            "p95 %s, data-fetch p95 %s)",
            host,
            entry["step_time_p95"] or float("nan"),
            summary["skew"],
            self.skew_factor,
            summary["side"],
            f"{work:.4f}s" if work is not None else "n/a",
            f"{entry['data_fetch_p95']:.4f}s"
            if entry["data_fetch_p95"] is not None
            else "n/a",
        )

    # ---------------------------------------------------------- emergency

    def snapshot(self, counters: Mapping[str, int] | None = None) -> dict:
        """A collective-free fleet payload for abnormal-exit paths: the
        last gathered summary when one exists (peers' numbers as of the
        last healthy window — exactly the forensics a hung run needs),
        else this host alone (``counters`` = the caller's fit-delta
        counters, so steps_lost is real even when the run wedged before
        its first window). Never blocks, never enters a collective."""
        if self._last is not None:
            return dict(self._last, emergency=True)
        try:
            index, _ = self._topology()
        except Exception:  # pragma: no cover - dying anyway; best effort
            index = 0
        vec = self.local_vector(counters or {})
        hosts = [
            {
                "host": index,
                **{k: _num(vec[i]) for i, k in enumerate(VECTOR_KEYS)},
            }
        ]
        return {
            "hosts": hosts,
            "slowest_host": None,
            "skew": None,
            "side": None,
            "straggler": False,
            "emergency": True,
        }
