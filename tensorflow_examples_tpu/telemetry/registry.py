"""Process-local metrics registry: counters, gauges, time-histograms.

The one place every runtime layer publishes its numbers into (ISSUE 2
tentpole (a)): the prefetch pipeline counts skipped poisoned batches,
``retry_io`` counts IO retries, the bad-step guard counts skipped/rolled
back steps, the checkpoint manager counts saves — and the ``Telemetry``
window writer (telemetry/hub.py) snapshots everything into each JSONL
line, so the PR 1 resilience events stop being write-only log text.

Design constraints, in order:

* **Cheap on the happy path.** An increment is a dict lookup (cached at
  the call site via the returned instrument handle) + one locked int
  add. No per-element work, no allocation.
* **Thread-safe.** Instruments are hit from the training loop, the
  prefetch generator, and the watchdog thread.
* **Cumulative.** Counters are monotonic for the life of the process.
  The ``Telemetry`` hub (hub.py) snapshots them at fit start and emits
  per-fit DELTAS, so each emitted session is self-contained; within a
  session consumers diff windows for rates, and a torn/partial final
  window is harmless — the previous line still carries a consistent
  prefix of the run.

A module-level default registry mirrors ``logging``'s root-logger
pattern: library code (data/prefetch.py, utils/faults.py, …) publishes
into ``default_registry()`` without plumbing a handle through every
call; the trainer's ``Telemetry`` drains the same instance. Tests use
``reset_default_registry()`` for isolation.
"""

from __future__ import annotations

import collections
import math
import threading
from typing import Mapping


class Counter:
    """Monotonic cumulative counter."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0  # guard: self._lock
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: inc({n}) must be >= 0")
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value  # graftlint: ignore — atomic int load; a
        # snapshot read concurrent with inc() sees either value, both
        # consistent (monotonic counter)


class Gauge:
    """Last-write-wins instantaneous value. Lockless BY DESIGN: a
    gauge store is a single reference assignment (atomic under the
    GIL) and concurrent setters racing is the semantics, not a bug —
    the graftlint ignores below record that decision where the
    ``_value`` annotation on Counter/TimeHistogram would otherwise
    flag these same-named accesses."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value: float | None = None  # graftlint: ignore — lockless by design

    def set(self, v: float) -> None:
        self._value = float(v)  # graftlint: ignore — atomic ref store

    @property
    def value(self) -> float | None:
        return self._value  # graftlint: ignore — atomic ref load


def _nearest_rank(sorted_samples: list[float], q: float) -> float | None:
    """Nearest-rank percentile (q in [0, 100]) over pre-sorted samples."""
    if not sorted_samples:
        return None
    rank = max(int(math.ceil(q / 100.0 * len(sorted_samples))) - 1, 0)
    return sorted_samples[min(rank, len(sorted_samples) - 1)]


class TimeHistogram:
    """Duration distribution: running count/sum/min/max plus a bounded
    sample window for percentiles.

    Exact aggregates are kept for the whole run; percentiles are
    computed over the most recent ``max_samples`` observations (a
    training run's step-time distribution is what you want *recently*,
    and an unbounded sample list would grow without limit on a
    multi-week run).
    """

    __slots__ = ("name", "count", "total", "min", "max", "_samples", "_lock")

    def __init__(self, name: str, *, max_samples: int = 8192):
        self.name = name
        self.count = 0     # guard: self._lock
        self.total = 0.0   # guard: self._lock
        self.min = math.inf   # guard: self._lock
        self.max = -math.inf  # guard: self._lock
        self._samples: collections.deque = collections.deque(  # guard: self._lock
            maxlen=max_samples
        )
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        s = float(seconds)
        with self._lock:
            self.count += 1
            self.total += s
            self.min = min(self.min, s)
            self.max = max(self.max, s)
            self._samples.append(s)

    def percentile(self, q: float) -> float | None:
        """Nearest-rank percentile (q in [0, 100]) over the sample window."""
        with self._lock:
            samples = sorted(self._samples)
        return _nearest_rank(samples, q)

    def percentiles(self, *qs: float) -> tuple[float | None, ...]:
        """Several percentiles in ONE lock acquisition + sort (the fleet
        vector and the /metrics endpoint read p50+p95 together every
        window — don't pay the sort twice)."""
        with self._lock:
            samples = sorted(self._samples)
        return tuple(_nearest_rank(samples, q) for q in qs)

    def summary(self) -> dict:
        with self._lock:
            n, total = self.count, self.total
            lo = self.min if n else None
            hi = self.max if n else None
            samples = sorted(self._samples)
        return {
            "count": n,
            "total": total,
            "mean": (total / n) if n else None,
            "min": lo,
            "max": hi,
            "p50": _nearest_rank(samples, 50),
            "p95": _nearest_rank(samples, 95),
            "p99": _nearest_rank(samples, 99),
        }


class MetricsRegistry:
    """Namespace of instruments; get-or-create by name, snapshot as dicts."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}  # guard: self._lock
        self._gauges: dict[str, Gauge] = {}      # guard: self._lock
        self._histograms: dict[str, TimeHistogram] = {}  # guard: self._lock

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str, **kw) -> TimeHistogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = TimeHistogram(name, **kw)
            return h

    # ----------------------------------------------------------- snapshots

    def counter_values(self) -> dict[str, int]:
        with self._lock:
            counters = list(self._counters.values())
        return {c.name: c.value for c in counters}

    def gauge_values(self) -> dict[str, float]:
        with self._lock:
            gauges = list(self._gauges.values())
        return {g.name: g.value for g in gauges if g.value is not None}

    def histogram_summaries(self) -> dict[str, dict]:
        with self._lock:
            hists = list(self._histograms.values())
        return {h.name: h.summary() for h in hists}

    def snapshot(self) -> dict:
        return {
            "counters": self.counter_values(),
            "gauges": self.gauge_values(),
            "histograms": self.histogram_summaries(),
        }

    def merge_counter_values(self, values: Mapping[str, int]) -> None:
        """Fold an external counter snapshot into this registry —
        offline aggregation (e.g. combining per-session or per-host
        snapshots in analysis code). The in-loop cross-host reduction
        (Telemetry._reduced_counters) is collective-based and does not
        go through here."""
        for name, v in values.items():
            self.counter(name).inc(int(v))


_default: MetricsRegistry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry library code publishes into."""
    return _default


def reset_default_registry() -> MetricsRegistry:
    """Fresh default registry (test isolation); returns the new one."""
    global _default
    _default = MetricsRegistry()
    return _default
