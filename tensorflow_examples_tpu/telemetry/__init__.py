"""Telemetry subsystem (ISSUE 2 host side, ISSUE 3 device side):
metrics registry, span tracer, pluggable sinks, derived
throughput/MFU/goodput accounting, recompilation sentinel, memory
accounting, and in-loop profiler windows.

See docs/observability.md for the architecture and file formats.

Layer map:

* ``registry``    — process-local counters/gauges/time-histograms every
                    runtime layer publishes into (``default_registry()``).
* ``spans``       — ``with span("data_fetch")`` host timeline; Chrome
                    trace export; open-span introspection for watchdog
                    hang dumps.
* ``sinks``       — JSONL (crash-safe append), clu/TensorBoard (explicit
                    null-writer fallback), console.
* ``accounting``  — examples/sec, 6ND model-FLOPs MFU (+ observed duty
                    cycle), goodput math.
* ``schema``      — the self-describing JSONL line schema + validator
                    (v2: memory / compile_warning / profile fields).
* ``compilation`` — recompilation sentinel around the jitted step fns:
                    compile counts/spans + post-warmup recompile
                    warnings naming the shape/dtype delta.
* ``memory``      — HBM/host memory accounting: init breakdown, peak
                    watermark gauge, OOM allocation forensics.
* ``profiling``   — programmable one-shot ``jax.profiler`` windows
                    (TrainConfig ``profile_start_step``/``num_steps``/
                    ``dir``) cross-linked from the run's final line.
* ``fleet``       — per-host health-vector allgather, slowest-host /
                    skew-ratio attribution, ``kind="fleet"`` lines and
                    the straggler warning (ISSUE 4).
* ``serve``       — the opt-in per-process /metrics (Prometheus text),
                    /health, /window HTTP endpoints (ISSUE 4).
* ``hub``         — the ``Telemetry`` object the trainer owns, tying the
                    above together per run.
"""

from tensorflow_examples_tpu.telemetry.accounting import (  # noqa: F401
    goodput,
    mfu,
    peak_flops_per_device,
    train_step_flops,
)
from tensorflow_examples_tpu.telemetry.compilation import (  # noqa: F401
    CompilationSentinel,
)
from tensorflow_examples_tpu.telemetry.fleet import (  # noqa: F401
    FleetMonitor,
)
from tensorflow_examples_tpu.telemetry.hub import Telemetry  # noqa: F401
from tensorflow_examples_tpu.telemetry.memory import (  # noqa: F401
    MemoryMonitor,
    live_array_bytes,
    tree_bytes,
)
from tensorflow_examples_tpu.telemetry.profiling import (  # noqa: F401
    ProfilerWindow,
)
from tensorflow_examples_tpu.telemetry.registry import (  # noqa: F401
    MetricsRegistry,
    default_registry,
    reset_default_registry,
)
from tensorflow_examples_tpu.telemetry.schema import (  # noqa: F401
    SCHEMA_VERSION,
    validate_line,
)
from tensorflow_examples_tpu.telemetry.serve import (  # noqa: F401
    MetricsServer,
    render_prometheus,
)
from tensorflow_examples_tpu.telemetry.spans import (  # noqa: F401
    Tracer,
    active_span_names,
    default_tracer,
    reset_default_tracer,
    span,
)
