"""Telemetry subsystem (ISSUE 2): metrics registry, span tracer,
pluggable sinks, derived throughput/MFU/goodput accounting.

See docs/observability.md for the architecture and file formats.

Layer map:

* ``registry``   — process-local counters/gauges/time-histograms every
                   runtime layer publishes into (``default_registry()``).
* ``spans``      — ``with span("data_fetch")`` host timeline; Chrome
                   trace export; open-span introspection for watchdog
                   hang dumps.
* ``sinks``      — JSONL (crash-safe append), clu/TensorBoard (explicit
                   null-writer fallback), console.
* ``accounting`` — examples/sec, 6ND model-FLOPs MFU, goodput math.
* ``schema``     — the self-describing JSONL line schema + validator.
* ``hub``        — the ``Telemetry`` object the trainer owns, tying the
                   above together per run.
"""

from tensorflow_examples_tpu.telemetry.accounting import (  # noqa: F401
    goodput,
    mfu,
    peak_flops_per_device,
    train_step_flops,
)
from tensorflow_examples_tpu.telemetry.hub import Telemetry  # noqa: F401
from tensorflow_examples_tpu.telemetry.registry import (  # noqa: F401
    MetricsRegistry,
    default_registry,
    reset_default_registry,
)
from tensorflow_examples_tpu.telemetry.schema import (  # noqa: F401
    SCHEMA_VERSION,
    validate_line,
)
from tensorflow_examples_tpu.telemetry.spans import (  # noqa: F401
    Tracer,
    active_span_names,
    default_tracer,
    reset_default_tracer,
    span,
)
