"""Pluggable metric sinks (ISSUE 2 tentpole (c)).

One ``Telemetry`` object fans each window line out to every configured
sink (``TrainConfig.telemetry_sinks``):

* ``jsonl``       — the always-on machine record: one schema-versioned
                    line per log window appended to
                    ``workdir/telemetry/metrics.jsonl``. Crash-safe by
                    construction: append-only, flushed per write, so the
                    file is valid up to the last completed line no
                    matter how the process dies. Process 0 only.
* ``tensorboard`` — the existing clu ``metric_writers`` path. Import or
                    construction failure degrades to an explicit NULL
                    writer with a ONE-TIME warning naming the failure
                    (replacing train/loop.py's old silent
                    ``except Exception: return None``).
* ``console``     — the historical ``log.info("step N: {...}")`` line.

Sinks receive the full schema line (telemetry/schema.py) and pick what
they render; they must never raise into the training loop.
"""

from __future__ import annotations

import json
import logging
import os

log = logging.getLogger(__name__)

SINK_NAMES = ("jsonl", "tensorboard", "console")


class Sink:
    """Interface: write one schema line; flush/close are idempotent."""

    def write(self, line: dict) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self.flush()


class JsonlSink(Sink):
    """Append-only JSONL, flushed per line (a crash loses at most the
    line being written — never previously-written windows)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a")  # noqa: SIM115 - outlives the call

    def write(self, line: dict) -> None:
        self._f.write(json.dumps(line) + "\n")
        self._f.flush()

    def flush(self) -> None:
        if not self._f.closed:
            self._f.flush()
            try:
                os.fsync(self._f.fileno())
            except OSError:  # pragma: no cover - fs without fsync
                pass

    def close(self) -> None:
        if not self._f.closed:
            self.flush()
            self._f.close()


# Line kinds the scalar-oriented sinks (console, tensorboard) render;
# device-side snapshot kinds (memory, compile_warning — schema v2) are
# JSONL-record material and already logged by their producers.
_SCALAR_KINDS = ("window", "eval", "final")


class ConsoleSink(Sink):
    """The historical human-readable log line, one per window."""

    def write(self, line: dict) -> None:
        if line.get("kind", "window") not in _SCALAR_KINDS:
            return
        shown = {k: round(v, 5) for k, v in line["metrics"].items()
                 if v is not None}
        log.info("step %d: %s", line["step"], shown)


_tb_warned = False  # one-time per process: don't spam every window


class TensorBoardSink(Sink):
    """clu metric_writers, degrading to an explicit null writer.

    The old ``Trainer._make_writer`` swallowed every exception silently
    — a broken clu install meant a run with NO TensorBoard output and no
    hint why. Here the failure is named once at WARNING and the sink
    becomes an inert null writer, keeping the loop alive either way.
    """

    def __init__(self, workdir: str):
        global _tb_warned
        self._writer = None
        try:
            import jax
            from clu import metric_writers

            self._writer = metric_writers.create_default_writer(
                workdir, just_logging=jax.process_index() != 0
            )
        except Exception as e:
            if not _tb_warned:
                _tb_warned = True
                log.warning(
                    "TensorBoard sink unavailable — falling back to a null "
                    "writer (scalars will NOT reach TensorBoard). Cause: "
                    "%s: %s",
                    type(e).__name__,
                    e,
                )

    def write(self, line: dict) -> None:
        if self._writer is None:
            return
        if line.get("kind", "window") not in _SCALAR_KINDS:
            # A mid-run memory/compile_warning line would re-write the
            # whole derived scalar set at its step, duplicating (or
            # reordering against) the adjacent window line.
            return
        scalars = {
            k: v for k, v in line["metrics"].items() if v is not None
        }
        scalars.update(
            {
                f"telemetry/{k}": v
                for k, v in line["derived"].items()
                if v is not None
            }
        )
        if scalars:
            self._writer.write_scalars(line["step"], scalars)

    def flush(self) -> None:
        if self._writer is not None:
            self._writer.flush()


def telemetry_dir(workdir: str) -> str:
    return os.path.join(workdir, "telemetry")


def metrics_path(workdir: str) -> str:
    return os.path.join(telemetry_dir(workdir), "metrics.jsonl")


def host_metrics_path(workdir: str, host: int) -> str:
    """Host ``k``'s telemetry shard (ISSUE 4): the per-host JSONL each
    NON-ZERO process of a multi-host run appends its own lines to.
    Process 0 writes no shard — ``metrics.jsonl`` already IS its
    stream, and duplicating it would double the run-record host's
    per-line write+flush for identical bytes (the report CLI merges
    metrics.jsonl in as host 0)."""
    return os.path.join(telemetry_dir(workdir), f"telemetry.host{host}.jsonl")


def trace_path(workdir: str) -> str:
    return os.path.join(telemetry_dir(workdir), "trace.json")


def make_sinks(spec: str, workdir: str) -> list[Sink]:
    """Build the sink list from the comma-separated config spec.

    File-backed sinks need a workdir; without one, only ``console``
    materializes. The ``jsonl`` sink writes the run record
    (``metrics.jsonl``) on process 0 — the fleet lines and reduced
    counters make one file the record — while every OTHER host of a
    multi-host run appends to its own ``telemetry.host{k}.jsonl``
    shard, whose derived/memory/gauge sections are genuinely host-local
    (the per-host stream straggler triage and the shard-merging report
    read, ISSUE 4; process 0's stream is metrics.jsonl itself).
    """
    import jax

    sinks: list[Sink] = []
    names = [s.strip() for s in (spec or "").split(",") if s.strip()]
    for name in names:
        if name not in SINK_NAMES:
            raise ValueError(
                f"unknown telemetry sink {name!r} (one of {SINK_NAMES})"
            )
        if name == "console":
            sinks.append(ConsoleSink())
        elif name == "jsonl" and workdir:
            if jax.process_index() == 0:
                sinks.append(JsonlSink(metrics_path(workdir)))
            elif jax.process_count() > 1:
                sinks.append(
                    JsonlSink(
                        host_metrics_path(workdir, jax.process_index())
                    )
                )
        elif name == "tensorboard" and workdir:
            sinks.append(TensorBoardSink(workdir))
    return sinks
