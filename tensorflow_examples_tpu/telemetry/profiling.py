"""In-loop ``jax.profiler`` windows (ISSUE 3 tentpole (3)).

The host span timeline (telemetry/spans.py) answers "where did the host
loop's time go"; the *device*-internal breakdown belongs to the XLA
profiler. Before this module the loop had one hardcoded one-shot window
(``--profile`` → steps 10..20) and the measurement tooling
(tools/profile_trace.py) re-implemented its own capture loop.

``ProfilerWindow`` is the single programmable capture path:

* ``TrainConfig.profile_start_step`` / ``profile_num_steps`` /
  ``profile_dir`` describe a window in run-relative steps; any run can
  capture a device trace without code changes. The legacy ``--profile``
  flag is sugar for ``start=10, num=10``.
* The window is **one-shot** (a re-arm would sync + restart the
  profiler every subsequent step — pinned by
  tests/test_bundled_steps.py) and bracketed by a ``profile`` span in
  the host timeline.
* On stop, the window's facts land in gauges (``profile/steps``,
  ``profile/wall_secs``) and are cross-linked from the run's final
  JSONL line as the ``"profile"`` object (dir, start, steps, wall) —
  so the record of *where the trace lives* survives with the run.
* When the TF profiler plugin can convert the captured xplane (the
  tools/profile_trace.py protocol), the observed **device duty cycle**
  is extracted and published as ``profile/device_duty_cycle`` — the
  measured companion to the analytic 6ND MFU (VERDICT r4 weak #5).
  Conversion is best-effort: missing plugin/backends degrade to None.
"""

from __future__ import annotations

import logging
import os
import time

log = logging.getLogger(__name__)


def try_device_duty_cycle(
    trace_dir: str, force: bool = False
) -> float | None:
    """Extract the device duty cycle (fraction of traced wall time the
    device was busy) from a captured xplane, via the TF profiler plugin
    when available. Returns None when anything is missing — the
    conversion stack is optional by design.

    The conversion imports TensorFlow (tens of seconds, hundreds of MB)
    — far too heavy to pay implicitly inside a training loop or the CI
    suite — so it only runs when ``force=True`` (tools/profile_trace.py,
    the measurement protocol) or ``PROFILE_DUTY_CYCLE=1`` is set (an
    operator opting a production run in)."""
    if not force and os.environ.get("PROFILE_DUTY_CYCLE", "") in ("", "0"):
        return None
    import glob

    xplanes = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not xplanes:
        return None
    try:
        # Stale-proto guard shared with tools/profile_trace.py.
        os.environ.setdefault(
            "PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python"
        )
        from tensorboard_plugin_profile.protobuf import overview_page_pb2
        from tensorflow.python.profiler.internal import (
            _pywrap_profiler_plugin as pp,
        )

        data, ok = pp.xspace_to_tools_data(list(xplanes), "overview_page", {})
        if not ok:
            return None
        page = overview_page_pb2.OverviewPage()
        page.ParseFromString(data)
        fields = {
            f.name: v
            for f, v in page.analysis.ListFields()
            if isinstance(v, (int, float))
        }
        for name, v in fields.items():
            if "duty_cycle" in name:
                return float(v) / 100.0 if v > 1.0 else float(v)
        idle = fields.get("device_idle_time_percent")
        if idle is not None:
            return max(0.0, min(1.0, 1.0 - float(idle) / 100.0))
    except Exception as e:  # noqa: BLE001 - optional measurement path
        log.debug("duty-cycle extraction unavailable: %s: %s",
                  type(e).__name__, e)
    return None


class ProfilerWindow:
    """One-shot windowed device trace, driven by the training loop.

    ``maybe_start(rel_step)`` before a chunk (run-relative step index),
    ``maybe_stop(rel_steps_done, block_on=...)`` after it; ``finish``
    closes an in-flight window on any exit path.
    """

    def __init__(
        self,
        start_step: int,
        num_steps: int,
        out_dir: str,
        telemetry=None,
    ):
        self.start_step = max(int(start_step), 0)
        self.num_steps = max(int(num_steps), 1)
        self.out_dir = out_dir
        self._telemetry = telemetry
        self._state = "pending"  # pending -> active -> done
        self._span_cm = None
        self._t0 = 0.0
        self._first_rel = 0
        self._last_rel = 0  # latest rel_steps_done seen while active
        self.info: dict | None = None

    @classmethod
    def from_config(cls, cfg, telemetry=None) -> "ProfilerWindow | None":
        """None when no window is configured. ``--profile`` (legacy) maps
        to the historical steps-10..20 one-shot."""
        num = int(getattr(cfg, "profile_num_steps", 0) or 0)
        start = int(getattr(cfg, "profile_start_step", 0) or 0)
        if num <= 0:
            if not getattr(cfg, "profile", False):
                return None
            start, num = (start or 10), 10
        out_dir = (
            getattr(cfg, "profile_dir", "") or
            (os.path.join(cfg.workdir, "profile") if cfg.workdir
             else "/tmp/tpu_profile")
        )
        return cls(start, num, out_dir, telemetry)

    # -------------------------------------------------------------- drive

    @property
    def active(self) -> bool:
        return self._state == "active"

    def maybe_start(self, rel_step: int) -> None:
        if self._state != "pending" or rel_step < self.start_step:
            return
        import jax

        jax.profiler.start_trace(self.out_dir)
        self._state = "active"
        self._first_rel = rel_step
        self._last_rel = rel_step
        self._t0 = time.perf_counter()
        if self._telemetry is not None:
            self._span_cm = self._telemetry.span(
                "profile", dir=self.out_dir
            )
            self._span_cm.__enter__()
        log.info(
            "profiler window open: run-relative step %d, %d step(s) -> %s",
            rel_step, self.num_steps, self.out_dir,
        )

    def maybe_stop(self, rel_steps_done: int, block_on=None) -> None:
        if self._state != "active":
            return
        self._last_rel = rel_steps_done
        if rel_steps_done - self._first_rel >= self.num_steps:
            self._stop(rel_steps_done, block_on)

    def finish(self, block_on=None) -> None:
        """Close an in-flight window (exit paths: preempt, abort, loop
        end before the window filled). Steps already traced — the
        latest ``maybe_stop`` progress mark — are recorded, not lost."""
        if self._state == "active":
            self._stop(self._last_rel, block_on)

    # ------------------------------------------------------------ internal

    def _stop(self, rel_steps_done: int, block_on) -> None:
        import jax

        if block_on is not None:
            # The traced steps must actually retire inside the window.
            jax.block_until_ready(block_on)
        wall = time.perf_counter() - self._t0
        jax.profiler.stop_trace()
        self._state = "done"
        if self._span_cm is not None:
            self._span_cm.__exit__(None, None, None)
            self._span_cm = None
        steps = max(rel_steps_done - self._first_rel, 0)
        self.info = {
            "dir": self.out_dir,
            "start_step": self._first_rel,
            "num_steps": steps,
            "wall_secs": round(wall, 6),
        }
        duty = try_device_duty_cycle(self.out_dir)
        if self._telemetry is not None:
            reg = self._telemetry.registry
            reg.gauge("profile/steps").set(steps)
            reg.gauge("profile/wall_secs").set(wall)
            if duty is not None:
                reg.gauge("profile/device_duty_cycle").set(duty)
                # Per-fit handoff: derived blocks read THIS fit's
                # measurement, never the (process-global) gauge.
                self._telemetry.observed_duty_cycle = duty
            self._telemetry.note_profile(self.info)
        log.info(
            "profiler window closed: %d step(s) in %.3fs -> %s%s",
            steps, wall, self.out_dir,
            f" (device duty cycle {duty:.1%})" if duty is not None else "",
        )
