"""Span tracer: a per-step host-side timeline, exportable as Chrome trace.

``with span("data_fetch"): ...`` brackets each training-loop phase (host
batch fetch, device step dispatch, metric flush, eval, checkpoint
save/restore — wired in train/loop.py and train/checkpoint.py). Each
completed span becomes

* a **trace event** in a bounded in-memory buffer, exported as
  Chrome-trace/Perfetto JSON (``chrome://tracing`` / ui.perfetto.dev
  "complete" events, phase ``"X"``) by ``Telemetry.close()``; and
* a **duration sample** in the registry time-histogram
  ``span/<name>`` — which is where the run report's per-phase time
  breakdown and the step-time percentiles come from.

The open-span bookkeeping is keyed by thread id and readable from OTHER
threads: the watchdog's hang dump (utils/diagnostics.py) calls
``active_span_names()`` so a stall report says "stuck inside
``data_fetch``", not just the loop's coarse phase marker.

Host-side only by design: device-internal timing belongs to the XLA
profiler (``cfg.profile``); these spans answer the cheaper, always-on
question "where did the *host* loop's wall time go".
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Callable

# Chrome-trace buffer bound: ~100k events ≈ a few MB of JSON — plenty
# for any smoke/diagnostic run; a multi-day run keeps the FIRST N events
# (startup + steady state onset, the diagnostically interesting part)
# and counts the rest as dropped.
MAX_EVENTS = 100_000


class Tracer:
    def __init__(
        self,
        registry=None,
        *,
        max_events: int = MAX_EVENTS,
        now_ns: Callable[[], int] | None = None,
    ):
        # None = resolve default_registry() per record, so a tracer made
        # before reset_default_registry() still lands in the live one.
        self._registry = registry
        self._now_ns = now_ns if now_ns is not None else time.perf_counter_ns
        self._epoch_ns = self._now_ns()
        self._max_events = max_events
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self.dropped = 0
        # thread id -> stack of open span names (read cross-thread by the
        # watchdog; mutated only by the owning thread, under the lock).
        self._open: dict[int, list[str]] = {}

    # ------------------------------------------------------------- record

    @contextlib.contextmanager
    def span(self, name: str, **args):
        tid = threading.get_ident()
        t0 = self._now_ns()
        with self._lock:
            self._open.setdefault(tid, []).append(name)
        try:
            yield
        finally:
            t1 = self._now_ns()
            with self._lock:
                stack = self._open.get(tid)
                if stack and stack[-1] == name:
                    stack.pop()
                if len(self._events) < self._max_events:
                    ev = {
                        "name": name,
                        "ph": "X",
                        "ts": (t0 - self._epoch_ns) / 1e3,  # µs
                        "dur": (t1 - t0) / 1e3,
                        "pid": 0,
                        "tid": tid,
                    }
                    if args:
                        ev["args"] = args
                    self._events.append(ev)
                else:
                    self.dropped += 1
            reg = self._registry
            if reg is None:
                from tensorflow_examples_tpu.telemetry import registry as _reg

                reg = _reg.default_registry()
            reg.histogram(f"span/{name}").record((t1 - t0) / 1e9)

    # ------------------------------------------------------------ inspect

    def active_span_names(self) -> list[str]:
        """Innermost open span of every thread that has one (the watchdog
        reads this from its own thread while the loop thread is stuck)."""
        with self._lock:
            return [stack[-1] for stack in self._open.values() if stack]

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    # ------------------------------------------------------------- export

    def chrome_trace(self) -> dict:
        """The Chrome-trace JSON object (load in chrome://tracing or
        ui.perfetto.dev). ``displayTimeUnit`` and per-event fields follow
        the Trace Event Format spec's "complete event" shape."""
        with self._lock:
            events = list(self._events)
            dropped = self.dropped
        trace = {"traceEvents": events, "displayTimeUnit": "ms"}
        if dropped:
            trace["droppedEventCount"] = dropped
        return trace

    def write_chrome_trace(self, path: str) -> None:
        import os

        # The jsonl sink usually creates workdir/telemetry/ first, but
        # the trace must not depend on which sinks are enabled.
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
            f.write("\n")


_default: Tracer = Tracer()


def default_tracer() -> Tracer:
    return _default


def reset_default_tracer(**kw) -> Tracer:
    """Fresh default tracer (test isolation / new run); returns it."""
    global _default
    _default = Tracer(**kw)
    return _default


def span(name: str, **args):
    """Convenience: a span on the default tracer (library call sites)."""
    return _default.span(name, **args)


def active_span_names() -> list[str]:
    return _default.active_span_names()
