"""SLO objectives, error-budget accounting, burn-rate alerting (ISSUE 19).

PRs 2/3/4/18 built the raw signal — instruments, stats lines, scrape
surfaces, trace trees. This module is the layer that *evaluates* it:

* :class:`SLOObjective` / :class:`SLOConfig` — the declarative rules
  table (the ShardingConfig/PrecisionConfig precedent: a frozen,
  validated, serializable config the fleet loads from ``slo.json``).
  Each objective names an SLO class and its ceilings — TTFT/TPOT/e2e
  latency, an error budget, a probe availability floor.

* :class:`AlertEngine` — good/bad-event SLO accounting. Every request
  outcome (and every synthetic probe) is classified against its
  class's objectives; a request slower than the objective, or errored,
  *consumes error budget*. Each rule is evaluated over TWO windows
  (the multi-window burn-rate method: a fast window for detection
  speed, a slow window so a single spike cannot page) and walks a
  pending → firing → resolved state machine with dwell times on both
  edges — the hysteresis that suppresses flapping. Firing and resolve
  transitions land as schema-v14 ``kind="alert"`` JSONL lines with the
  PR-2 sink discipline (one line per transition, flush + fsync per
  append, torn-tail-tolerant read), and every firing alert embeds the
  worst-offender ``trace_id`` observed in the window — from the alert
  to ``trace_report --trace-id`` is one copy-paste.

The engine owns no thread and no clock loop: ``observe*`` is called
from the serving path, ``evaluate()`` from the existing stats cadence
(and the prober's tick), and ``now`` is injectable everywhere so the
unit matrix drives time deterministically. The engine's lock is a
leaf — no callback ever runs under it.

Stdlib only; no device, no network.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
import time

from tensorflow_examples_tpu.telemetry.registry import default_registry

__all__ = [
    "SLOObjective", "SLOConfig", "AlertEngine", "read_alerts",
    "SLO_JSON_VERSION",
]

SLO_JSON_VERSION = 1

# Per-rule event rings are bounded twice over: by wall clock (pruned
# past 2x the slow window) and by count (a deque cap), so a traffic
# flood cannot grow the engine without limit.
_MAX_EVENTS_PER_RULE = 8192


# --------------------------------------------------------------- config


@dataclasses.dataclass(frozen=True)
class SLOObjective:
    """One SLO class's ceilings. A latency field of 0 disables that
    rule; ``error_budget`` is the allowed bad-event fraction (latency
    breaches and errors both consume it); ``availability`` is the
    synthetic-probe success floor (probe failures burn the budget
    ``1 - availability``)."""

    slo: str
    ttft_p95_s: float = 0.0
    tpot_p95_s: float = 0.0
    e2e_p95_s: float = 0.0
    error_budget: float = 0.05
    availability: float = 0.95

    def __post_init__(self):
        if not isinstance(self.slo, str) or not self.slo:
            raise ValueError(f"slo must be a non-empty string, got "
                             f"{self.slo!r}")
        for name in ("ttft_p95_s", "tpot_p95_s", "e2e_p95_s"):
            v = getattr(self, name)
            object.__setattr__(self, name, float(v))
            if float(v) < 0:
                raise ValueError(f"{name} must be >= 0, got {v!r}")
        for name in ("error_budget", "availability"):
            v = float(getattr(self, name))
            object.__setattr__(self, name, v)
            if not 0.0 < v <= 1.0:
                raise ValueError(
                    f"{name} must be in (0, 1], got {v!r}"
                )

    def to_json_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json_dict(cls, obj) -> "SLOObjective":
        if not isinstance(obj, dict):
            raise ValueError(
                f"slo objective must be a JSON object, got "
                f"{type(obj).__name__}"
            )
        unknown = set(obj) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise ValueError(f"unknown slo objective keys "
                             f"{sorted(unknown)}")
        if "slo" not in obj:
            raise ValueError("slo objective is missing 'slo'")
        return cls(**obj)


def _default_objectives() -> tuple:
    # Deliberately generous: a healthy smoke bench on a CPU host must
    # fire ZERO alerts (the false-positive gate the bench bank pins).
    return (
        SLOObjective(slo="interactive", ttft_p95_s=5.0,
                     tpot_p95_s=2.0, e2e_p95_s=60.0),
        SLOObjective(slo="batch", ttft_p95_s=30.0,
                     tpot_p95_s=5.0, e2e_p95_s=300.0),
    )


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """The fleet's alerting policy: per-class objectives plus the
    burn-rate evaluation knobs shared by every rule.

    ``windows_s`` is (fast, slow); a rule breaches only when BOTH
    windows burn past their threshold (``burn_thresholds``, same
    order) — the fast window bounds detection delay, the slow window
    keeps one spike from paging. ``pending_for_s`` / ``resolve_after_s``
    are the state-machine dwell times (fire only after a sustained
    breach; resolve only after sustained health)."""

    objectives: tuple = dataclasses.field(
        default_factory=_default_objectives
    )
    windows_s: tuple = (60.0, 300.0)
    burn_thresholds: tuple = (10.0, 2.0)
    pending_for_s: float = 2.0
    resolve_after_s: float = 5.0

    def __post_init__(self):
        objs = tuple(
            o if isinstance(o, SLOObjective)
            else SLOObjective.from_json_dict(o)
            for o in self.objectives
        )
        if not objs:
            raise ValueError("SLOConfig needs at least one objective")
        seen: set = set()
        for o in objs:
            if o.slo in seen:
                raise ValueError(f"duplicate objective for slo "
                                 f"{o.slo!r}")
            seen.add(o.slo)
        object.__setattr__(self, "objectives", objs)
        win = tuple(float(w) for w in self.windows_s)
        if len(win) != 2 or not 0 < win[0] < win[1]:
            raise ValueError(
                f"windows_s must be (fast, slow) with 0 < fast < slow, "
                f"got {self.windows_s!r}"
            )
        object.__setattr__(self, "windows_s", win)
        thr = tuple(float(t) for t in self.burn_thresholds)
        if len(thr) != 2 or any(t <= 0 for t in thr):
            raise ValueError(
                f"burn_thresholds must be two positive rates, got "
                f"{self.burn_thresholds!r}"
            )
        object.__setattr__(self, "burn_thresholds", thr)
        for name in ("pending_for_s", "resolve_after_s"):
            v = float(getattr(self, name))
            object.__setattr__(self, name, v)
            if v < 0:
                raise ValueError(f"{name} must be >= 0, got {v!r}")

    def objective(self, slo: str) -> SLOObjective | None:
        for o in self.objectives:
            if o.slo == slo:
                return o
        return None

    # -------------------------------------------------- serialization

    def to_json_dict(self) -> dict:
        return {
            "objectives": [o.to_json_dict() for o in self.objectives],
            "windows_s": list(self.windows_s),
            "burn_thresholds": list(self.burn_thresholds),
            "pending_for_s": self.pending_for_s,
            "resolve_after_s": self.resolve_after_s,
        }

    @classmethod
    def from_json_dict(cls, obj) -> "SLOConfig":
        if not isinstance(obj, dict):
            raise ValueError(
                f"slo config must be a JSON object, got "
                f"{type(obj).__name__}"
            )
        unknown = set(obj) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise ValueError(f"unknown slo config keys {sorted(unknown)}")
        kw = dict(obj)
        if "objectives" in kw:
            kw["objectives"] = tuple(kw["objectives"])
        return cls(**kw)

    def save(self, path: str, *, extra=None) -> None:
        """Atomic write of ``{"version", "config", **extra}`` — the
        ``slo.json`` the serving CLIs auto-load (the sharding.json
        precedent)."""
        doc = {
            "version": SLO_JSON_VERSION,
            "config": self.to_json_dict(),
        }
        if extra:
            doc.update(extra)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "SLOConfig":
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"{path}: not a JSON object")
        if "config" in doc:
            version = doc.get("version")
            if version != SLO_JSON_VERSION:
                raise ValueError(
                    f"{path}: slo.json version {version!r} (this build "
                    f"reads {SLO_JSON_VERSION})"
                )
            return cls.from_json_dict(doc["config"])
        # A bare config object (hand-written, no wrapper) also loads.
        return cls.from_json_dict(doc)


# --------------------------------------------------------------- engine


class _Rule:
    """One alert rule's event ring + state machine (engine-internal;
    all mutation happens under the engine lock)."""

    __slots__ = ("name", "slo", "kind", "budget", "threshold",
                 "state", "breach_since", "healthy_since", "fired",
                 "events", "last_burn", "last_remaining")

    def __init__(self, name: str, slo: str, kind: str, budget: float,
                 threshold: float):
        self.name = name
        self.slo = slo
        self.kind = kind          # "ttft" | "tpot" | "e2e" | "errors"
        #                           | "probe"
        self.budget = budget      # allowed bad-event fraction
        self.threshold = threshold  # latency ceiling (0 for errors/probe)
        self.state = "ok"         # "ok" | "pending" | "firing"
        self.breach_since: float | None = None
        self.healthy_since: float | None = None
        self.fired = 0
        # (t, bad, value, trace_id, replica)
        self.events: collections.deque = collections.deque(
            maxlen=_MAX_EVENTS_PER_RULE
        )
        self.last_burn = (0.0, 0.0)
        self.last_remaining = 1.0


class AlertEngine:
    """Error-budget accounting + multi-window burn-rate alerting.

    Call :meth:`observe` per finished request, :meth:`observe_probe`
    per synthetic probe, :meth:`evaluate` on the stats cadence; read
    :meth:`stats` (the four v14 serving-line keys), :meth:`payload`
    (the ``GET /alerts`` body), and the ``kind="alert"`` JSONL sink.
    """

    def __init__(self, config: SLOConfig | None = None, *,
                 registry=None, path: str | None = None,
                 now=None):
        self.config = config or SLOConfig()
        self.registry = (
            registry if registry is not None else default_registry()
        )
        self._now = now or time.time
        self._lock = threading.Lock()
        self._t_session = self._now()
        self._rules: dict[str, _Rule] = {}
        for o in self.config.objectives:
            for kind, thr in (("ttft", o.ttft_p95_s),
                              ("tpot", o.tpot_p95_s),
                              ("e2e", o.e2e_p95_s)):
                if thr > 0:
                    self._add_rule(f"{kind}_{o.slo}", o.slo, kind,
                                   o.error_budget, thr)
            self._add_rule(f"errors_{o.slo}", o.slo, "errors",
                           o.error_budget, 0.0)
            self._add_rule(f"probe_{o.slo}", o.slo, "probe",
                           max(1.0 - o.availability, 1e-9), 0.0)
        self._file = open(path, "a") if path else None
        self.path = path

    def _add_rule(self, name, slo, kind, budget, threshold):
        self._rules[name] = _Rule(name, slo, kind, budget, threshold)

    # ----------------------------------------------------------- feed

    def observe(self, slo: str, *, ttft_s: float | None = None,
                tpot_s: float | None = None,
                e2e_s: float | None = None, error: bool = False,
                trace_id: str | None = None,
                replica: str | None = None,
                now: float | None = None) -> None:
        """One finished ORGANIC request: classify it against its
        class's objectives and append good/bad events to the class's
        rules. Unknown SLO classes are ignored (no objective, no
        budget)."""
        o = self.config.objective(slo)
        if o is None:
            return
        t = self._now() if now is None else float(now)
        with self._lock:
            for kind, value in (("ttft", ttft_s), ("tpot", tpot_s),
                                ("e2e", e2e_s)):
                rule = self._rules.get(f"{kind}_{slo}")
                if rule is None or value is None:
                    continue
                bad = float(value) > rule.threshold
                rule.events.append(
                    (t, bad, float(value), trace_id, replica)
                )
            rule = self._rules[f"errors_{slo}"]
            rule.events.append(
                (t, bool(error), 1.0 if error else 0.0, trace_id,
                 replica)
            )

    def observe_probe(self, *, slo: str, ok: bool, replica: str,
                      ttft_s: float | None = None,
                      trace_id: str | None = None,
                      now: float | None = None) -> None:
        """One synthetic canary probe result (serving/prober.py). A
        failed probe burns the availability budget; a slow-but-ok
        probe burns the class's TTFT budget like organic traffic."""
        o = self.config.objective(slo)
        if o is None:
            return
        t = self._now() if now is None else float(now)
        with self._lock:
            rule = self._rules[f"probe_{slo}"]
            rule.events.append(
                (t, not ok, 0.0 if ok else 1.0, trace_id, replica)
            )
            if ok and ttft_s is not None:
                lat = self._rules.get(f"ttft_{slo}")
                if lat is not None:
                    lat.events.append(
                        (t, float(ttft_s) > lat.threshold,
                         float(ttft_s), trace_id, replica)
                    )

    # ------------------------------------------------------- evaluate

    @staticmethod
    def _window(rule: _Rule, now: float, win: float):
        """(total, bad, worst-bad-event) over [now - win, now]."""
        total = bad = 0
        worst = None  # (value, trace_id, replica)
        for t, is_bad, value, trace_id, replica in rule.events:
            if t < now - win:
                continue
            total += 1
            if is_bad:
                bad += 1
                if worst is None or value > worst[0]:
                    worst = (value, trace_id, replica)
        return total, bad, worst

    def evaluate(self, *, now: float | None = None) -> list[dict]:
        """One alerting tick: recompute every rule's burn rates, walk
        the state machines, and return (and sink) the transitions that
        happened — each a v14 ``alert`` object dict."""
        t = self._now() if now is None else float(now)
        fast_w, slow_w = self.config.windows_s
        fast_thr, slow_thr = self.config.burn_thresholds
        cfg = self.config
        reg = self.registry
        reg.counter("alert/evaluations_total").inc()
        transitions: list[dict] = []
        with self._lock:
            for rule in self._rules.values():
                # Prune far outside the slow window so rings stay small
                # on long runs regardless of the count cap.
                horizon = t - 2 * slow_w
                while rule.events and rule.events[0][0] < horizon:
                    rule.events.popleft()
                total_f, bad_f, worst_f = self._window(rule, t, fast_w)
                total_s, bad_s, worst_s = self._window(rule, t, slow_w)
                burn_f = (
                    (bad_f / total_f) / rule.budget if total_f else 0.0
                )
                burn_s = (
                    (bad_s / total_s) / rule.budget if total_s else 0.0
                )
                rule.last_burn = (burn_f, burn_s)
                rule.last_remaining = (
                    max(0.0, 1.0 - (bad_s / total_s) / rule.budget)
                    if total_s else 1.0
                )
                breached = (
                    total_f > 0 and total_s > 0
                    and burn_f >= fast_thr and burn_s >= slow_thr
                )
                worst = worst_f or worst_s
                if rule.state == "ok":
                    if breached:
                        rule.state = "pending"
                        rule.breach_since = t
                elif rule.state == "pending":
                    if not breached:
                        rule.state = "ok"
                        rule.breach_since = None
                    elif t - rule.breach_since >= cfg.pending_for_s:
                        rule.state = "firing"
                        rule.healthy_since = None
                        rule.fired += 1
                        reg.counter("alert/firing_total").inc()
                        transitions.append(self._transition(
                            rule, "firing", t, worst
                        ))
                elif rule.state == "firing":
                    if breached:
                        rule.healthy_since = None
                    else:
                        if rule.healthy_since is None:
                            rule.healthy_since = t
                        if t - rule.healthy_since >= cfg.resolve_after_s:
                            rule.state = "ok"
                            rule.breach_since = None
                            rule.healthy_since = None
                            reg.counter("alert/resolved_total").inc()
                            transitions.append(self._transition(
                                rule, "resolved", t, worst
                            ))
            firing = sum(
                1 for r in self._rules.values() if r.state == "firing"
            )
        reg.gauge("alert/firing").set(firing)
        reg.gauge("alert/error_budget_remaining").set(
            self.stats()["error_budget_remaining"]
        )
        for tr in transitions:
            self._write_line(tr)
        return transitions

    def _transition(self, rule: _Rule, state: str, t: float,
                    worst) -> dict:
        """Build one v14 alert object. Severity: a fast burn hot
        enough to exhaust the budget in well under the slow window
        pages; anything else is a ticket."""
        fast_thr, _slow_thr = self.config.burn_thresholds
        alert = {
            "name": rule.name,
            "slo": rule.slo,
            "state": state,
            "severity": (
                "page" if rule.last_burn[0] >= 2 * fast_thr
                else "ticket"
            ),
            "burn_rate": rule.last_burn[0],
            "budget_remaining": rule.last_remaining,
            "since_unix": rule.breach_since
            if rule.breach_since is not None else t,
            "window_s": self.config.windows_s[0],
        }
        if rule.threshold > 0:
            alert["threshold"] = rule.threshold
        if worst is not None:
            value, trace_id, replica = worst
            alert["value"] = value
            # The worst offender's trace: the alert -> trace_report
            # copy-paste (ISSUE 18's exemplar discipline).
            if trace_id:
                alert["trace_id"] = str(trace_id)
            if replica:
                alert["replica"] = str(replica)
        return alert

    def _write_line(self, alert: dict) -> None:
        if self._file is None:
            return
        from tensorflow_examples_tpu.telemetry import schema

        line = {
            "schema_version": schema.SERVING_SCHEMA_VERSION,
            "kind": "alert",
            "step": 0,
            "time_unix": self._now(),
            "session_start_unix": self._t_session,
            "host": 0,
            "metrics": {},
            "counters": {},
            "gauges": {},
            "derived": {},
            "alert": alert,
        }
        with self._lock:
            if self._file is None:
                return
            # One transition per line, flushed and fsynced per append
            # (the PR-2 sink discipline): a crash tears at most the
            # tail line, which readers tolerate.
            self._file.write(json.dumps(line) + "\n")
            self._file.flush()
            os.fsync(self._file.fileno())

    # ----------------------------------------------------------- read

    def firing(self) -> list[dict]:
        """The currently-firing alerts (payload()["firing"])."""
        return self.payload()["firing"]

    def stats(self) -> dict:
        """The v14 serving-line keys (the router's stats_line stamps
        exactly these)."""
        with self._lock:
            firing = sum(
                1 for r in self._rules.values() if r.state == "firing"
            )
            fired = sum(r.fired for r in self._rules.values())
            remaining = min(
                (r.last_remaining for r in self._rules.values()),
                default=1.0,
            )
            probe_total = probe_bad = 0
            for r in self._rules.values():
                if r.kind != "probe":
                    continue
                t_now = self._now()
                total, bad, _ = self._window(
                    r, t_now, self.config.windows_s[1]
                )
                probe_total += total
                probe_bad += bad
        return {
            "alerts_firing": firing,
            "error_budget_remaining": remaining,
            "probe_success_rate": (
                (probe_total - probe_bad) / probe_total
                if probe_total else 1.0
            ),
            "alert_count": fired,
        }

    def payload(self) -> dict:
        """The ``GET /alerts`` JSON body: every rule's live burn rates
        and state, the firing subset with exemplars, the config that
        produced them, and the v14 summary."""
        t = self._now()
        firing: list[dict] = []
        rules: dict[str, dict] = {}
        with self._lock:
            for rule in self._rules.values():
                entry = {
                    "slo": rule.slo,
                    "kind": rule.kind,
                    "state": rule.state,
                    "burn_rate_fast": rule.last_burn[0],
                    "burn_rate_slow": rule.last_burn[1],
                    "budget_remaining": rule.last_remaining,
                    "fired": rule.fired,
                }
                if rule.threshold > 0:
                    entry["threshold"] = rule.threshold
                rules[rule.name] = entry
                if rule.state == "firing":
                    _tot, _bad, worst = self._window(
                        rule, t, self.config.windows_s[1]
                    )
                    firing.append(
                        self._transition(rule, "firing", t, worst)
                    )
        out = {"firing": firing, "rules": rules,
               "config": self.config.to_json_dict()}
        out.update(self.stats())
        return out

    def close(self) -> None:
        with self._lock:
            f, self._file = self._file, None
        if f is not None:
            f.close()


def read_alerts(path: str) -> list[dict]:
    """Load an alert JSONL sink into a list of alert objects (each
    with its line's ``time_unix`` attached as ``"_time_unix"``).
    Torn-tail tolerant: an unparseable line — the one a crash can
    tear — is skipped, never raised."""
    out: list[dict] = []
    try:
        f = open(path)
    except OSError:
        return out
    with f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                line = json.loads(raw)
            except ValueError:
                continue
            if not isinstance(line, dict) or line.get("kind") != "alert":
                continue
            alert = line.get("alert")
            if not isinstance(alert, dict):
                continue
            alert = dict(alert)
            alert["_time_unix"] = line.get("time_unix")
            out.append(alert)
    return out
