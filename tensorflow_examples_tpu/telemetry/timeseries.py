"""Bounded in-process time-series store (ISSUE 19).

The metrics registry answers "what is the value NOW"; every question an
alerting layer or an operator eyeballing a regression actually asks is
"what was it over the last N minutes". This module is the smallest
store that closes that gap without a database: one named ring per
series (a ``deque(maxlen=capacity)`` of ``(time_unix, value)`` points),
fed by :meth:`TimeSeriesStore.sample` on the EXISTING stats cadence
(the serving stats loop / the router's ``--stats-every`` tick — no new
thread, no new clock), and scraped as JSON via ``GET /series`` on both
the router and replica frontends.

One ``sample()`` call walks the registry snapshot:

* every counter becomes a series of its cumulative value (consumers
  difference adjacent points for a rate);
* every gauge becomes a series of its instantaneous value;
* every histogram becomes THREE series — ``<name>.p50`` / ``.p95`` /
  ``.p99`` over the histogram's bounded sample window at sample time —
  so tail latency is a curve, not a single scrape-time number.

Memory is bounded by construction: ``capacity`` points per series,
series count bounded by the registry's instrument count. At the
default 720-point capacity and a 2 s stats cadence one ring holds
24 minutes — enough to see a burn-rate window develop, small enough
to never matter.

Locking: the registry snapshot is taken BEFORE the store lock is
acquired and holders never call out while holding it, so the store is
a leaf in the lock order (scrape threads and the stats thread contend
only with each other, never with the batcher or router locks).

Stdlib only; no device, no network.
"""

from __future__ import annotations

import collections
import threading
import time

__all__ = ["TimeSeriesStore"]

# Histogram percentile suffixes sampled into their own series.
_HIST_QS = (("p50", 50.0), ("p95", 95.0), ("p99", 99.0))


def _nearest_rank(sorted_vals: list[float], q: float) -> float | None:
    if not sorted_vals:
        return None
    rank = max(int(-(-(q / 100.0 * len(sorted_vals)) // 1)) - 1, 0)
    return sorted_vals[min(rank, len(sorted_vals) - 1)]


class TimeSeriesStore:
    """Ring-buffered ``{series name: [(time_unix, value), ...]}``."""

    def __init__(self, registry=None, *, capacity: int = 720):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.registry = registry
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._series: dict[str, collections.deque] = {}  # guard: _lock
        self.samples_taken = 0  # guard: _lock

    # ----------------------------------------------------------- write

    def record(self, name: str, value: float, *,
               now: float | None = None) -> None:
        """Append one point to one named series (probers and engines
        that track values the registry has no instrument for)."""
        t = time.time() if now is None else float(now)
        v = float(value)
        with self._lock:
            ring = self._series.get(name)
            if ring is None:
                ring = self._series[name] = collections.deque(
                    maxlen=self.capacity
                )
            ring.append((t, v))

    def sample(self, *, now: float | None = None) -> int:
        """Take one fixed-cadence sample of the attached registry;
        returns the number of points appended. No-op without a
        registry (a record()-only store is legal)."""
        if self.registry is None:
            return 0
        t = time.time() if now is None else float(now)
        # Snapshot OUTSIDE the store lock: the registry has its own
        # locks and this ordering keeps the store a lock-order leaf.
        counters = self.registry.counter_values()
        gauges = self.registry.gauge_values()
        hists = self.registry.histogram_summaries()
        points: list[tuple[str, float]] = []
        for k, v in counters.items():
            points.append((k, float(v)))
        for k, v in gauges.items():
            points.append((k, float(v)))
        for hname, summ in hists.items():
            for suffix, _q in _HIST_QS:
                v = summ.get(suffix)
                if v is not None:
                    points.append((f"{hname}.{suffix}", float(v)))
        with self._lock:
            for name, v in points:
                ring = self._series.get(name)
                if ring is None:
                    ring = self._series[name] = collections.deque(
                        maxlen=self.capacity
                    )
                ring.append((t, v))
            self.samples_taken += 1
        return len(points)

    # ------------------------------------------------------------ read

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def series(self, name: str, *, last: int | None = None) -> list:
        """One series' points, oldest first (``last`` trims to the most
        recent N). Unknown names return []."""
        with self._lock:
            ring = self._series.get(name)
            pts = list(ring) if ring is not None else []
        if last is not None and last >= 0:
            pts = pts[-last:]
        return pts

    def rollup(self, name: str) -> dict:
        """p50/p95/p99 (plus count/min/max/last) over everything the
        ring currently holds for ``name`` — the store-level rollup an
        operator reads when the histogram's own window has already
        rotated past the incident."""
        vals = sorted(v for _t, v in self.series(name))
        out = {
            "count": len(vals),
            "min": vals[0] if vals else None,
            "max": vals[-1] if vals else None,
            "last": None,
        }
        pts = self.series(name, last=1)
        if pts:
            out["last"] = pts[-1][1]
        for suffix, q in _HIST_QS:
            out[suffix] = _nearest_rank(vals, q)
        return out

    def to_payload(self, *, last: int | None = None) -> dict:
        """The ``GET /series`` JSON body: every series' points (each a
        ``[time_unix, value]`` pair, oldest first) plus per-series
        rollups and the store's own accounting."""
        with self._lock:
            names = sorted(self._series)
            rings = {n: list(self._series[n]) for n in names}
            taken = self.samples_taken
        if last is not None and last >= 0:
            rings = {n: pts[-last:] for n, pts in rings.items()}
        payload = {
            "capacity": self.capacity,
            "samples_taken": taken,
            "series": {
                n: [[t, v] for t, v in pts] for n, pts in rings.items()
            },
            "rollups": {},
        }
        for n, pts in rings.items():
            vals = sorted(v for _t, v in pts)
            payload["rollups"][n] = {
                "count": len(vals),
                "last": pts[-1][1] if pts else None,
                "p50": _nearest_rank(vals, 50.0),
                "p95": _nearest_rank(vals, 95.0),
                "p99": _nearest_rank(vals, 99.0),
            }
        return payload
