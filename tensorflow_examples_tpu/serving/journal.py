"""Durable request journal + router lease: the control plane's crash
story (ISSUE 16 tentpole).

PRs 9 and 12 made every DATA-plane failure an ordinary input, but the
router that provides those guarantees held accepted requests only in
its own memory — router death lost them, and a client whose connection
dropped mid-generation lost the stream even though per-request
``fold_in`` seeding makes every token bit-reproducible. This module is
the missing durability layer, three pieces:

* :class:`RequestJournal` — a crash-safe JSONL log with the PR-2 sink
  discipline (append-only, one ``flush``+``fsync`` per line, a
  torn-tail-tolerant reader that treats a half-written final line as
  the crash artifact it is, schema-validated records). Three record
  kinds per request id: ``intent`` (everything needed to replay the
  generation token-identically — prompt ids, seed, sampling params,
  SLO class, tenant key), ``progress`` (a committed-token offset), and
  ``done`` (the final stream + status). ``incomplete()`` is the replay
  worklist a restarted/promoted router drains through the fleet; the
  in-memory dedupe window (sized, counted) is what makes a duplicated
  ``request_id`` retry return the ORIGINAL tokens instead of burning a
  second generation. ``refresh()`` tails the file, so a standby
  holding its own instance converges on the primary's appends.
* :class:`Lease` — the active-router lease file with a MONOTONIC
  fencing token. Promotion rewrites the lease with ``token + 1``
  (atomic ``os.replace``, never a torn read); every dispatching router
  checks the file before serving, so a stalled-then-revived primary
  whose token is now stale refuses its own dispatches
  (``router/fenced_dispatch_total``) — no request is ever served by
  two routers (the split-brain pin).
* :class:`StandbyMonitor` — the warm-standby loop (thread
  ``router-standby``): heartbeat-watches the lease the primary
  refreshes from its probe loop, mirrors the primary's ``/replicas``
  view so fleet membership survives the handover, and on a missed
  heartbeat budget promotes its router — acquire the fenced lease,
  start probing (state rebuilt from the first synchronous ``/health``
  sweep), replay the journal's incomplete intents through the fleet
  (token-identical by seeding), and stamp ``router/takeover_total`` +
  ``router/takeover_latency_s``.

``serving/chaos.RouterPair`` composes all three over an in-proc fleet;
``tools/serve_fleet.py --standby`` wires the same machinery over
process fleets.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import threading
import time

try:  # POSIX-only; the lease degrades to in-process locking without it
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX hosts
    fcntl = None

log = logging.getLogger(__name__)

JOURNAL_VERSION = 1

RECORD_KINDS = ("intent", "progress", "done")

# Per-kind required fields of a journal record (the reader validates
# every line it keeps; an invalid line is counted, never applied).
_REQUIRED: dict[str, tuple] = {
    "intent": ("request_id", "prompt", "max_new_tokens", "temperature",
               "top_k", "seed", "slo", "tenant", "ts"),
    "progress": ("request_id", "committed", "ts"),
    "done": ("request_id", "tokens", "status", "ts"),
}


def validate_record(rec) -> list[str]:
    """Problems with one journal record ([] = valid). Schema-validated
    in the telemetry sense: kind-dispatched required fields, typed."""
    if not isinstance(rec, dict):
        return ["record is not an object"]
    problems = []
    if rec.get("v") != JOURNAL_VERSION:
        problems.append(f"journal version {rec.get('v')!r} != "
                        f"{JOURNAL_VERSION}")
    kind = rec.get("rec")
    if kind not in RECORD_KINDS:
        return problems + [f"unknown record kind {kind!r}"]
    for key in _REQUIRED[kind]:
        if key not in rec:
            problems.append(f"{kind} record missing {key!r}")
    rid = rec.get("request_id")
    if not isinstance(rid, str) or not rid:
        problems.append("request_id must be a non-empty string")
    if kind == "intent":
        prompt = rec.get("prompt")
        if not (isinstance(prompt, list) and prompt
                and all(isinstance(t, int) and not isinstance(t, bool)
                        for t in prompt)):
            problems.append("intent prompt must be non-empty token ids")
    if kind == "progress" and not isinstance(rec.get("committed"), int):
        problems.append("progress committed must be an int offset")
    if kind == "done":
        toks = rec.get("tokens")
        if not isinstance(toks, list):
            problems.append("done tokens must be a list")
        if not isinstance(rec.get("status"), int):
            problems.append("done status must be an int")
    # ISSUE 18: intent/done records may carry the request's trace_id —
    # OPTIONAL (no journal version bump: readers ignore unknown extra
    # fields by construction), but typed when present. This is what
    # stitches a takeover-survived request's trace across routers: the
    # successor's dedupe/replay recovers the original trace_id from
    # here and continues THAT trace instead of forking a new one.
    tid = rec.get("trace_id")
    if tid is not None and (not isinstance(tid, str) or not tid):
        problems.append("trace_id must be a non-empty string when present")
    return problems


class RequestJournal:
    """Crash-safe JSONL intent/progress/done log + dedupe window.

    One writer at a time (the ACTIVE router — the lease's fencing token
    is what enforces "one"); any number of tailing readers. All mutable
    state is lock-guarded: appends come from router dispatch threads,
    ``refresh()`` from the standby loop, ``stats()`` from whoever asks.
    """

    def __init__(self, path: str, *, dedup_window: int = 256,
                 registry=None):
        self.path = path
        self.registry = registry
        self.dedup_window = int(dedup_window)
        self._lock = threading.Lock()
        self._fh = None                    # guard: RequestJournal._lock (lazy append handle)
        self._read_pos = 0                 # guard: RequestJournal._lock (tail-follow offset)
        self._intents: dict = {}           # guard: RequestJournal._lock (request_id -> intent)
        self._progress: dict = {}          # guard: RequestJournal._lock (request_id -> committed)
        self._done = collections.OrderedDict()  # guard: RequestJournal._lock (dedupe window)
        self._done_ids: set = set()        # guard: RequestJournal._lock (ALL completed ids)
        self.appends = 0                   # guard: RequestJournal._lock
        self.invalid_lines = 0             # guard: RequestJournal._lock
        self.torn_tail = 0                 # guard: RequestJournal._lock
        self.torn_tail_repaired = 0        # guard: RequestJournal._lock
        self._torn_at: int | None = None   # guard: RequestJournal._lock (offset of last-seen fragment)
        self.dedup_evictions = 0           # guard: RequestJournal._lock
        self.refresh()

    # -------------------------------------------------------- reading

    def refresh(self) -> int:
        """Tail the file from the last consumed offset: apply every
        complete, valid line; a half-written FINAL line (no newline —
        the writer died mid-append) is the torn tail the format
        tolerates by design, left for the next refresh in case the
        writer is merely slow. Returns records applied."""
        applied = 0
        with self._lock:
            try:
                with open(self.path, "rb") as f:
                    f.seek(self._read_pos)
                    chunk = f.read()
            except FileNotFoundError:
                return 0
            lines = chunk.split(b"\n")
            # A final fragment with no trailing newline is a torn tail
            # (the writer died — or is still — mid-append): tolerated,
            # not consumed, so a later refresh can pick it up whole.
            tail = lines.pop()
            if tail:
                # One crash (or slow write) = one count: the fragment
                # grows in place across polls, so key the stat on where
                # it STARTS, not on how many refreshes observed it.
                start = self._read_pos + len(chunk) - len(tail)
                if start != self._torn_at:
                    self.torn_tail += 1
                    self._torn_at = start
            else:
                self._torn_at = None
            self._read_pos += len(chunk) - len(tail)
            for raw in lines:
                if not raw.strip():
                    continue
                try:
                    rec = json.loads(raw)
                except ValueError:
                    self.invalid_lines += 1
                    continue
                if validate_record(rec):
                    self.invalid_lines += 1
                    continue
                self._apply_locked(rec)
                applied += 1
        return applied

    def _apply_locked(self, rec: dict) -> None:
        # Caller holds self._lock (graftlint lock-pass convention).
        kind, rid = rec["rec"], rec["request_id"]
        if kind == "intent":
            self._intents.setdefault(rid, rec)
        elif kind == "progress":
            self._progress[rid] = max(
                int(rec["committed"]), self._progress.get(rid, 0)
            )
        else:
            self._done_ids.add(rid)
            self._done[rid] = rec
            self._done.move_to_end(rid)
            while len(self._done) > self.dedup_window:
                self._done.popitem(last=False)
                self.dedup_evictions += 1

    # -------------------------------------------------------- writing

    def _append_locked(self, rec: dict) -> dict:
        # Caller holds self._lock. PR-2 sink discipline: one line, one
        # flush, one fsync — a crash tears at most the line in flight,
        # and the reader side treats that torn tail as absent.
        problems = validate_record(rec)
        if problems:
            raise ValueError(
                f"refusing to append invalid journal record: {problems}"
            )
        if self._fh is None:
            self._fh = open(self.path, "ab")
            self._fh.seek(0, os.SEEK_END)
            if self._fh.tell() > 0:
                # A dead predecessor may have left a torn (newline-less)
                # fragment at the tail. Appending straight onto it would
                # weld OUR record to the fragment into one invalid line —
                # silently discarding the new record for every reader.
                # Terminate the fragment first: it becomes a complete
                # invalid line (counted, never applied) and our append
                # starts clean.
                with open(self.path, "rb") as probe:
                    probe.seek(-1, os.SEEK_END)
                    last = probe.read(1)
                if last != b"\n":
                    self._fh.write(b"\n")
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                    self.torn_tail_repaired += 1
                    log.warning(
                        "journal %s: terminated a torn tail left by a "
                        "dead writer before appending", self.path,
                    )
        line = (json.dumps(rec, separators=(",", ":")) + "\n").encode()
        self._fh.write(line)
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._read_pos = self._fh.tell()  # own appends are pre-applied
        self._apply_locked(rec)
        self.appends += 1
        if self.registry is not None:
            self.registry.counter("router/journal_appends_total").inc()
        return rec

    def append_intent(self, request_id: str, body: dict, *,
                      trace_id: str | None = None) -> dict:
        """Journal one accepted generate request — everything replay
        needs to reproduce the stream bit-identically (generation is a
        pure function of (params, prompt, seed)), plus the SLO class
        and a tenant-ready key for the multi-tenant roadmap item.
        ``trace_id`` (ISSUE 18) stamps the request's trace so a
        successor router's replay continues the SAME trace."""
        rec = {
            "rec": "intent", "v": JOURNAL_VERSION,
            "request_id": str(request_id),
            "prompt": [int(t) for t in body.get("prompt", [])],
            "max_new_tokens": int(body.get("max_new_tokens", 16)),
            "temperature": float(body.get("temperature", 0.0)),
            "top_k": int(body.get("top_k", 0)),
            "seed": int(body.get("seed", 0)),
            "slo": str(body.get("slo", "interactive")),
            "tenant": str(body.get("tenant", "default")),
            "ts": time.time(),
        }
        if trace_id:
            rec["trace_id"] = str(trace_id)
        with self._lock:
            return self._append_locked(rec)

    def append_progress(self, request_id: str, committed: int) -> dict:
        """Journal a committed-token offset (the resume watermark)."""
        rec = {
            "rec": "progress", "v": JOURNAL_VERSION,
            "request_id": str(request_id),
            "committed": int(committed), "ts": time.time(),
        }
        with self._lock:
            return self._append_locked(rec)

    def append_done(self, request_id: str, tokens, status: int, *,
                    trace_id: str | None = None) -> dict:
        """Journal a request's final stream. The done record is also
        the dedupe window's entry: a duplicated ``request_id`` retry is
        answered from here, not the fleet — and its ``trace_id``
        (ISSUE 18, optional) is what joins the dedupe fast path's
        spans onto the ORIGINAL request's trace."""
        rec = {
            "rec": "done", "v": JOURNAL_VERSION,
            "request_id": str(request_id),
            "tokens": [int(t) for t in tokens],
            "status": int(status), "ts": time.time(),
        }
        if trace_id:
            rec["trace_id"] = str(trace_id)
        with self._lock:
            return self._append_locked(rec)

    # -------------------------------------------------------- queries

    def has_intent(self, request_id: str) -> bool:
        with self._lock:
            return request_id in self._intents

    def lookup(self, request_id: str) -> dict | None:
        """The done record for ``request_id`` while it is inside the
        dedupe window (None = never completed, or evicted)."""
        with self._lock:
            rec = self._done.get(request_id)
            return dict(rec) if rec is not None else None

    def committed(self, request_id: str) -> int:
        with self._lock:
            return self._progress.get(request_id, 0)

    def incomplete(self) -> list[dict]:
        """Intent records with no done record — the replay worklist a
        restarted or promoted router drains through the fleet. Ordered
        by journal position (insertion order)."""
        with self._lock:
            return [
                dict(rec) for rid, rec in self._intents.items()
                if rid not in self._done_ids
            ]

    def stats(self) -> dict:
        with self._lock:
            return {
                "appends": self.appends,
                "intents": len(self._intents),
                "done": len(self._done_ids),
                "incomplete": sum(
                    1 for rid in self._intents
                    if rid not in self._done_ids
                ),
                "dedup_window": self.dedup_window,
                "dedup_entries": len(self._done),
                "dedup_evictions": self.dedup_evictions,
                "invalid_lines": self.invalid_lines,
                "torn_tail": self.torn_tail,
                "torn_tail_repaired": self.torn_tail_repaired,
            }

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            fh.close()


class Lease:
    """Active-router lease file with a monotonic fencing token.

    The file is a single JSON object ``{"token", "owner", "ts"}``
    written via temp-file + ``os.replace`` so readers NEVER see a torn
    lease. ``acquire()`` bumps the token (promotion); ``heartbeat()``
    refreshes ``ts`` only while the caller still holds the newest
    token; ``fenced(token)`` is the dispatch-time check — true once
    anyone acquired a newer token, at which point the stale holder must
    refuse to serve (split-brain fencing).

    ``acquire()`` and ``heartbeat()`` are read-modify-write sequences,
    and the competing routers may be separate PROCESSES (``serve_fleet
    --standby`` tails the same file across processes), so the in-process
    ``threading.Lock`` alone cannot serialize them: a revived primary's
    heartbeat could read its old token, pass the check, and
    ``os.replace`` AFTER a standby's acquire wrote ``token + 1`` —
    reverting the lease and un-fencing the old primary. Both verbs
    therefore also hold an exclusive ``fcntl.flock`` on a sidecar
    ``<path>.lock`` file for the whole read-check-write, making the
    sequence atomic across processes on the same host (the only
    deployment the file-based lease supports)."""

    def __init__(self, path: str, *, owner: str = "router"):
        self.path = path
        self.owner = owner
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def _exclusive(self):
        """self._lock + an exclusive flock on the sidecar lock file:
        the cross-process critical section for read-modify-write."""
        with self._lock:
            if fcntl is None:  # pragma: no cover - non-POSIX hosts
                yield
                return
            fd = os.open(self.path + ".lock", os.O_CREAT | os.O_RDWR,
                         0o644)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX)
                yield
            finally:
                fcntl.flock(fd, fcntl.LOCK_UN)
                os.close(fd)

    def read(self) -> dict | None:
        """The current lease, or None (no file yet / unreadable —
        an unreadable lease never crashes a dispatch path)."""
        try:
            with open(self.path, "rb") as f:
                rec = json.loads(f.read())
        except (OSError, ValueError):
            return None
        if not isinstance(rec, dict) or not isinstance(
            rec.get("token"), int
        ):
            return None
        return rec

    def _write_locked(self, rec: dict) -> None:
        # Caller holds self._lock. Atomic replace: a reader sees the
        # old lease or the new one, never a torn hybrid.
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def acquire(self) -> int:
        """Take the lease with a strictly newer fencing token (the
        promotion verb; also the initial grant). Returns the token."""
        with self._exclusive():
            cur = self.read()
            token = (cur["token"] + 1) if cur else 1
            self._write_locked(
                {"token": token, "owner": self.owner, "ts": time.time()}
            )
        log.info("lease %s acquired by %s (fencing token %d)",
                 self.path, self.owner, token)
        return token

    def heartbeat(self, token: int) -> bool:
        """Refresh ``ts`` while still holding the newest token. False
        (and NO write) once fenced — a stale heartbeat must never
        clobber the new holder's lease."""
        with self._exclusive():
            cur = self.read()
            if cur is None or cur["token"] != token:
                return False
            cur["ts"] = time.time()
            self._write_locked(cur)
            return True

    def fenced(self, token: int) -> bool:
        """True when a NEWER token exists: the holder of ``token`` has
        been superseded and must refuse dispatch."""
        cur = self.read()
        return cur is not None and cur["token"] > int(token)

    def age_s(self) -> float | None:
        """Seconds since the holder's last heartbeat (None = no
        lease)."""
        cur = self.read()
        if cur is None or not isinstance(cur.get("ts"), (int, float)):
            return None
        return max(0.0, time.time() - float(cur["ts"]))


class StandbyMonitor:
    """Warm-standby takeover loop (thread ``router-standby``).

    Watches the primary's lease heartbeats and mirrors its
    ``/replicas`` view onto the standby router; once the lease goes
    stale past ``miss_budget_s`` the standby promotes itself:

    1. ``lease.acquire()`` — the monotonic fencing token now outranks
       the primary's, so a stalled-then-revived primary refuses its
       own dispatches (split-brain pin);
    2. ``router.start()`` — the first synchronous probe sweep rebuilds
       fleet state from ``/health``;
    3. ``router.replay_incomplete()`` — the journal's accepted-but-
       unfinished intents replay through the fleet, token-identical by
       seeding, so router death lost nothing;
    4. stamp ``router/takeover_total`` and the detection-to-serving
       wall in ``router/takeover_latency_s``.

    Until promotion the standby router is dispatch-fenced (its token 0
    is older than any granted lease), so a client hitting the standby
    endpoint early gets a retryable 503, never a second serving path.
    """

    def __init__(self, router, *, lease: Lease,
                 journal: RequestJournal | None = None,
                 primary_url: str | None = None,
                 interval_s: float = 0.25,
                 miss_budget_s: float = 1.5,
                 on_promote=None):
        self.router = router
        self.lease = lease
        self.journal = journal
        self.primary_url = (
            primary_url.rstrip("/") if primary_url else None
        )
        self.interval_s = float(interval_s)
        self.miss_budget_s = float(miss_budget_s)
        self.on_promote = on_promote
        self.promoted = threading.Event()
        self.takeover_latency_s: float | None = None
        self.replayed = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        router.attach_lease(lease, 0)  # fenced until promotion

    # ------------------------------------------------------------ loop

    def start(self) -> "StandbyMonitor":
        self._thread = threading.Thread(
            target=self._loop, name="router-standby", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the watchdog must survive
                log.exception("standby poll failed")
            if self.promoted.is_set():
                return  # promoted: the router's own loops take over
            self._stop.wait(self.interval_s)

    def poll_once(self) -> None:
        """One watch step (tests call it directly for determinism):
        tail the journal, mirror fleet membership, check the
        heartbeat, and promote when the budget is blown."""
        if self.promoted.is_set():
            return
        if self.journal is not None:
            self.journal.refresh()
        self._mirror_replicas()
        age = self.lease.age_s()
        if age is not None and age > self.miss_budget_s:
            self.promote(detected_age_s=age)

    def _mirror_replicas(self) -> None:
        """Adopt the primary's fleet membership (the autoscaler may
        have resized it since the standby was configured). Best-effort:
        an unreachable primary changes nothing — that is exactly the
        heartbeat's case to detect."""
        if self.primary_url is None:
            return
        from tensorflow_examples_tpu.serving.router import _get_json

        status, body = _get_json(
            self.primary_url + "/replicas", self.interval_s * 2
        )
        if status != 200 or not isinstance(body.get("replicas"), list):
            return
        want: dict = {}
        for snap in body["replicas"]:
            if isinstance(snap, dict) and isinstance(
                snap.get("url"), str
            ):
                want[snap["url"].rstrip("/")] = snap.get("set", "base")
        if not want:
            return
        have = {r.url for r in self.router.replicas}
        for url, set_name in want.items():
            if url not in have:
                self.router.add_replica(url, set_name)
        for url in have - set(want):
            self.router.remove_replica(url)

    # ------------------------------------------------------- promotion

    def promote(self, detected_age_s: float = 0.0) -> None:
        """Missed-heartbeat takeover (idempotent)."""
        if self.promoted.is_set():
            return
        t0 = time.monotonic()
        token = self.lease.acquire()
        self.router.attach_lease(self.lease, token)
        log.warning(
            "STANDBY PROMOTED: primary heartbeat stale %.2fs past the "
            "%.2fs budget — fencing token now %d",
            detected_age_s, self.miss_budget_s, token,
        )
        self.router.start()  # synchronous first sweep: /health rebuild
        if self.journal is not None:
            self.journal.refresh()
        self.replayed = self.router.replay_incomplete()
        self.takeover_latency_s = time.monotonic() - t0
        reg = self.router.registry
        reg.counter("router/takeover_total").inc()
        reg.gauge("router/takeover_latency_s").set(
            self.takeover_latency_s
        )
        self.promoted.set()
        log.warning(
            "takeover complete in %.3fs (%d incomplete intent(s) "
            "replayed)", self.takeover_latency_s, self.replayed,
        )
        if self.on_promote is not None:
            self.on_promote(self)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
