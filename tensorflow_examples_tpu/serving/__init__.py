"""Inference serving engine (ISSUE 5 tentpole).

The training half of the repo can fit, survive faults, and observe
itself; this package opens the inference half: load a trained
checkpoint and serve concurrent generate/classify requests at
TPU-friendly static shapes.

Layout (one module per concern, mirroring the training stack):

* ``kv_cache.py``  — preallocated slot-granular KV cache pool with
  per-slot length tracking and the variable-length decode attention
  that reads it (the per-slot generalization of
  ``ops/decode.flash_decode_attention``'s populated-prefix contract),
  plus its gather-by-block-table path for the paged pool.
* ``paged_kv.py``  — ISSUE 8: the block-paged pool behind the same
  interface — free-list block allocator with loud exhaustion, prefix
  cache reusing immutable full prompt blocks (shared system prompts
  prefill once), optional int8 KV with per-block scales.
* ``router.py``    — ISSUE 8/10: the fleet tier — an HTTP router over
  N engine replicas with load-aware dispatch from ``/health`` probes,
  drain-aware rollout, per-replica circuit breakers, bounded
  retry-with-backoff, optional hedged dispatch, in-flight failover on
  replica death, and canary per-set records for ``tools/run_diff.py``.
* ``supervisor.py`` — ISSUE 10: replica supervision — detect a dead or
  stuck replica, restart it (process- or in-proc), re-admit to the
  router only after ``/health`` goes green.
* ``chaos.py``     — ISSUE 10: the serving chaos harness — restartable
  in-proc replicas the fault engine (``utils/faults.py`` serve specs)
  can crash/slow/starve deterministically, assembled as a
  :class:`~.chaos.ChaosFleet` (replicas + hardened router +
  supervisor) for the chaos acceptance tier and ``serve_bench
  --chaos``.
* ``engine.py``    — the compiled serving step: bucketed prefill +
  fixed-shape continuous decode, warmed up ahead of traffic over the
  padding-bucket ladder and wrapped in the PR-3 recompilation sentinel
  so steady-state serving is provably zero-recompile. ISSUE 11 adds
  the speculative ``verify_k`` rungs (score k draft tokens in one
  forward, commit the longest agreeing prefix, token-identical by
  per-position sampling keys) and the ``attention="paged_flash"``
  fused Pallas paged-decode kernel (``ops/paged_decode.py``).
* ``scheduler.py``  — ISSUE 12: cache-aware fleet scheduling
  primitives — content-addressed prefix chain keys (the affinity hash
  the router matches prompts against replica digests with), the
  block-aligned chunk planner behind chunked prefill admission, and
  the serialized KV-page wire format of the disaggregated
  prefill->decode handoff.
* ``speculative.py`` — ISSUE 11: the draft side of speculative
  decoding — the self-speculative n-gram ``DraftSource`` (a small
  draft model plugs into the same interface) and the deterministic
  acceptance rule.
* ``batcher.py``   — the continuous-batching request queue: admission
  control, max-batch/max-delay coalescing, per-request deadlines,
  bounded-queue backpressure with a load-shed counter, futures back to
  callers; with speculation on, the decode step becomes draft-propose/
  verify-commit with per-request acceptance accounting.
* ``frontend.py``  — stdlib HTTP endpoints (``/generate`` ``/classify``
  ``/metrics`` ``/health`` ``/window``) + SIGTERM drain with
  resilience-layer parity (reuses ``train.resilience.PreemptionGuard``).

``tools/serve_bench.py`` drives the whole stack closed-loop and banks a
BENCH-style JSON record; ``docs/serving.md`` is the operator guide.
"""

from tensorflow_examples_tpu.serving.batcher import (  # noqa: F401
    ContinuousBatcher,
    DeadlineExceeded,
    Draining,
    QueueFull,
    Request,
)
from tensorflow_examples_tpu.serving.engine import (  # noqa: F401
    InferenceEngine,
    ServeConfig,
)
from tensorflow_examples_tpu.serving.frontend import (  # noqa: F401
    ServingFrontend,
    run_until_preempted,
)
from tensorflow_examples_tpu.serving.kv_cache import KVCachePool  # noqa: F401
from tensorflow_examples_tpu.serving.paged_kv import (  # noqa: F401
    BlockExhausted,
    PagedKVPool,
)
from tensorflow_examples_tpu.serving.router import (  # noqa: F401
    Router,
    RouterConfig,
    RouterFrontend,
)

# supervisor.py / chaos.py are imported lazily by their consumers
# (tools/serve_fleet.py, serve_bench --chaos, tests/test_chaos.py) —
# importing them here would drag the chaos machinery into every
# serving import.
