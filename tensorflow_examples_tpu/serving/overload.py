"""Brownout overload controller: graceful degradation under load
(ISSUE 13 tentpole (2)).

PR 9 made replica *failure* a normal input; this module does the same
for *load*. When a flash crowd outruns the fleet, the failure mode
must not be undifferentiated 503s for everyone — it must be an ordered
ladder of cheapened service, walked one rung at a time and walked back
down as pressure clears:

    level 0  normal
    level 1  shed batch       — new batch-class submits are load-shed
                                (503, retryable); interactive flows
    level 2  cap tokens       — + generate requests are capped at
                                ``brownout_max_new_tokens`` (streams
                                stay a PREFIX of the uncapped stream —
                                ``truncated="brownout"`` says so)
    level 3  no speculation   — + the batcher skips draft/verify work
                                (plain 1-token decode steps: less
                                compute per step, same tokens)
    level 4  shed interactive — + new interactive submits are shed:
                                the last rung before falling over

The controller is a pure host-side state machine the batcher loop
ticks once per iteration with the signals the ISSUE names — queue
depth, KV occupancy, and a recent-window TTFT p95 — and it applies
**hysteresis** in both directions: one rung per ``hold_s`` on the way
up (an overloaded tick escalates progressively, not 0->4), and a rung
down only after every signal has stayed below the clear watermark
(``clear_frac`` x its high watermark) for a full ``hold_s``. Every
transition is counted (``serving/brownout_transitions_total``),
logged, gauged (``serving/brownout_level``) and kept in ``events`` for
the acceptance tier; the frontend's ``/health`` exposes the level so
the router (and the autoscaler reading the router's view) can see a
browning-out replica before it sheds.

Wired knobs live on ``ServeConfig`` (``brownout*``); the controller is
off by default — ``serve_bench --traffic`` and the overload tier turn
it on explicitly.
"""

from __future__ import annotations

import collections
import logging
import threading
import time

log = logging.getLogger(__name__)

# The ladder, in escalation order. Index == level.
LADDER = (
    "normal",            # 0
    "shed_batch",        # 1
    "cap_tokens",        # 2
    "no_spec",           # 3
    "shed_interactive",  # 4
)
MAX_LEVEL = len(LADDER) - 1

# Level thresholds the enforcement sites key on.
LEVEL_SHED_BATCH = 1
LEVEL_CAP_TOKENS = 2
LEVEL_NO_SPEC = 3
LEVEL_SHED_INTERACTIVE = 4

# Recent-window TTFT samples kept for the p95 signal.
_TTFT_WINDOW = 256
_TTFT_WINDOW_S = 5.0

# Transition-event tail kept for observability. A replica flapping at
# the hysteresis boundary transitions ~2/hold_s forever; the durable
# count lives in _transitions + the registry counter, so the event
# list can stay bounded in a weeks-long serving process.
_MAX_EVENTS = 4096


class OverloadController:
    """The brownout ladder as a tickable state machine.

    Single-writer by design: :meth:`update` runs on the batcher loop
    thread. ``level`` reads are lock-free int loads (submit() on
    frontend threads reads it), ``note_ttft`` takes the small sample
    lock only.
    """

    def __init__(
        self,
        *,
        registry,
        enabled: bool = True,
        queue_hi: int = 16,
        kv_hi: float = 0.92,
        ttft_hi_s: float = 0.0,      # 0 disables the TTFT signal
        clear_frac: float = 0.5,
        hold_s: float = 0.5,
        max_new_tokens_cap: int = 8,
        clock=time.monotonic,
    ):
        self.registry = registry
        self.enabled = bool(enabled)
        self.queue_hi = max(1, int(queue_hi))
        self.kv_hi = float(kv_hi)
        self.ttft_hi_s = float(ttft_hi_s)
        self.clear_frac = float(clear_frac)
        self.hold_s = float(hold_s)
        self.max_new_tokens_cap = max(1, int(max_new_tokens_cap))
        self._clock = clock
        self.level = 0
        # (wall_unix, from_level, to_level, reason) — the acceptance
        # tier asserts engage-then-clear off this. Bounded: the oldest
        # half is dropped past _MAX_EVENTS; _transitions keeps the
        # full count.
        self.events: list[tuple[float, int, int, str]] = []
        self._transitions = 0
        # Backdated one hold: the FIRST hot tick escalates immediately;
        # the hold paces successive rungs, not the initial reaction.
        self._last_change = clock() - self.hold_s
        self._clear_since: float | None = None
        self._ttft_lock = threading.Lock()
        # Fed by frontend threads (note_ttft at every TTFT record),
        # read by the batcher loop's tick.
        self._ttft: collections.deque = collections.deque(  # guard: self._ttft_lock
            maxlen=_TTFT_WINDOW
        )
        registry.gauge("serving/brownout_level").set(0)

    # --------------------------------------------------------- signals

    def note_ttft(self, value_s: float) -> None:
        """Feed one TTFT observation (the batcher calls this where it
        records the TTFT histogram)."""
        with self._ttft_lock:
            self._ttft.append((self._clock(), float(value_s)))

    def ttft_p95(self, window_s: float = _TTFT_WINDOW_S) -> float | None:
        """p95 over the TTFT samples of the last ``window_s`` seconds
        (None with no recent sample) — a *recent* pressure signal, not
        the run-cumulative histogram."""
        cutoff = self._clock() - window_s
        with self._ttft_lock:
            vals = sorted(v for t, v in self._ttft if t >= cutoff)
        if not vals:
            return None
        return vals[min(len(vals) - 1, int(0.95 * len(vals)))]

    # ----------------------------------------------------- enforcement

    def sheds(self, slo: str) -> bool:
        """Does the current level shed NEW submits of this class?"""
        if slo == "batch":
            return self.level >= LEVEL_SHED_BATCH
        return self.level >= LEVEL_SHED_INTERACTIVE

    def max_new_cap(self) -> int | None:
        """Generation-budget cap at the current level (None = no cap)."""
        if self.level >= LEVEL_CAP_TOKENS:
            return self.max_new_tokens_cap
        return None

    def spec_disabled(self) -> bool:
        """Level 3+: skip speculation's extra verify compute."""
        return self.level >= LEVEL_NO_SPEC

    # ------------------------------------------------------------ tick

    def update(self, *, queue_depth: int, kv_occupancy: float) -> int:
        """One controller tick (batcher loop thread). Returns the
        (possibly changed) level."""
        if not self.enabled:
            return 0
        now = self._clock()
        p95 = self.ttft_p95() if self.ttft_hi_s > 0 else None
        hot_reasons = []
        if queue_depth >= self.queue_hi:
            hot_reasons.append(
                f"queue_depth {queue_depth} >= {self.queue_hi}"
            )
        if kv_occupancy >= self.kv_hi:
            hot_reasons.append(
                f"kv_occupancy {kv_occupancy:.2f} >= {self.kv_hi:.2f}"
            )
        if p95 is not None and p95 >= self.ttft_hi_s:
            hot_reasons.append(
                f"ttft_p95 {p95:.3f}s >= {self.ttft_hi_s:.3f}s"
            )
        clear = (
            queue_depth <= self.clear_frac * self.queue_hi
            and kv_occupancy <= self.clear_frac * self.kv_hi
            and (
                self.ttft_hi_s <= 0
                or p95 is None
                or p95 <= self.clear_frac * self.ttft_hi_s
            )
        )
        if hot_reasons:
            self._clear_since = None
            if (
                self.level < MAX_LEVEL
                and now - self._last_change >= self.hold_s
            ):
                self._step(+1, "; ".join(hot_reasons), now)
        elif clear and self.level > 0:
            if self._clear_since is None:
                self._clear_since = now
            elif now - self._clear_since >= self.hold_s:
                self._step(-1, "pressure cleared", now)
                self._clear_since = now  # a full hold per rung down
        else:
            self._clear_since = None
        return self.level

    def _step(self, delta: int, reason: str, now: float) -> None:
        old, new = self.level, self.level + delta
        self.level = new
        self._last_change = now
        self._transitions += 1
        self.events.append((time.time(), old, new, reason))
        if len(self.events) > _MAX_EVENTS:
            del self.events[: _MAX_EVENTS // 2]
        reg = self.registry
        reg.counter("serving/brownout_transitions_total").inc()
        if delta > 0:
            reg.counter("serving/brownout_escalations_total").inc()
        reg.gauge("serving/brownout_level").set(new)
        msg = (
            "BROWNOUT level %d -> %d (%s): %s",
            old, new, LADDER[new], reason,
        )
        if delta > 0:
            log.warning(*msg)
        else:
            log.info(*msg)

    # ------------------------------------------------------------ stats

    def transitions(self) -> int:
        # max() keeps harness-injected events (tests seed the list
        # directly) counted alongside real _step transitions after the
        # event tail starts dropping.
        return max(self._transitions, len(self.events))
