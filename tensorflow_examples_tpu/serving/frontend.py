"""HTTP frontend + SIGTERM drain for the serving stack.

Extends the ISSUE-4 stdlib ``http.server`` pattern
(``telemetry/serve.py``) with the request side: POST endpoints that
feed the continuous batcher and block on its futures, next to the same
observability surface a training process exposes.

Endpoints:

* ``POST /generate`` — body ``{"prompt": [ids], "max_new_tokens": n,
  "temperature": t, "top_k": k, "seed": s, "eos_id": id,
  "deadline_s": d, "slo": "interactive"|"batch"}`` (all but ``prompt``
  optional; ``"text"`` may replace ``prompt`` when the frontend was
  built with a tokenizer). ``slo`` is the ISSUE 13 service class:
  batch queues behind interactive and absorbs shedding/preemption
  first.
  Replies ``{"tokens": [...], "prompt_len": n, "truncated": null,
  "queue_wait_s": ..., "ttft_s": ..., "total_s": ...}`` (+ ``"text"``
  with a tokenizer).
* ``POST /classify`` — same request shape (no generation knobs);
  replies the top-n next-token distribution
  ``{"top": [{"token": id, "logprob": lp}, ...]}``.
* ``POST /prefill`` / ``POST /resume`` — the disaggregated-role
  handoff pair (ISSUE 12, paged pool only). ``/prefill`` runs the
  prompt to completion-of-prefill and replies ``{"first_token": id,
  "pages": {...}}`` (``serving/scheduler.py`` wire format, int8 scales
  included); ``/resume`` takes the same generate body plus
  ``pages``/``first_token`` and continues the decode stream —
  token-identical to a mixed replica serving the whole request. The
  router orchestrates the pair; roles are advisory, so every replica
  still answers a full ``/generate`` (that is what makes role failover
  a plain in-flight failover).
* ``GET /metrics`` — the registry as Prometheus text
  (``telemetry.serve.render_prometheus``): the ``serving/*`` counters
  and gauges plus the latency summaries — ``serving_queue_wait``,
  ``serving_prefill``, ``serving_ttft``, ``serving_tpot``,
  ``serving_e2e`` — each with p50/p95/p99 quantiles.
* ``GET /health`` — JSON: draining flag, active/queued requests, KV
  occupancy, post-warmup recompile count, watchdog phase when the
  batcher runs one. 503 once draining (a load balancer stops routing
  here the moment the drain starts).
* ``GET /window`` — the latest schema-v4 ``kind="serving"`` stats line
  (``ContinuousBatcher.stats_line``).
* ``GET /series`` — the in-process time-series store (ISSUE 19):
  ring-buffered history of every instrument, sampled on the stats
  loop's cadence, with p50/p95/p99 rollups per series.

Status mapping (the flow-control contract, outermost first):
``QueueFull``/``Draining`` -> 503 (retry elsewhere/later, body says
which), ``DeadlineExceeded`` -> 504, admission ``ValueError``/bad JSON
-> 400, anything else -> 500 with the exception class named.

**SIGTERM drain** (resilience-layer parity with
``train.resilience.PreemptionGuard``): :func:`run_until_preempted`
installs the guard, serves until SIGTERM/SIGINT, then (1) flips the
batcher to draining — new submits raise ``Draining``, the frontend
returns 503 — (2) waits for every accepted request to finish, (3)
closes the ports, (4) returns exit code 0. A second signal force-quits
through the guard's escalation path, exactly like training.
"""

from __future__ import annotations

import concurrent.futures
import http.server
import json
import logging
import socket
import threading
import time

from tensorflow_examples_tpu.serving.batcher import (
    ContinuousBatcher,
    DeadlineExceeded,
    Draining,
    QueueFull,
    Request,
)
from tensorflow_examples_tpu.serving.paged_kv import BlockExhausted
from tensorflow_examples_tpu.telemetry import timeseries as timeseries_mod
from tensorflow_examples_tpu.telemetry.serve import (
    json_safe,
    render_prometheus,
)
from tensorflow_examples_tpu.utils import faults as faults_mod
# Module-level on purpose: a lazy import inside run_until_preempted would
# leave a multi-second window after "ready" during which SIGTERM still
# hits the default handler (import of the train package is slow) — the
# guard must be installable the instant the caller asks.
from tensorflow_examples_tpu.train.resilience import PreemptionGuard

log = logging.getLogger(__name__)

_MAX_BODY = 1 << 20  # 1 MiB of JSON is already a pathological prompt
# The /resume body carries a whole prompt's serialized KV pages —
# sized for the repo's own worst case, not a guess: gpt2 at fp32 is
# 2 (k+v) * 12 layers * 12 heads * 64 head_dim * 4 B ~= 72 KiB per
# token, so a max_len=1024 prompt serializes to ~75 MiB raw and
# ~100 MiB after base64. A cap below that would 413 exactly the
# long-prompt handoffs disaggregation exists for, silently degrading
# every such request to double-prefill fallback.
_MAX_RESUME_BODY = 256 << 20


class _TrackingHTTPServer(http.server.ThreadingHTTPServer):
    """ThreadingHTTPServer that keeps the set of in-flight client
    connections, so :meth:`ServingFrontend.abort` can RESET them —
    simulating a replica process dying mid-request (clients observe a
    transport failure, never a polite HTTP status). The chaos harness
    (serving/chaos.py) and the ``crash@R:N`` serve fault are the
    consumers; normal shutdown never touches this."""

    # An overloaded replica must SHED (a 503 the class queues decide),
    # never silently drop connections: the stdlib default accept
    # backlog of 5 overflows under a flash crowd's connection burst
    # and turns correct shedding into spurious transport failures
    # (ISSUE 13).
    request_queue_size = 128

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.conn_lock = threading.Lock()
        self.live_connections: set = set()

    def process_request(self, request, client_address):
        with self.conn_lock:
            self.live_connections.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self.conn_lock:
            self.live_connections.discard(request)
        super().shutdown_request(request)


def _request_from_body(body: dict, *, kind: str, tokenizer=None) -> Request:
    """Validated JSON body -> :class:`Request` (raises ValueError with a
    client-facing message on any malformed field)."""
    if not isinstance(body, dict):
        raise ValueError("body must be a JSON object")
    prompt = body.get("prompt")
    if prompt is None and "text" in body:
        if tokenizer is None:
            raise ValueError(
                "this server has no tokenizer; send token ids as 'prompt'"
            )
        if not isinstance(body["text"], str):
            raise ValueError("'text' must be a string")
        prompt = tokenizer.encode(body["text"])
    if (
        not isinstance(prompt, list)
        or not prompt
        or not all(isinstance(t, int) and not isinstance(t, bool)
                   for t in prompt)
    ):
        raise ValueError("'prompt' must be a non-empty list of token ids")
    known = {
        "prompt", "text", "max_new_tokens", "temperature", "top_k",
        "seed", "eos_id", "deadline_s", "top_n", "slo",
        # ISSUE 16: idempotency / resume markers. The ROUTER consumes
        # these (journal dedupe, replay-and-skip) and strips them
        # before dispatch, but a replica must also tolerate them so a
        # client talking straight to one frontend isn't rejected —
        # accepted and ignored here (a single replica regenerates
        # deterministically anyway).
        "request_id", "resume_from",
        # ISSUE 18: the router's traceparent-style context. A traced
        # request's replica-side spans come back in the reply under
        # "trace_spans"; an untraced body costs nothing.
        "trace",
        # ISSUE 19: the synthetic canary prober's tag. The router
        # strips it before dispatch, but the prober also probes
        # replicas DIRECTLY (per-replica black-box TTFT), so a replica
        # must tolerate it — accepted and ignored here (a replica has
        # no journal or organic-vs-probe accounting to protect).
        "probe",
    }
    if kind == "resume":
        known |= {"pages", "first_token"}
    elif kind == "prefill":
        # Delta handoff (ISSUE 15): the router's digest exchange —
        # leading prompt tokens the resume-side replica already
        # caches, so the export leaves those pages off the wire.
        known |= {"skip_tokens"}
    unknown = set(body) - known
    if unknown:
        raise ValueError(f"unknown fields: {sorted(unknown)}")
    slo = body.get("slo", "interactive")
    if slo not in ("interactive", "batch"):
        raise ValueError(
            "'slo' must be 'interactive' or 'batch'"
        )
    pages = first_token = None
    if kind == "resume":
        pages = body.get("pages")
        if not isinstance(pages, dict):
            raise ValueError("'pages' must be the prefill replica's "
                             "page payload object")
        first_token = body.get("first_token")
        if not isinstance(first_token, int) or isinstance(
            first_token, bool
        ):
            raise ValueError("'first_token' must be a token id")

    def number(name, default, cls=float, minimum=None, maximum=None):
        v = body.get(name, default)
        if v is None:
            if default is None:  # nullable fields (eos_id, deadline_s)
                return None
            raise ValueError(f"'{name}' must be a number")
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ValueError(f"'{name}' must be a number")
        if cls is int and isinstance(v, float) and not v.is_integer():
            raise ValueError(f"'{name}' must be an integer")
        v = cls(v)
        if minimum is not None and v < minimum:
            raise ValueError(f"'{name}' must be >= {minimum}")
        if maximum is not None and v > maximum:
            raise ValueError(f"'{name}' must be <= {maximum}")
        return v

    return Request(
        prompt=[int(t) for t in prompt],
        max_new_tokens=number("max_new_tokens", 16, int, 1),
        temperature=number("temperature", 0.0, float, 0.0),
        top_k=number("top_k", 0, int, 0),
        seed=number("seed", 0, int, 0, maximum=2**31 - 1),
        eos_id=number("eos_id", None, int, 0),
        deadline_s=number("deadline_s", None, float, 0.0),
        kind=kind,
        classify_top_n=number("top_n", 5, int, 1),
        pages=pages,
        first_token=first_token,
        skip_tokens=(
            number("skip_tokens", 0, int, 0) if kind == "prefill" else 0
        ),
        slo=slo,
        # Tolerant parse: a malformed context disables tracing for
        # this request, never fails it (same contract as the router's
        # TraceContext.from_wire).
        trace=(
            body["trace"]
            if isinstance(body.get("trace"), dict)
            and isinstance(body["trace"].get("trace_id"), str)
            and body["trace"]["trace_id"]
            else None
        ),
    )


class ServingFrontend:
    """The serving process's HTTP surface. One daemon-threaded
    ``ThreadingHTTPServer``; request handlers block on batcher futures
    (scrape endpoints never do), so a slow generation cannot starve
    ``/metrics``."""

    def __init__(
        self,
        batcher: ContinuousBatcher,
        *,
        port: int = 0,
        bind_host: str = "",
        tokenizer=None,
    ):
        self.batcher = batcher
        self.tokenizer = tokenizer
        self.requested_port = int(port)
        self.bind_host = bind_host
        self.port: int | None = None
        self._httpd: http.server.ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        # In-process time-series store (ISSUE 19), served as
        # GET /series. The frontend owns no cadence of its own — the
        # serving process's stats loop calls ``series.sample()`` on
        # its tick (examples/gpt2/serve.py), exactly like the stats
        # line itself.
        self.series = timeseries_mod.TimeSeriesStore(batcher.registry)

    @property
    def replica_id(self) -> int:
        """This stack's replica index in a fleet (0 standalone) — the
        key the serve fault engine targets (``utils/faults.py``)."""
        return int(getattr(self.batcher.engine, "replica_id", 0))

    # ------------------------------------------------------------ payloads

    def handle_request(self, body: dict, *, kind: str) -> tuple[int, dict]:
        """(status, reply) for one generate/classify body — the HTTP
        handler minus the socket, so tests and the bench can drive the
        full admission/serialization path in-process."""
        try:
            req = _request_from_body(
                body, kind=kind, tokenizer=self.tokenizer
            )
        except ValueError as e:
            return 400, {"error": str(e)}
        try:
            fut = self.batcher.submit(req)
            result = fut.result(
                timeout=self.batcher.engine.cfg.request_timeout_s
            )
        except Draining as e:
            return 503, {"error": str(e), "draining": True}
        except QueueFull as e:
            # "shed": true marks a LOAD shed (queue full / brownout) —
            # what lets serve_bench (ISSUE 13 satellite) count correct
            # shedding apart from transport failures in its records.
            return 503, {"error": str(e), "retry": True, "shed": True}
        except BlockExhausted as e:
            # Paged-KV capacity shed: same retry contract as QueueFull,
            # but "exhausted" marks it apart — a wedged-full pool can
            # shed FOREVER (leaked refcounts, stuck long requests), so
            # the router still counts these against the circuit breaker
            # where a policy shed (queue/brownout, transient by
            # construction) does not.
            return 503, {
                "error": str(e), "retry": True, "shed": True,
                "exhausted": True,
            }
        except DeadlineExceeded as e:
            return 504, {"error": str(e)}
        except ValueError as e:
            return 400, {"error": str(e)}
        except concurrent.futures.TimeoutError:
            return 504, {
                "error": (
                    "request timed out after "
                    f"{self.batcher.engine.cfg.request_timeout_s}s"
                )
            }
        except Exception as e:  # noqa: BLE001 — surface, don't crash
            log.exception("request failed")
            return 500, {"error": f"{type(e).__name__}: {e}"}
        reply: dict = {
            "prompt_len": result.prompt_len,
            "truncated": result.truncated,
            "queue_wait_s": result.queue_wait_s,
            "ttft_s": result.ttft_s,
            "total_s": result.total_s,
        }
        if kind == "classify":
            reply["top"] = result.top
        elif kind == "prefill":
            # Disaggregated handoff (ISSUE 12): the product is the KV
            # pages + the first sampled token, which the router ships
            # to a decode replica's /resume.
            reply["first_token"] = result.tokens[0]
            reply["pages"] = result.pages
        else:
            reply["tokens"] = result.tokens
            if self.tokenizer is not None:
                reply["text"] = self.tokenizer.decode(result.tokens)
        if result.spans:
            # ISSUE 18: the replica's per-request spans ride the reply
            # — the router (or a direct client) adopts them into the
            # request's trace tree. No shared memory assumed, so
            # in-proc and cross-process fleets stitch identically.
            reply["trace_spans"] = result.spans
        return 200, reply

    def health_payload(self) -> tuple[int, dict]:
        batcher = self.batcher
        engine = batcher.engine
        body = {
            "ok": not batcher.draining,
            "draining": batcher.draining,
            # Mid-chunked-prefill requests ARE active load (each one
            # stalls a chunk per decode-loop iteration) — the router's
            # load score and the affinity guard must see them.
            "active_requests": (
                len(batcher._active) + len(batcher._prefilling)
            ),
            "queue_depth": batcher.queue_depth(),
            "slots": engine.pool.num_slots,
            "kv_occupancy": engine.pool.occupancy,
            "post_warmup_recompiles": engine.post_warmup_recompiles(),
            "warmed": engine.warmed,
        }
        body["role"] = getattr(engine.cfg, "role", "mixed")
        # Brownout state (ISSUE 13): the router's probe and the
        # autoscaler both read the level here — a browning-out replica
        # is visible to the fleet BEFORE it sheds interactive traffic.
        body["brownout_level"] = int(batcher.brownout_level)
        body["brownout_transitions"] = int(
            batcher._overload.transitions()
        )
        paged = getattr(engine.pool, "paged_stats", None)
        if callable(paged):
            stats = paged()
            body["kv_block_occupancy"] = stats["kv_block_occupancy"]
            body["kv_slot_occupancy"] = stats["kv_slot_occupancy"]
            body["prefix_hit_rate"] = stats["prefix_hit_rate"]
        digest = getattr(engine.pool, "prefix_digest", None)
        if callable(digest):
            # The affinity summary (ISSUE 12): content chain keys of
            # the cached prefix blocks — what the router's
            # prefix-affinity dispatch matches prompts against.
            d = digest()
            body["prefix_block_size"] = engine.pool.block_size
            body["prefix_blocks"] = d["blocks"]
            body["prefix_chains"] = d["chains"]
            body["prefix_digest"] = d["keys"]
            # ISSUE 13 satellite: say when the digest is capped, so
            # affinity misses on very large caches are diagnosable.
            body["digest_truncated"] = bool(d.get("truncated"))
            if d.get("bloom"):
                # ISSUE 15 satellite: past the cap the FULL chain-key
                # set still routes — as a bloom filter the router
                # matches against instead of the truncated list.
                body["prefix_bloom"] = d["bloom"]
        wd = batcher._watchdog
        if wd is not None:
            status = wd.status()
            body.update(
                phase=status["phase"],
                phase_age_secs=status["phase_age_secs"],
                stalled_secs=status["stalled_secs"],
            )
        return (200 if body["ok"] else 503), body

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "ServingFrontend":
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, status, content_type, payload: bytes):
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _send_json(self, status, obj):
                self._send(
                    status,
                    "application/json",
                    (json.dumps(json_safe(obj)) + "\n").encode(),
                )

            def do_POST(self):  # noqa: N802 - http.server contract
                path = self.path.split("?", 1)[0].rstrip("/")
                feng = faults_mod.serve_active()
                if feng is not None and feng.transport_fault(
                    server.replica_id
                ):
                    # Injected transport fault (ISSUE 10): drop the
                    # request with no response bytes — the client sees
                    # a reset, exactly like a died-mid-request process.
                    self.close_connection = True
                    return
                if path not in ("/generate", "/classify", "/prefill",
                                "/resume"):
                    self._send_json(
                        404,
                        {"error": "POST endpoints: /generate /classify "
                                  "/prefill /resume"},
                    )
                    return
                max_body = (
                    _MAX_RESUME_BODY if path == "/resume" else _MAX_BODY
                )
                try:
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                    except ValueError:
                        n = -1
                    if n < 0:
                        self._send_json(
                            400, {"error": "bad Content-Length header"}
                        )
                        return
                    if n > max_body:
                        self._send_json(
                            413, {"error": f"body exceeds {max_body} bytes"}
                        )
                        return
                    try:
                        body = json.loads(self.rfile.read(n) or b"{}")
                    except json.JSONDecodeError as e:
                        self._send_json(400, {"error": f"bad JSON: {e}"})
                        return
                    status, reply = server.handle_request(
                        body, kind=path[1:]
                    )
                    self._send_json(status, reply)
                except ConnectionError:  # client went away mid-write
                    pass

            def do_GET(self):  # noqa: N802 - http.server contract
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        self._send(
                            200,
                            "text/plain; version=0.0.4; charset=utf-8",
                            render_prometheus(
                                server.batcher.registry,
                                exemplars=server.batcher.exemplars,
                            ).encode(),
                        )
                    elif path == "/health":
                        feng = faults_mod.serve_active()
                        if feng is not None and feng.health_fault(
                            server.replica_id
                        ):
                            # Injected poisoned /health (ISSUE 10):
                            # non-JSON garbage with a 200 — the probe
                            # loop must mark this replica unhealthy,
                            # never crash.
                            self._send(
                                200, "application/json",
                                b"<<<not json at all>>>",
                            )
                            return
                        self._send_json(*server.health_payload())
                    elif path == "/window":
                        self._send_json(200, server.batcher.stats_line())
                    elif path == "/series":
                        # Ring-buffered instrument history (ISSUE 19),
                        # sampled by the stats loop's tick.
                        self._send_json(
                            200, server.series.to_payload()
                        )
                    else:
                        self._send(
                            404,
                            "text/plain; charset=utf-8",
                            b"GET: /metrics /health /window /series   "
                            b"POST: /generate /classify /prefill "
                            b"/resume\n",
                        )
                except ConnectionError:
                    pass

            def log_message(self, fmt, *args):  # quiet under load
                log.debug("serving frontend: " + fmt, *args)

        self._httpd = _TrackingHTTPServer(
            (self.bind_host, self.requested_port), Handler
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="serving-frontend",
            daemon=True,
        )
        self._thread.start()
        log.info(
            "serving frontend live on port %d "
            "(POST /generate /classify; GET /metrics /health /window)",
            self.port,
        )
        return self

    def url(self, path: str = "/generate") -> str:
        host = self.bind_host or "127.0.0.1"
        return f"http://{host}:{self.port}{path}"

    def close(self) -> None:
        """Idempotent; stops accepting connections (in-flight handler
        threads finish their writes — they hold batcher futures, which
        the drain resolves first)."""
        with self._lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5)

    def abort(self) -> None:
        """Die like a killed process (the chaos harness's crash verb):
        stop listening AND reset every in-flight client connection, so
        callers observe a transport failure — never a drained 503 or a
        polite error body. Handler threads are left to hit the dead
        sockets on their own (their writes raise ConnectionError, which
        the handlers already swallow); nothing is joined. Safe from any
        thread, including the batcher loop mid-decode."""
        with self._lock:
            httpd, self._httpd = self._httpd, None
            self._thread = None
        if httpd is None:
            return
        try:
            httpd.shutdown()
            httpd.server_close()
        except OSError:  # pragma: no cover - best-effort teardown
            pass
        with httpd.conn_lock:
            conns = list(httpd.live_connections)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # already gone


def run_until_preempted(
    frontend: ServingFrontend,
    *,
    poll_s: float = 0.2,
    drain_timeout_s: float = 60.0,
    guard=None,
) -> int:
    """Serve until SIGTERM/SIGINT, then drain and return 0.

    The serving mirror of the trainer's preemption contract
    (``train.resilience.PreemptionGuard``): first signal starts a clean
    drain — the batcher rejects new work (frontend answers 503), every
    already-accepted request runs to completion, ports close, exit 0 —
    and a second signal force-quits. ``guard`` is injectable for tests
    (anything with ``.install()`` and ``.requested``).
    """
    if guard is None:
        guard = PreemptionGuard()
    guard.install()
    batcher = frontend.batcher
    try:
        while not guard.requested:
            time.sleep(poll_s)
        log.warning(
            "preemption requested: draining %d active + %d queued requests",
            len(batcher._active), batcher.queue_depth(),
        )
        batcher.registry.counter("serving/preemptions").inc()
        batcher.close(drain=True, timeout=drain_timeout_s)
        log.info("drain complete; shutting down frontend")
        return 0
    finally:
        frontend.close()
        if not batcher._stop.is_set():
            batcher.close(drain=False)
        if hasattr(guard, "uninstall"):
            guard.uninstall()
