"""Replica supervision: detect a dead/stuck replica, restart it,
re-admit it only after /health goes green (ISSUE 10 tentpole (1)).

PR 8's router already *stops dispatching* to a replica that dies (probe
failures rotate it out; the ISSUE 10 circuit breaker ejects it on
dispatch failures) — but nothing brought it back: a crashed replica
left a hole in the fleet until an operator noticed. This module is the
missing loop, the serving mirror of the trainer's PreemptionGuard
discipline: **failure is a normal input**.

The supervisor owns a set of :class:`ReplicaHandle`-shaped objects —
anything with ``url``, ``alive()`` and a blocking ``restart()`` — and a
background thread that, per sweep:

1. **Detects** a dead or stuck replica: ``alive()`` false (process
   exit / in-proc kill), or its ``/health`` not answering green for
   longer than ``health_stall_s`` (a wedged process that still holds
   its socket — the serving version of the training watchdog's hung
   step).
2. **Quarantines** it in the router (``Router.quarantine`` — no
   dispatch no matter what the probe/breaker state says) so the
   restart window cannot eat requests.
3. **Restarts** it via the handle — for the in-proc chaos replicas
   (serving/chaos.py) that means a fresh engine + **full AOT warmup**
   of the bucket ladder; for :class:`ProcessReplica` a respawned
   process whose own startup warms.
4. **Re-admits** it (``Router.readmit``) only once ``/health`` answers
   200 with ``ok: true`` — never a cold or half-warm replica; bumps
   ``router/restarts_total`` (the schema-v7 ``router_restarts``
   counter).

A handle that keeps dying is retried up to ``max_restarts`` times with
``restart_backoff_s`` between attempts, then left quarantined with an
ERROR — a crash-looping build must page an operator, not flap the
fleet forever. ``tools/serve_fleet.py --spawn`` wires this over real
processes; the chaos tier (tests/test_chaos.py, ``serve_bench
--chaos``) drives it in-proc.
"""

from __future__ import annotations

import logging
import shlex
import subprocess
import sys
import threading
import time

from tensorflow_examples_tpu.serving.router import Router, _get_json

log = logging.getLogger(__name__)


class ProcessReplica:
    """A replica that is a real child process (``serve_fleet --spawn``).

    ``cmd`` is the spawn command (string, ``shlex``-split; a ``{port}``
    placeholder receives ``port``). The process is expected to serve
    the PR 5 frontend surface on ``http://127.0.0.1:{port}``.
    """

    def __init__(self, cmd: str, *, port: int,
                 host: str = "127.0.0.1",
                 stop_timeout_s: float = 10.0):
        self.cmd = cmd
        self.port = int(port)
        self.url = f"http://{host}:{self.port}"
        self.stop_timeout_s = stop_timeout_s
        self._proc: subprocess.Popen | None = None

    def start(self) -> "ProcessReplica":
        argv = shlex.split(self.cmd.format(port=self.port))
        log.info("spawning replica %s: %s", self.url, argv)
        self._proc = subprocess.Popen(argv)
        return self

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def terminate(self) -> None:
        """SIGTERM (the replica's own drain path), escalate to SIGKILL
        after ``stop_timeout_s``."""
        proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=self.stop_timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=self.stop_timeout_s)

    def restart(self) -> None:
        self.terminate()
        self.start()

    def close(self) -> None:
        self.terminate()


class Supervisor:
    """Watch replicas, restart the dead/stuck ones, re-admit on green.

    ``handles`` maps replica URL -> handle; every URL must already be a
    replica of ``router``. Restarts run serially on the supervisor
    thread (one failure at a time is the design point; a correlated
    fleet-wide outage needs an operator anyway).
    """

    def __init__(
        self,
        router: Router,
        handles,
        *,
        poll_s: float = 0.25,
        health_stall_s: float = 5.0,
        health_timeout_s: float = 2.0,
        warm_timeout_s: float = 300.0,
        max_restarts: int = 5,
        restart_backoff_s: float = 0.5,
    ):
        self.router = router
        self.handles = {h.url.rstrip("/"): h for h in handles}
        for url in self.handles:
            if router._find(url) is None:
                raise ValueError(
                    f"supervised url {url} is not a router replica"
                )
        self.poll_s = poll_s
        self.health_stall_s = health_stall_s
        self.health_timeout_s = health_timeout_s
        self.warm_timeout_s = warm_timeout_s
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        # Completed restart cycles (reporting: serve_bench --chaos sums
        # this into router_restarts).
        self.restarts: dict[str, int] = {u: 0 for u in self.handles}
        # Failed attempts within the CURRENT incident — reset on every
        # successful readmit, so max_restarts bounds one crash-loop,
        # not the replica's whole lifetime (a replica independently
        # recovered N times must not be abandoned on failure N+1).
        self._attempts: dict[str, int] = {u: 0 for u in self.handles}
        self.given_up: set[str] = set()
        # Last role each replica's /health reported (ISSUE 12):
        # heterogeneous prefill/decode fleets are first-class, so an
        # incident log must say WHICH role went down — a dead prefill
        # replica stalls handoffs fleet-wide, not 1/N of traffic.
        self.roles: dict[str, str] = {u: "mixed" for u in self.handles}
        # (url, event) rows: "detected" / "restarted" / "readmitted" /
        # "gave_up" — the chaos tier asserts the transition sequence.
        self.events: list[tuple[str, str]] = []
        self._last_ok = {u: time.monotonic() for u in self.handles}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ sweep

    def _healthy(self, url: str) -> bool:
        status, body = _get_json(
            url + "/health", self.health_timeout_s
        )
        if status == 0:
            return False
        if isinstance(body.get("role"), str):
            self.roles[url] = body["role"]
        # Any well-formed HTTP answer means the process is responsive;
        # a 503 that is an orderly drain is NOT a stall (the replica is
        # finishing its work on purpose).
        return status == 200 or bool(body.get("draining"))

    def check_once(self) -> None:
        """One synchronous sweep (the loop body; tests call it
        directly for determinism)."""
        now = time.monotonic()
        for url, handle in self.handles.items():
            if url in self.given_up:
                continue
            if handle.alive() and self._healthy(url):
                self._last_ok[url] = time.monotonic()
                continue
            stalled = now - self._last_ok[url]
            if handle.alive() and stalled < self.health_stall_s:
                continue  # transient blip: give /health time to recover
            reason = (
                "process dead" if not handle.alive()
                else f"/health stalled {stalled:.1f}s"
            )
            log.warning(
                "SUPERVISOR: %s replica %s down (%s) — quarantining "
                "and restarting", self.roles.get(url, "mixed"), url,
                reason,
            )
            self.events.append((url, "detected"))
            self.router.quarantine(url)
            self._restart(url, handle)

    def _restart(self, url: str, handle) -> None:
        while self._attempts[url] < self.max_restarts:
            self._attempts[url] += 1
            try:
                handle.restart()  # blocking: respawn + re-warm the AOT
                #                   ladder before anything is re-admitted
            except Exception:  # noqa: BLE001 — a failed restart must
                # not kill the supervisor loop
                log.exception(
                    "SUPERVISOR: restart of %s failed (attempt %d/%d)",
                    url, self._attempts[url], self.max_restarts,
                )
                time.sleep(self.restart_backoff_s)
                continue
            self.events.append((url, "restarted"))
            if self._await_green(url):
                self._last_ok[url] = time.monotonic()
                self._attempts[url] = 0  # incident over: fresh budget
                self.restarts[url] += 1
                self.router.readmit(url)
                self.router.registry.counter(
                    "router/restarts_total"
                ).inc()
                self.events.append((url, "readmitted"))
                log.info(
                    "SUPERVISOR: replica %s restarted and re-admitted "
                    "(/health green)", url,
                )
                return
            log.warning(
                "SUPERVISOR: restarted %s never went green within "
                "%.1fs (attempt %d/%d)", url, self.warm_timeout_s,
                self._attempts[url], self.max_restarts,
            )
            time.sleep(self.restart_backoff_s)
        self.given_up.add(url)
        self.events.append((url, "gave_up"))
        log.error(
            "SUPERVISOR: giving up on %s after %d restart attempts — "
            "left quarantined; operator action required", url,
            self.max_restarts,
        )

    def _await_green(self, url: str) -> bool:
        deadline = time.monotonic() + self.warm_timeout_s
        while time.monotonic() < deadline and not self._stop.is_set():
            status, body = _get_json(
                url + "/health", self.health_timeout_s
            )
            if status == 200 and body.get("ok"):
                return True
            time.sleep(min(0.05, self.poll_s))
        return False

    # -------------------------------------------------------- lifecycle

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 — the watcher must survive
                log.exception("supervisor sweep failed")
            self._stop.wait(self.poll_s)

    def start(self) -> "Supervisor":
        self._thread = threading.Thread(
            target=self._loop, name="replica-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(10.0, self.warm_timeout_s))


def main_check(urls, timeout_s: float = 2.0) -> int:  # pragma: no cover
    """Tiny CLI helper: print each replica's health verdict (used by
    operators, not tests)."""
    rc = 0
    for url in urls:
        status, body = _get_json(
            url.rstrip("/") + "/health", timeout_s
        )
        ok = status == 200 and bool(body.get("ok"))
        print(f"{url}: {'OK' if ok else f'DOWN (status {status})'}")
        rc = rc or (0 if ok else 1)
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main_check(sys.argv[1:]))
