"""Replica supervision: detect a dead/stuck replica, restart it,
re-admit it only after /health goes green (ISSUE 10 tentpole (1)).

PR 8's router already *stops dispatching* to a replica that dies (probe
failures rotate it out; the ISSUE 10 circuit breaker ejects it on
dispatch failures) — but nothing brought it back: a crashed replica
left a hole in the fleet until an operator noticed. This module is the
missing loop, the serving mirror of the trainer's PreemptionGuard
discipline: **failure is a normal input**.

The supervisor owns a set of :class:`ReplicaHandle`-shaped objects —
anything with ``url``, ``alive()`` and a blocking ``restart()`` — and a
background thread that, per sweep:

1. **Detects** a dead or stuck replica: ``alive()`` false (process
   exit / in-proc kill), or its ``/health`` not answering green for
   longer than ``health_stall_s`` (a wedged process that still holds
   its socket — the serving version of the training watchdog's hung
   step).
2. **Quarantines** it in the router (``Router.quarantine`` — no
   dispatch no matter what the probe/breaker state says) so the
   restart window cannot eat requests.
3. **Restarts** it via the handle — for the in-proc chaos replicas
   (serving/chaos.py) that means a fresh engine + **full AOT warmup**
   of the bucket ladder; for :class:`ProcessReplica` a respawned
   process whose own startup warms.
4. **Re-admits** it (``Router.readmit``) only once ``/health`` answers
   200 with ``ok: true`` — never a cold or half-warm replica; bumps
   ``router/restarts_total`` (the schema-v7 ``router_restarts``
   counter).

A handle that keeps dying is retried up to ``max_restarts`` times with
``restart_backoff_s`` between attempts, then left quarantined with an
ERROR — a crash-looping build must page an operator, not flap the
fleet forever. ``tools/serve_fleet.py --spawn`` wires this over real
processes; the chaos tier (tests/test_chaos.py, ``serve_bench
--chaos``) drives it in-proc.

ISSUE 13 adds the :class:`Autoscaler` — the loop that *decides* fleet
size. The supervisor keeps replicas ALIVE; the autoscaler keeps the
fleet SIZED to its SLO, scaling up (spawn -> AOT warm -> /health green
-> join router + supervisor) when the probe-fed signals run hot and
scaling down drain-first when they stay idle, with a crash-loop guard
so the two loops never fight over the same replica.
"""

from __future__ import annotations

import logging
import shlex
import subprocess
import sys
import threading
import time

from tensorflow_examples_tpu.serving.router import Router, _get_json

log = logging.getLogger(__name__)


class ProcessReplica:
    """A replica that is a real child process (``serve_fleet --spawn``).

    ``cmd`` is the spawn command (string, ``shlex``-split; a ``{port}``
    placeholder receives ``port``). The process is expected to serve
    the PR 5 frontend surface on ``http://127.0.0.1:{port}``.
    """

    def __init__(self, cmd: str, *, port: int,
                 host: str = "127.0.0.1",
                 stop_timeout_s: float = 10.0):
        self.cmd = cmd
        self.port = int(port)
        self.url = f"http://{host}:{self.port}"
        self.stop_timeout_s = stop_timeout_s
        self._proc: subprocess.Popen | None = None

    def start(self) -> "ProcessReplica":
        argv = shlex.split(self.cmd.format(port=self.port))
        log.info("spawning replica %s: %s", self.url, argv)
        self._proc = subprocess.Popen(argv)
        return self

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def terminate(self) -> None:
        """SIGTERM (the replica's own drain path), escalate to SIGKILL
        after ``stop_timeout_s``."""
        proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=self.stop_timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=self.stop_timeout_s)

    def restart(self) -> None:
        self.terminate()
        self.start()

    def close(self) -> None:
        self.terminate()


class Supervisor:
    """Watch replicas, restart the dead/stuck ones, re-admit on green.

    ``handles`` maps replica URL -> handle; every URL must already be a
    replica of ``router``. Restarts run serially on the supervisor
    thread (one failure at a time is the design point; a correlated
    fleet-wide outage needs an operator anyway).
    """

    def __init__(
        self,
        router: Router,
        handles,
        *,
        poll_s: float = 0.25,
        health_stall_s: float = 5.0,
        health_timeout_s: float = 2.0,
        warm_timeout_s: float = 300.0,
        max_restarts: int = 5,
        restart_backoff_s: float = 0.5,
    ):
        self.router = router
        self.handles = {h.url.rstrip("/"): h for h in handles}
        for url in self.handles:
            if router._find(url) is None:
                raise ValueError(
                    f"supervised url {url} is not a router replica"
                )
        self.poll_s = poll_s
        self.health_stall_s = health_stall_s
        self.health_timeout_s = health_timeout_s
        self.warm_timeout_s = warm_timeout_s
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        # True while an incident is being handled (detect -> restart ->
        # readmit/give-up). The autoscaler's crash-loop guard reads it:
        # no scaling decision while the supervisor is spending its
        # restart budget (ISSUE 13).
        self._busy = False
        # Completed restart cycles (reporting: serve_bench --chaos sums
        # this into router_restarts).
        self.restarts: dict[str, int] = {u: 0 for u in self.handles}
        # Failed attempts within the CURRENT incident — reset on every
        # successful readmit, so max_restarts bounds one crash-loop,
        # not the replica's whole lifetime (a replica independently
        # recovered N times must not be abandoned on failure N+1).
        self._attempts: dict[str, int] = {u: 0 for u in self.handles}
        self.given_up: set[str] = set()
        # Last role each replica's /health reported (ISSUE 12):
        # heterogeneous prefill/decode fleets are first-class, so an
        # incident log must say WHICH role went down — a dead prefill
        # replica stalls handoffs fleet-wide, not 1/N of traffic.
        self.roles: dict[str, str] = {u: "mixed" for u in self.handles}
        # (url, event) rows: "detected" / "restarted" / "readmitted" /
        # "gave_up" — the chaos tier asserts the transition sequence.
        self.events: list[tuple[str, str]] = []
        self._last_ok = {u: time.monotonic() for u in self.handles}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ----------------------------------------------- elastic fleet (ISSUE 13)

    def busy(self) -> bool:
        """An incident is in flight (quarantine -> restart -> readmit).
        The autoscaler holds all scaling while this is true so it never
        fights the restart budget."""
        return self._busy

    def adopt_router(self, router: "Router") -> None:
        """Re-point supervision at a new router (ISSUE 16: warm-standby
        takeover). The standby rebuilt its replica view from /health
        sweeps before promoting, so every supervised URL is expected to
        exist there; any that don't are added so quarantine/readmit
        keep working across the switch."""
        for url in self.handles:
            if router._find(url) is None:
                router.add_replica(url)
        self.router = router

    def add_handle(self, handle) -> None:
        """Supervise one more replica at runtime (the autoscaler's
        scale-up registers its freshly-green spawn here)."""
        url = handle.url.rstrip("/")
        self.handles[url] = handle
        self.restarts.setdefault(url, 0)
        self._attempts.setdefault(url, 0)
        self.roles.setdefault(url, "mixed")
        self._last_ok[url] = time.monotonic()
        self.given_up.discard(url)

    def remove_handle(self, url: str) -> None:
        """Stop supervising a replica (scale-down, after drain +
        router removal). The handle itself is the caller's to close."""
        url = url.rstrip("/")
        self.handles.pop(url, None)
        self.restarts.pop(url, None)
        self._attempts.pop(url, None)
        self.roles.pop(url, None)
        self._last_ok.pop(url, None)
        self.given_up.discard(url)

    # ------------------------------------------------------------ sweep

    def _healthy(self, url: str) -> bool:
        status, body = _get_json(
            url + "/health", self.health_timeout_s
        )
        if status == 0:
            return False
        if isinstance(body.get("role"), str):
            self.roles[url] = body["role"]
        # Any well-formed HTTP answer means the process is responsive;
        # a 503 that is an orderly drain is NOT a stall (the replica is
        # finishing its work on purpose).
        return status == 200 or bool(body.get("draining"))

    def check_once(self) -> None:
        """One synchronous sweep (the loop body; tests call it
        directly for determinism)."""
        now = time.monotonic()
        # Snapshot: the autoscaler may add/remove handles mid-sweep.
        for url, handle in list(self.handles.items()):
            if url in self.given_up or url not in self.handles:
                continue
            if handle.alive() and self._healthy(url):
                self._last_ok[url] = time.monotonic()
                continue
            stalled = now - self._last_ok.get(url, now)
            if handle.alive() and stalled < self.health_stall_s:
                continue  # transient blip: give /health time to recover
            reason = (
                "process dead" if not handle.alive()
                else f"/health stalled {stalled:.1f}s"
            )
            log.warning(
                "SUPERVISOR: %s replica %s down (%s) — quarantining "
                "and restarting", self.roles.get(url, "mixed"), url,
                reason,
            )
            self.events.append((url, "detected"))
            self.router.quarantine(url)
            self._busy = True
            try:
                self._restart(url, handle)
            finally:
                self._busy = False

    def _restart(self, url: str, handle) -> None:
        while self._attempts[url] < self.max_restarts:
            self._attempts[url] += 1
            try:
                handle.restart()  # blocking: respawn + re-warm the AOT
                #                   ladder before anything is re-admitted
            except Exception:  # noqa: BLE001 — a failed restart must
                # not kill the supervisor loop
                log.exception(
                    "SUPERVISOR: restart of %s failed (attempt %d/%d)",
                    url, self._attempts[url], self.max_restarts,
                )
                time.sleep(self.restart_backoff_s)
                continue
            self.events.append((url, "restarted"))
            if self._await_green(url):
                self._last_ok[url] = time.monotonic()
                self._attempts[url] = 0  # incident over: fresh budget
                self.restarts[url] += 1
                self.router.readmit(url)
                self.router.registry.counter(
                    "router/restarts_total"
                ).inc()
                self.events.append((url, "readmitted"))
                log.info(
                    "SUPERVISOR: replica %s restarted and re-admitted "
                    "(/health green)", url,
                )
                return
            log.warning(
                "SUPERVISOR: restarted %s never went green within "
                "%.1fs (attempt %d/%d)", url, self.warm_timeout_s,
                self._attempts[url], self.max_restarts,
            )
            time.sleep(self.restart_backoff_s)
        self.given_up.add(url)
        self.events.append((url, "gave_up"))
        log.error(
            "SUPERVISOR: giving up on %s after %d restart attempts — "
            "left quarantined; operator action required", url,
            self.max_restarts,
        )

    def _await_green(self, url: str) -> bool:
        deadline = time.monotonic() + self.warm_timeout_s
        while time.monotonic() < deadline and not self._stop.is_set():
            status, body = _get_json(
                url + "/health", self.health_timeout_s
            )
            if status == 200 and body.get("ok"):
                return True
            time.sleep(min(0.05, self.poll_s))
        return False

    # -------------------------------------------------------- lifecycle

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 — the watcher must survive
                log.exception("supervisor sweep failed")
            self._stop.wait(self.poll_s)

    def start(self) -> "Supervisor":
        self._thread = threading.Thread(
            target=self._loop, name="replica-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(10.0, self.warm_timeout_s))


# --------------------------------------------------------------------------
# Telemetry-driven autoscaler (ISSUE 13 tentpole (3)): the loop that
# DECIDES fleet size.


def scrape_ttft_p95(url: str, timeout_s: float = 2.0) -> float | None:
    """One replica's recent ``serving_ttft_seconds{quantile="0.95"}``
    from its Prometheus ``/metrics`` endpoint (None when unreachable or
    no TTFT sample yet). The autoscaler's latency signal comes from the
    replica's real scrape surface, not a private API."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(
            url.rstrip("/") + "/metrics", timeout=timeout_s
        ) as resp:
            text = resp.read().decode("utf-8", "replace")
    except (OSError, ValueError):
        return None
    for line in text.splitlines():
        if line.startswith("serving_ttft_seconds{") \
                and 'quantile="0.95"' in line:
            try:
                return float(line.rsplit(" ", 1)[1])
            except (IndexError, ValueError):
                return None
    return None


class AutoscalerConfig:
    """Scaling policy knobs (plain attributes so callers override a la
    carte)."""

    def __init__(
        self,
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        target_queue_depth: float = 4.0,   # mean queued per eligible
        #                                    replica above this -> up
        target_kv_occupancy: float = 0.85,  # mean KV pressure -> up
        target_ttft_p95_s: float = 0.0,    # worst replica TTFT p95
        #                                    above this -> up (0 off)
        scale_down_frac: float = 0.25,     # idle watermark = frac of
        #                                    each up-target
        hold_s: float = 2.0,               # min wall between actions
        scale_down_idle_s: float = 3.0,    # sustained idle before a
        #                                    drain starts
        drain_timeout_s: float = 60.0,
        warm_timeout_s: float = 300.0,     # green gate for a spawn
        evaluate_every_s: float = 0.5,
    ):
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.target_queue_depth = float(target_queue_depth)
        self.target_kv_occupancy = float(target_kv_occupancy)
        self.target_ttft_p95_s = float(target_ttft_p95_s)
        self.scale_down_frac = float(scale_down_frac)
        self.hold_s = float(hold_s)
        self.scale_down_idle_s = float(scale_down_idle_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.warm_timeout_s = float(warm_timeout_s)
        self.evaluate_every_s = float(evaluate_every_s)


class Autoscaler:
    """Resize the fleet against an SLO target (ISSUE 13).

    Reads the router's probe-fed replica view (the ``/replicas``
    numbers: queue depth, KV occupancy, brownout level) plus each
    replica's real ``/metrics`` TTFT p95, and walks the fleet between
    ``min_replicas`` and ``max_replicas``:

    * **Scale-up** — ``spawn(index)`` builds a new replica handle
      (blocking through its full AOT warmup, so cold-start compilation
      happens BEFORE the replica sees traffic), the green gate waits
      for ``/health`` 200 ok (the PR 9 readmit discipline), and only
      then does the replica join the router and the supervisor.
      ``scale_up_latencies`` records decision -> serving wall per
      event (the ``scale_up_latency_s`` the traffic record stamps).
    * **Scale-down** — always drain-first: ``router.drain`` stops new
      dispatch, the loop waits for the replica to go idle
      (active == 0, queue empty via ``/health``), then removes it from
      router + supervisor and closes the handle (``stop()`` when the
      handle has one — the graceful path — else ``close()``). A drain
      that cannot complete within ``drain_timeout_s`` is ABORTED
      (undrain, keep the replica): scaling down may be delayed,
      never lossy.
    * **Crash-loop guard** — no action while ``supervisor.busy()`` (an
      incident is spending the restart budget), quarantined replicas
      are never drain targets, and once the supervisor has GIVEN UP on
      a crash-looping replica the autoscaler refuses to scale up at
      all (spawning more of a crash-looping build fights the budget
      the supervisor just exhausted; ``autoscaler/blocked_total``
      counts both guards).

    One action per evaluation, serially, with ``hold_s`` between
    actions — the same one-failure-at-a-time design point as the
    supervisor. Tests drive :meth:`evaluate_once` directly."""

    def __init__(
        self,
        router: Router,
        supervisor: Supervisor,
        spawn,
        *,
        cfg: AutoscalerConfig | None = None,
        registry=None,
        health_timeout_s: float = 2.0,
        alerts=None,
    ):
        self.router = router
        self.supervisor = supervisor
        self.spawn = spawn
        self.cfg = cfg or AutoscalerConfig()
        # Advisory alert signal (ISSUE 19): anything with the
        # AlertEngine ``stats()`` shape. A firing SLO alert marks the
        # fleet hot (scale up even before queue depth shows it) and
        # vetoes scale-down — the brownout ladder's cousin, fed by the
        # canary prober and organic burn rates instead of queue state.
        self.alerts = alerts
        self.registry = (
            registry if registry is not None else router.registry
        )
        self.health_timeout_s = health_timeout_s
        # Handles this autoscaler manages (it may scale down replicas
        # it did not spawn, as long as the supervisor holds a handle).
        self._spawn_index = len(supervisor.handles)
        self.events: list[tuple[float, str, str]] = []  # (unix, verb, url)
        self.scale_up_latencies: list[float] = []
        self._last_action = 0.0
        self._idle_since: float | None = None
        self._acting = False
        self._soft_stop = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def acting(self) -> bool:
        """A scale action (spawn/warm/drain) is in flight right now."""
        return self._acting

    # ---------------------------------------------------------- signals

    def fleet_signals(self) -> dict:
        """The decision inputs, from the fleet's own scrape surfaces:
        the router's probe-fed replica states and each eligible
        replica's ``/metrics`` TTFT p95."""
        cfg = self.router.cfg
        eligible = [
            r for r in self.router.replicas
            if r.eligible(cfg.unhealthy_after)
        ]
        n = len(eligible)
        ttft = None
        if self.cfg.target_ttft_p95_s > 0:
            vals = [
                v for v in (
                    scrape_ttft_p95(r.url, self.health_timeout_s)
                    for r in eligible
                ) if v is not None
            ]
            ttft = max(vals) if vals else None
        return {
            "replicas": len(self.router.replicas),
            "eligible": n,
            "queue_depth_mean": (
                sum(r.queue_depth for r in eligible) / n if n else 0.0
            ),
            "kv_occupancy_mean": (
                sum(r.kv_occupancy for r in eligible) / n if n else 0.0
            ),
            "brownout_max": max(
                (r.brownout_level for r in eligible), default=0
            ),
            "ttft_p95_s": ttft,
            "alerts_firing": (
                int(self.alerts.stats()["alerts_firing"])
                if self.alerts is not None else 0
            ),
        }

    # --------------------------------------------------------- decision

    def evaluate_once(self) -> str:
        """One control-loop tick; returns the action taken
        ("scale_up" / "scale_down" / "hold" / "blocked")."""
        reg = self.registry
        reg.counter("autoscaler/evaluations_total").inc()
        cfg = self.cfg
        if self.supervisor.busy():
            # Crash-loop guard (1): an incident is mid-restart — the
            # fleet picture is churning and the budget is spoken for.
            reg.counter("autoscaler/blocked_total").inc()
            return "blocked"
        sig = self.fleet_signals()
        reg.gauge("autoscaler/replicas").set(sig["replicas"])
        now = time.monotonic()
        if sig["alerts_firing"] > 0:
            reg.counter("autoscaler/alert_advisory_total").inc()
        hot = (
            sig["queue_depth_mean"] >= cfg.target_queue_depth
            or sig["kv_occupancy_mean"] >= cfg.target_kv_occupancy
            or sig["brownout_max"] > 0
            or sig["alerts_firing"] > 0
            or (
                cfg.target_ttft_p95_s > 0
                and sig["ttft_p95_s"] is not None
                and sig["ttft_p95_s"] >= cfg.target_ttft_p95_s
            )
            or sig["eligible"] == 0
        )
        idle = (
            sig["queue_depth_mean"]
            <= cfg.scale_down_frac * cfg.target_queue_depth
            and sig["kv_occupancy_mean"]
            <= cfg.scale_down_frac * cfg.target_kv_occupancy
            and sig["brownout_max"] == 0
            and sig["alerts_firing"] == 0
            and (
                cfg.target_ttft_p95_s <= 0
                or sig["ttft_p95_s"] is None
                or sig["ttft_p95_s"]
                <= cfg.scale_down_frac * cfg.target_ttft_p95_s
            )
        )
        if hot:
            self._idle_since = None
            if sig["replicas"] >= cfg.max_replicas:
                reg.counter("autoscaler/at_max_total").inc()
                return "hold"
            if self.supervisor.given_up:
                # Crash-loop guard (2): the supervisor just exhausted a
                # restart budget on this build — spawning more of it
                # would crash-loop too. Page an operator instead.
                reg.counter("autoscaler/blocked_total").inc()
                log.error(
                    "AUTOSCALER: scale-up refused — supervisor gave up "
                    "on %s; operator action required",
                    sorted(self.supervisor.given_up),
                )
                return "blocked"
            if now - self._last_action < cfg.hold_s:
                return "hold"
            self._acting = True
            try:
                return self._scale_up()
            finally:
                self._acting = False
        if idle and sig["replicas"] > cfg.min_replicas:
            if self._idle_since is None:
                self._idle_since = now
                return "hold"
            if (
                now - self._idle_since >= cfg.scale_down_idle_s
                and now - self._last_action >= cfg.hold_s
            ):
                self._acting = True
                try:
                    return self._scale_down()
                finally:
                    self._acting = False
            return "hold"
        self._idle_since = None
        return "hold"

    # ---------------------------------------------------------- actions

    def _await_green(self, url: str, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline and not self._stop.is_set():
            status, body = _get_json(
                url + "/health", self.health_timeout_s
            )
            if status == 200 and body.get("ok"):
                return True
            time.sleep(0.05)
        return False

    def _scale_up(self) -> str:
        reg = self.registry
        t0 = time.monotonic()
        idx = self._spawn_index
        self._spawn_index += 1
        log.info("AUTOSCALER: scaling up (spawn %d)", idx)
        try:
            handle = self.spawn(idx)  # blocking: build + AOT warmup
        except Exception:  # noqa: BLE001 — a failed spawn must not
            # kill the control loop
            log.exception("AUTOSCALER: spawn %d failed", idx)
            reg.counter("autoscaler/spawn_failures_total").inc()
            self._last_action = time.monotonic()
            return "hold"
        url = handle.url.rstrip("/")
        if not self._await_green(url, self.cfg.warm_timeout_s):
            # Green gate (PR 9 discipline): never admit a cold or
            # half-warm replica. A spawn that cannot go green is torn
            # down, not routed to.
            log.error(
                "AUTOSCALER: spawned %s never went green; discarding",
                url,
            )
            reg.counter("autoscaler/spawn_failures_total").inc()
            handle.close()
            self._last_action = time.monotonic()
            return "hold"
        self.router.add_replica(url)
        self.router.probe_once()
        self.supervisor.add_handle(handle)
        latency = time.monotonic() - t0
        self.scale_up_latencies.append(latency)
        self._last_action = time.monotonic()
        reg.counter("autoscaler/scale_ups_total").inc()
        reg.histogram("autoscaler/scale_up_latency").record(latency)
        self.events.append((time.time(), "scale_up", url))
        log.info(
            "AUTOSCALER: %s serving after %.1fs (decision -> green -> "
            "routed)", url, latency,
        )
        return "scale_up"

    def _pick_drain_target(self):
        cfg = self.router.cfg
        candidates = [
            r for r in self.router.replicas
            if r.url in self.supervisor.handles
            and not r.quarantined
            and not r.drained
            and r.eligible(cfg.unhealthy_after)
        ]
        if len(candidates) <= self.cfg.min_replicas:
            return None
        # Least-loaded goes first: fewest in-flight requests to wait
        # out, and the fleet loses the least capacity.
        return min(
            candidates,
            key=lambda r: (r.load_score(), -self.router.replicas.index(r)),
        )

    def _scale_down(self) -> str:
        reg = self.registry
        target = self._pick_drain_target()
        if target is None:
            return "hold"
        url = target.url
        log.info("AUTOSCALER: scaling down %s (drain first)", url)
        self.router.drain(url)
        deadline = time.monotonic() + self.cfg.drain_timeout_s
        drained = False
        while time.monotonic() < deadline and not self._stop.is_set():
            status, body = _get_json(
                url + "/health", self.health_timeout_s
            )
            if status in (200, 503) and isinstance(body, dict) and (
                body.get("active_requests") == 0
                and body.get("queue_depth") == 0
            ):
                drained = True
                break
            time.sleep(0.05)
        if not drained:
            # Never lossy: a drain that cannot complete aborts the
            # scale-down and the replica keeps serving.
            log.warning(
                "AUTOSCALER: drain of %s did not complete in %.0fs — "
                "aborting scale-down", url, self.cfg.drain_timeout_s,
            )
            self.router.undrain(url)
            reg.counter("autoscaler/drain_aborted_total").inc()
            self._last_action = time.monotonic()
            return "hold"
        handle = self.supervisor.handles.get(url)
        self.router.remove_replica(url)
        self.supervisor.remove_handle(url)
        if handle is not None:
            stop = getattr(handle, "stop", None)
            (stop if callable(stop) else handle.close)()
        self._idle_since = None
        self._last_action = time.monotonic()
        reg.counter("autoscaler/scale_downs_total").inc()
        self.events.append((time.time(), "scale_down", url))
        log.info("AUTOSCALER: %s drained and removed", url)
        return "scale_down"

    # -------------------------------------------------------- lifecycle

    def _loop(self) -> None:
        while not self._stop.is_set() and not self._soft_stop:
            try:
                self.evaluate_once()
            except Exception:  # noqa: BLE001 — the control loop must
                # survive any single evaluation
                log.exception("autoscaler evaluation failed")
            self._stop.wait(self.cfg.evaluate_every_s)

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        """Graceful first: stop scheduling NEW evaluations and let an
        in-flight action (a spawn mid-warmup, a drain mid-wait) finish
        — aborting a half-done scale action would discard a warmed
        replica or strand a drained one. Hard-stop only if the join
        times out."""
        self._soft_stop = True
        if self._thread is not None:
            self._thread.join(timeout=max(
                30.0,
                self.cfg.drain_timeout_s + 5.0,
                self.cfg.warm_timeout_s + 5.0,
            ))
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)


def main_check(urls, timeout_s: float = 2.0) -> int:  # pragma: no cover
    """Tiny CLI helper: print each replica's health verdict (used by
    operators, not tests)."""
    rc = 0
    for url in urls:
        status, body = _get_json(
            url.rstrip("/") + "/health", timeout_s
        )
        ok = status == 200 and bool(body.get("ok"))
        print(f"{url}: {'OK' if ok else f'DOWN (status {status})'}")
        rc = rc or (0 if ok else 1)
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main_check(sys.argv[1:]))
