"""Draft proposers for speculative decoding (ISSUE 11 tentpole).

Speculative decoding turns the decode loop's latency bound around:
instead of one forward per generated token, a cheap *draft source*
proposes ``k`` candidate tokens and ONE compiled ``verify_k`` forward
(engine ``_verify_impl``) scores all of them, committing the longest
agreeing prefix plus one token the verify itself sampled. Per-step cost
grows mildly (k+1 query rows through the same weights); tokens per step
grows with the draft hit rate — that ratio is the TPOT win
(``tools/serve_bench.py --spec-decode`` measures it, never assumes it).

The determinism contract (what keeps every token-identical golden —
batched-vs-reference, chaos failover replay — valid with speculation
on): a committed token is always one the *verify* forward sampled with
the request's own ``fold_in(seed, position)`` key at that absolute
position, from a context made entirely of previously committed tokens.
Draft quality therefore affects SPEED only; output streams are a pure
function of (params, prompt, seed), exactly as without speculation.
A wrong draft can never ship — it merely fails to accelerate.

This module owns the draft side. The in-tree source is
:class:`NgramDraft` — self-speculative n-gram lookup over the request's
own context (prompt + committed tokens), the no-second-model drafter
that works out of the box on prompt-like text (code, templated prose,
anything whose continuations repeat earlier n-grams). The
:class:`DraftSource` interface is deliberately tiny so a small draft
*model* (its own engine at a fraction of the params) can plug in later
without touching the batcher: ``serving/batcher.py`` only ever calls
``begin`` / ``extend`` / ``propose`` / ``end``.
"""

from __future__ import annotations


class DraftSource:
    """Per-slot draft proposer interface the continuous batcher speaks.

    Lifecycle per request: ``begin(slot, ctx)`` at admission (prompt +
    first generated token), ``propose(slot, k)`` before each decode
    step, ``extend(slot, committed)`` after each step's accepted
    tokens, ``end(slot)`` at retirement. Implementations must be
    deterministic — proposals may be wrong (that costs speed, never
    correctness) but must be a pure function of the observed context,
    or the A/B bench loses reproducibility.
    """

    def begin(self, slot: int, ctx: list[int]) -> None:
        raise NotImplementedError

    def extend(self, slot: int, tokens: list[int]) -> None:
        raise NotImplementedError

    def propose(self, slot: int, k: int) -> list[int]:
        raise NotImplementedError

    def end(self, slot: int) -> None:
        raise NotImplementedError


class NgramDraft(DraftSource):
    """Self-speculative n-gram drafting: match the context's trailing
    n-gram against its own earlier occurrences and propose what
    followed last time.

    For each ``n`` in ``max_ngram .. min_ngram`` (longest first), the
    drafter keeps a per-slot map from every n-gram seen in the context
    to the position right AFTER its most recent occurrence (and the one
    before that, so the trailing suffix — which always matches itself —
    still finds a genuinely earlier match). A hit proposes the ``k``
    tokens that followed; a miss at every ``n`` proposes nothing and
    the step degrades to plain one-token decode. O(max_ngram) work per
    observed token, O(1) per proposal — the drafter can never become
    the new bottleneck.
    """

    def __init__(self, *, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram ({min_ngram}) <= max_ngram "
                f"({max_ngram})"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        # slot -> context token list
        self._ctx: dict[int, list[int]] = {}
        # slot -> {n -> {gram tuple -> continuation start}} for the
        # latest occurrence, and the previous one (see propose()).
        self._last: dict[int, dict[int, dict[tuple, int]]] = {}
        self._prev: dict[int, dict[int, dict[tuple, int]]] = {}

    def begin(self, slot: int, ctx: list[int]) -> None:
        self._ctx[slot] = []
        ns = range(self.min_ngram, self.max_ngram + 1)
        self._last[slot] = {n: {} for n in ns}
        self._prev[slot] = {n: {} for n in ns}
        self.extend(slot, ctx)

    def extend(self, slot: int, tokens: list[int]) -> None:
        ctx = self._ctx[slot]
        last, prev = self._last[slot], self._prev[slot]
        for t in tokens:
            ctx.append(int(t))
            i = len(ctx)  # continuation start for grams ending here
            for n in range(self.min_ngram, self.max_ngram + 1):
                if i < n:
                    continue
                gram = tuple(ctx[i - n:i])
                table = last[n]
                if gram in table:
                    prev[n][gram] = table[gram]
                table[gram] = i

    def propose(self, slot: int, k: int) -> list[int]:
        if k < 1:
            return []
        ctx = self._ctx[slot]
        end = len(ctx)
        for n in range(min(self.max_ngram, end), self.min_ngram - 1, -1):
            gram = tuple(ctx[end - n:end])
            pos = self._last[slot][n].get(gram)
            if pos == end:  # the trailing suffix matched itself
                pos = self._prev[slot][n].get(gram)
            if pos is not None and pos < end:
                # The match sits ``d`` tokens behind the present; the
                # model of this drafter is "the stream repeats with
                # period d", so token end+i is token end+i-d — known
                # context for i < d, the proposal's OWN earlier entries
                # after that (a period-1 loop proposes k tokens, not 1).
                d = end - pos
                out: list[int] = []
                for i in range(k):
                    j = pos + i
                    out.append(ctx[j] if j < end else out[i - d])
                return out
        return []

    def end(self, slot: int) -> None:
        self._ctx.pop(slot, None)
        self._last.pop(slot, None)
        self._prev.pop(slot, None)


def make_draft(cfg) -> DraftSource:
    """Draft source from ``ServeConfig`` knobs (``draft`` /
    ``draft_ngram``). The registry is a single name for now; a
    small-draft-model source would register here and slot straight
    into the batcher."""
    if cfg.draft == "ngram":
        return NgramDraft(max_ngram=cfg.draft_ngram)
    raise ValueError(
        f"ServeConfig.draft={cfg.draft!r}: the in-tree draft source is "
        "'ngram' (self-speculative); plug a model-backed DraftSource "
        "into ContinuousBatcher(draft=...) for anything else"
    )


def accept_drafts(drafts: list[int], sampled, *, limit: int) -> list[int]:
    """The acceptance rule, shared by the dense and paged verify paths
    (and test-pinned): commit ``sampled[0]`` (the token a plain decode
    step would have produced — its context is fully committed), then
    one more sampled token per leading draft that AGREES with the
    sampled stream, stopping at the first disagreement. ``limit`` caps
    committed tokens at the rows whose K/V actually landed in the cache
    (block/extent budget) — a committed token must be re-attendable.
    """
    take = 1
    for j, d in enumerate(drafts):
        if take >= limit or int(d) != int(sampled[j]):
            break
        take += 1
    return [int(t) for t in sampled[:take]]
