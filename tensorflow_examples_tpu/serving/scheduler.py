"""Cache-aware fleet scheduling primitives (ISSUE 12 tentpole).

The fleet built in PRs 8–10 is fault-tolerant and fast per-replica but
cache-blind: the prefix cache is per-replica, so N replicas hold N
copies of every shared system prompt and a hit depends on luck of
dispatch, while one long cold prefill monopolizes a replica's decode
loop for every co-scheduled request. This module holds the three
pure-function layers the scheduling tentpole composes — the router,
batcher, and engine import from here so the wire format and the hash
discipline have exactly one home:

* **Prefix chain keys** — a content-addressed mirror of
  ``paged_kv.py``'s chained prefix-cache keys. The pool's exact keys
  chain ``(parent PHYSICAL block id, block tokens)`` — collision-free
  on one replica, meaningless across replicas (physical ids are
  replica-local). :func:`chain_key` replaces the physical parent with
  the parent's own chain digest, so the key of block *i* is a pure
  function of the first ``(i+1) * block_size`` prompt tokens: two
  replicas that cached the same prefix publish the same keys, and the
  router can measure "how much of THIS prompt does THAT replica
  already hold" from a compact digest without shipping a single token.
  Stability across ``reset()``/restart is by construction (no physical
  id ever enters the hash) and test-pinned.
* **Chunk planning** — :func:`plan_chunks` splits a cold prompt tail
  into block-aligned spans of at most ``chunk_tokens`` each, the spans
  the engine's per-tail-bucket extend rung (PR 8) runs one per decode-
  loop iteration, so a long prefill interleaves with decode steps
  instead of monopolizing them.
* **KV page wire format** — :func:`encode_pages` / :func:`decode_pages`
  serialize a finished prompt's KV blocks (int8-aware: blockwise scales
  ride along) as a JSON-safe dict, the handoff payload a prefill-role
  replica returns from ``POST /prefill`` and a decode-role replica
  imports at ``POST /resume``. Geometry travels with the payload and is
  validated on import — a page from a different model shape is a loud
  400, never a silent garbage cache.

Everything here is stdlib + numpy: no device, no sockets, no locks.
"""

from __future__ import annotations

import base64
import hashlib

import numpy as np

ROLES = ("mixed", "prefill", "decode")

# Wire-format version for the KV page payload (bumped on any layout
# change; decode_pages rejects unknown versions loudly).
PAGE_WIRE_VERSION = 1

# Cap on the number of chain keys a replica publishes in its /health
# digest — bounds the probe payload; shallow keys are kept first
# because shared system prompts (the blocks worth routing for) are by
# construction the shallowest links of every chain that reuses them.
DIGEST_MAX_KEYS = 512


# ---------------------------------------------------------- chain keys


def chain_key(parent: str, block_tokens) -> str:
    """Content chain digest of one full prefix block: a pure function
    of (parent chain digest, the block's token ids). ``parent`` is ""
    for the root block. 64-bit blake2b hex — replica- and
    restart-stable, unlike the pool's physical-id chained keys."""
    h = hashlib.blake2b(digest_size=8)
    h.update(parent.encode("ascii"))
    h.update(np.asarray(block_tokens, np.int64).tobytes())
    return h.hexdigest()


def prompt_chain_keys(prompt, block_size: int) -> list[str]:
    """The chain keys of every REUSABLE full block of ``prompt`` —
    capped at ``(len - 1) // block_size`` exactly like
    ``paged_kv.prefix_lookup`` (at least one tail token always
    prefills), so key ``i`` matching a replica's digest means that
    replica can serve blocks ``[0, i]`` from cache."""
    if block_size < 1:
        raise ValueError(f"block_size={block_size} must be >= 1")
    keys: list[str] = []
    parent = ""
    for i in range((len(prompt) - 1) // block_size):
        parent = chain_key(
            parent, prompt[i * block_size:(i + 1) * block_size]
        )
        keys.append(parent)
    return keys


def affinity_blocks(chain_keys: list[str], digest) -> int:
    """How many leading blocks of a prompt (``chain_keys`` from
    :func:`prompt_chain_keys`) a replica's published ``digest``
    already holds — the router's affinity score. ``digest`` is
    anything supporting ``in``: the exact frozenset of published
    chain keys, or a :class:`BloomDigest` when the replica's cache
    outgrew the key-list cap. The walk stops at the first miss:
    cached blocks are only mappable as a chain from the root."""
    n = 0
    for key in chain_keys:
        if key not in digest:
            break
        n += 1
    return n


# ------------------------------------------------------- bloom digest
#
# ISSUE 15 satellite (PR 11/12 follow-up): ``prefix_digest()`` caps its
# key list at DIGEST_MAX_KEYS to bound the /health payload, which
# blinds affinity routing to everything past the cap on very large
# caches. When the cap bites, the replica ALSO publishes a bloom
# filter over its ENTIRE chain-key set — fixed ~1.25 KiB per 1k keys
# instead of 16 B/key — and the router matches against that. False
# positives can only OVERSTATE affinity (a preference, load-guarded;
# a wrong delta-handoff skip is validated importer-side and falls
# back), and there are no false negatives, so routing keeps working
# where the truncated list went blind. ``digest_truncated`` stays the
# operator's fallback signal.

BLOOM_BITS_PER_KEY = 10   # ~1% false-positive rate at 7 hashes
BLOOM_HASHES = 7
BLOOM_MIN_BITS = 64
BLOOM_MAX_BITS = 1 << 20  # 128 KiB hard cap on the /health payload


def _bloom_indices(key: str, m: int, k: int) -> list[int]:
    """Double hashing from one blake2b digest: k bit indices in
    [0, m)."""
    h = hashlib.blake2b(key.encode("ascii"), digest_size=16).digest()
    a = int.from_bytes(h[:8], "big")
    b = int.from_bytes(h[8:], "big") | 1  # odd: never collapses
    return [(a + i * b) % m for i in range(k)]


def encode_bloom(keys) -> dict:
    """Bloom filter over chain keys as a JSON-safe /health payload:
    ``{m, k, n, bits}`` with the bit array base64'd."""
    keys = list(keys)
    m = min(
        BLOOM_MAX_BITS,
        max(BLOOM_MIN_BITS, len(keys) * BLOOM_BITS_PER_KEY),
    )
    m = (m + 7) // 8 * 8  # whole bytes
    bits = bytearray(m // 8)
    for key in keys:
        for idx in _bloom_indices(key, m, BLOOM_HASHES):
            bits[idx // 8] |= 1 << (idx % 8)
    return {
        "m": m,
        "k": BLOOM_HASHES,
        "n": len(keys),
        "bits": base64.b64encode(bytes(bits)).decode("ascii"),
    }


class BloomDigest:
    """Read side of :func:`encode_bloom`: supports ``key in digest``
    (what :func:`affinity_blocks` needs) and ``len()`` (the published
    key count, so an empty filter is falsy like an empty frozenset)."""

    __slots__ = ("m", "k", "n", "_bits")

    def __init__(self, m: int, k: int, n: int, bits: bytes):
        self.m = m
        self.k = k
        self.n = n
        self._bits = bits

    def __contains__(self, key) -> bool:
        if not isinstance(key, str) or self.n == 0:
            return False
        return all(
            self._bits[idx // 8] & (1 << (idx % 8))
            for idx in _bloom_indices(key, self.m, self.k)
        )

    def __len__(self) -> int:
        return self.n

    def __repr__(self):
        return f"BloomDigest(m={self.m}, k={self.k}, n={self.n})"


def decode_bloom(payload) -> BloomDigest:
    """Parse a published bloom payload; every malformation raises
    ``ValueError`` (a garbage /health body must fail THIS field, not
    the probe sweep — the router treats it as 'no digest')."""
    if not isinstance(payload, dict):
        raise ValueError("bloom digest must be a JSON object")
    try:
        m, k, n = int(payload["m"]), int(payload["k"]), int(payload["n"])
        bits = base64.b64decode(payload["bits"], validate=True)
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"malformed bloom digest: {e}") from None
    if m < 8 or m % 8 or m > BLOOM_MAX_BITS:
        raise ValueError(f"bloom m={m} out of range")
    if not 1 <= k <= 32 or n < 0:
        raise ValueError(f"bloom k={k}/n={n} out of range")
    if len(bits) != m // 8:
        raise ValueError(
            f"bloom bits: {len(bits)} bytes does not match m={m}"
        )
    return BloomDigest(m, k, n, bits)


# ------------------------------------------------------- chunk planning


def plan_chunks(n: int, ctx: int, chunk_tokens: int,
                block_size: int) -> list[tuple[int, int]]:
    """Split the cold tail ``[ctx, n)`` of an ``n``-token prompt into
    ``(start, end)`` spans of at most ``chunk_tokens`` each. Every
    span start is block-aligned (the extend rung scatters whole
    blocks; ``ctx`` is block-aligned by the prefix cache's contract
    and ``chunk_tokens`` must be a block multiple); only the final
    span's end may be ragged. One span per decode-loop iteration is
    the admission discipline that bounds how long any chunk can stall
    co-scheduled decode steps."""
    if chunk_tokens < 1 or chunk_tokens % block_size:
        raise ValueError(
            f"chunk_tokens={chunk_tokens} must be a positive multiple "
            f"of block_size={block_size}"
        )
    if ctx % block_size:
        raise ValueError(f"ctx={ctx} is not block-aligned")
    if not ctx <= n:
        raise ValueError(f"ctx={ctx} exceeds prompt length {n}")
    spans = []
    start = ctx
    while start < n:
        end = min(start + chunk_tokens, n)
        spans.append((start, end))
        start = end
    return spans


# ----------------------------------------------------- KV page payload

_PAGE_META = ("block_size", "num_layers", "num_heads", "head_dim",
              "length", "kv_bits")


def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode(
        "ascii"
    )


def encode_pages(meta: dict, arrays: dict) -> dict:
    """Serialize a slot's finished KV blocks for the prefill->decode
    handoff. ``arrays`` maps name -> numpy array (``k``/``v`` always,
    ``k_scale``/``v_scale`` when quantized); geometry rides in
    ``meta`` so the importer can validate before touching its pool.

    ``meta["start_block"]`` (optional, default 0) is the streaming
    DELTA handoff (ISSUE 15 satellite): the arrays cover only blocks
    ``[start_block, ceil(length / block_size))`` — the exporter left
    off the leading blocks the router's digest exchange says the
    importer already caches. The importer validates its prefix cache
    actually covers the skipped tokens (400 + full-path fallback when
    a probe-stale digest lied)."""
    missing = [k for k in _PAGE_META if k not in meta]
    if missing:
        raise ValueError(f"page meta missing {missing}")
    payload = {"version": PAGE_WIRE_VERSION, **{k: int(meta[k]) for k in
                                                _PAGE_META}}
    start = int(meta.get("start_block", 0))
    if start < 0:
        raise ValueError(f"start_block={start} must be >= 0")
    if start:
        payload["start_block"] = start
    payload["arrays"] = {
        name: {
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
            "data": _b64(arr),
        }
        for name, arr in arrays.items()
    }
    return payload


def decode_pages(payload) -> tuple[dict, dict]:
    """Inverse of :func:`encode_pages`: ``(meta, arrays)``. Every
    malformation — wrong version, missing geometry, torn base64, a
    shape/bytes mismatch — raises ``ValueError`` with a client-facing
    message (the frontend maps it to 400)."""
    if not isinstance(payload, dict):
        raise ValueError("pages payload must be a JSON object")
    if payload.get("version") != PAGE_WIRE_VERSION:
        raise ValueError(
            f"unsupported pages wire version {payload.get('version')!r} "
            f"(this replica speaks {PAGE_WIRE_VERSION})"
        )
    meta = {}
    for key in _PAGE_META:
        v = payload.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            raise ValueError(f"pages meta {key!r} = {v!r} is not a "
                             "positive int")
        meta[key] = v
    if "start_block" in payload:
        start = payload["start_block"]
        if not isinstance(start, int) or isinstance(start, bool) \
                or start < 0:
            raise ValueError(
                f"pages meta 'start_block' = {start!r} is not a "
                "non-negative int"
            )
        if start * meta["block_size"] >= meta["length"]:
            raise ValueError(
                f"pages start_block={start} skips the whole "
                f"{meta['length']}-token prompt"
            )
        meta["start_block"] = start
    raw = payload.get("arrays")
    if not isinstance(raw, dict) or "k" not in raw or "v" not in raw:
        raise ValueError("pages payload is missing the k/v arrays")
    arrays = {}
    for name, spec in raw.items():
        if not isinstance(spec, dict):
            raise ValueError(f"pages array {name!r} is not an object")
        try:
            dtype = np.dtype(spec["dtype"])
            shape = tuple(int(d) for d in spec["shape"])
            data = base64.b64decode(spec["data"], validate=True)
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"malformed pages array {name!r}: {e}") \
                from None
        expect = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        if len(data) != expect:
            raise ValueError(
                f"pages array {name!r}: {len(data)} bytes does not "
                f"match shape {shape} of {dtype}"
            )
        arrays[name] = np.frombuffer(data, dtype).reshape(shape)
    return meta, arrays
