"""Cache-aware fleet scheduling primitives (ISSUE 12 tentpole).

The fleet built in PRs 8–10 is fault-tolerant and fast per-replica but
cache-blind: the prefix cache is per-replica, so N replicas hold N
copies of every shared system prompt and a hit depends on luck of
dispatch, while one long cold prefill monopolizes a replica's decode
loop for every co-scheduled request. This module holds the three
pure-function layers the scheduling tentpole composes — the router,
batcher, and engine import from here so the wire format and the hash
discipline have exactly one home:

* **Prefix chain keys** — a content-addressed mirror of
  ``paged_kv.py``'s chained prefix-cache keys. The pool's exact keys
  chain ``(parent PHYSICAL block id, block tokens)`` — collision-free
  on one replica, meaningless across replicas (physical ids are
  replica-local). :func:`chain_key` replaces the physical parent with
  the parent's own chain digest, so the key of block *i* is a pure
  function of the first ``(i+1) * block_size`` prompt tokens: two
  replicas that cached the same prefix publish the same keys, and the
  router can measure "how much of THIS prompt does THAT replica
  already hold" from a compact digest without shipping a single token.
  Stability across ``reset()``/restart is by construction (no physical
  id ever enters the hash) and test-pinned.
* **Chunk planning** — :func:`plan_chunks` splits a cold prompt tail
  into block-aligned spans of at most ``chunk_tokens`` each, the spans
  the engine's per-tail-bucket extend rung (PR 8) runs one per decode-
  loop iteration, so a long prefill interleaves with decode steps
  instead of monopolizing them.
* **KV page wire format** — :func:`encode_pages` / :func:`decode_pages`
  serialize a finished prompt's KV blocks (int8-aware: blockwise scales
  ride along) as a JSON-safe dict, the handoff payload a prefill-role
  replica returns from ``POST /prefill`` and a decode-role replica
  imports at ``POST /resume``. Geometry travels with the payload and is
  validated on import — a page from a different model shape is a loud
  400, never a silent garbage cache.

Everything here is stdlib + numpy: no device, no sockets, no locks.
"""

from __future__ import annotations

import base64
import hashlib

import numpy as np

ROLES = ("mixed", "prefill", "decode")

# Wire-format version for the KV page payload (bumped on any layout
# change; decode_pages rejects unknown versions loudly).
PAGE_WIRE_VERSION = 1

# Cap on the number of chain keys a replica publishes in its /health
# digest — bounds the probe payload; shallow keys are kept first
# because shared system prompts (the blocks worth routing for) are by
# construction the shallowest links of every chain that reuses them.
DIGEST_MAX_KEYS = 512


# ---------------------------------------------------------- chain keys


def chain_key(parent: str, block_tokens) -> str:
    """Content chain digest of one full prefix block: a pure function
    of (parent chain digest, the block's token ids). ``parent`` is ""
    for the root block. 64-bit blake2b hex — replica- and
    restart-stable, unlike the pool's physical-id chained keys."""
    h = hashlib.blake2b(digest_size=8)
    h.update(parent.encode("ascii"))
    h.update(np.asarray(block_tokens, np.int64).tobytes())
    return h.hexdigest()


def prompt_chain_keys(prompt, block_size: int) -> list[str]:
    """The chain keys of every REUSABLE full block of ``prompt`` —
    capped at ``(len - 1) // block_size`` exactly like
    ``paged_kv.prefix_lookup`` (at least one tail token always
    prefills), so key ``i`` matching a replica's digest means that
    replica can serve blocks ``[0, i]`` from cache."""
    if block_size < 1:
        raise ValueError(f"block_size={block_size} must be >= 1")
    keys: list[str] = []
    parent = ""
    for i in range((len(prompt) - 1) // block_size):
        parent = chain_key(
            parent, prompt[i * block_size:(i + 1) * block_size]
        )
        keys.append(parent)
    return keys


def affinity_blocks(chain_keys: list[str], digest) -> int:
    """How many leading blocks of a prompt (``chain_keys`` from
    :func:`prompt_chain_keys`) a replica's published ``digest`` (a set
    of chain keys) already holds — the router's affinity score. The
    walk stops at the first miss: cached blocks are only mappable as a
    chain from the root."""
    n = 0
    for key in chain_keys:
        if key not in digest:
            break
        n += 1
    return n


# ------------------------------------------------------- chunk planning


def plan_chunks(n: int, ctx: int, chunk_tokens: int,
                block_size: int) -> list[tuple[int, int]]:
    """Split the cold tail ``[ctx, n)`` of an ``n``-token prompt into
    ``(start, end)`` spans of at most ``chunk_tokens`` each. Every
    span start is block-aligned (the extend rung scatters whole
    blocks; ``ctx`` is block-aligned by the prefix cache's contract
    and ``chunk_tokens`` must be a block multiple); only the final
    span's end may be ragged. One span per decode-loop iteration is
    the admission discipline that bounds how long any chunk can stall
    co-scheduled decode steps."""
    if chunk_tokens < 1 or chunk_tokens % block_size:
        raise ValueError(
            f"chunk_tokens={chunk_tokens} must be a positive multiple "
            f"of block_size={block_size}"
        )
    if ctx % block_size:
        raise ValueError(f"ctx={ctx} is not block-aligned")
    if not ctx <= n:
        raise ValueError(f"ctx={ctx} exceeds prompt length {n}")
    spans = []
    start = ctx
    while start < n:
        end = min(start + chunk_tokens, n)
        spans.append((start, end))
        start = end
    return spans


# ----------------------------------------------------- KV page payload

_PAGE_META = ("block_size", "num_layers", "num_heads", "head_dim",
              "length", "kv_bits")


def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode(
        "ascii"
    )


def encode_pages(meta: dict, arrays: dict) -> dict:
    """Serialize a slot's finished KV blocks for the prefill->decode
    handoff. ``arrays`` maps name -> numpy array (``k``/``v`` always,
    ``k_scale``/``v_scale`` under int8); geometry rides in ``meta`` so
    the importer can validate before touching its pool."""
    missing = [k for k in _PAGE_META if k not in meta]
    if missing:
        raise ValueError(f"page meta missing {missing}")
    payload = {"version": PAGE_WIRE_VERSION, **{k: int(meta[k]) for k in
                                                _PAGE_META}}
    payload["arrays"] = {
        name: {
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
            "data": _b64(arr),
        }
        for name, arr in arrays.items()
    }
    return payload


def decode_pages(payload) -> tuple[dict, dict]:
    """Inverse of :func:`encode_pages`: ``(meta, arrays)``. Every
    malformation — wrong version, missing geometry, torn base64, a
    shape/bytes mismatch — raises ``ValueError`` with a client-facing
    message (the frontend maps it to 400)."""
    if not isinstance(payload, dict):
        raise ValueError("pages payload must be a JSON object")
    if payload.get("version") != PAGE_WIRE_VERSION:
        raise ValueError(
            f"unsupported pages wire version {payload.get('version')!r} "
            f"(this replica speaks {PAGE_WIRE_VERSION})"
        )
    meta = {}
    for key in _PAGE_META:
        v = payload.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            raise ValueError(f"pages meta {key!r} = {v!r} is not a "
                             "positive int")
        meta[key] = v
    raw = payload.get("arrays")
    if not isinstance(raw, dict) or "k" not in raw or "v" not in raw:
        raise ValueError("pages payload is missing the k/v arrays")
    arrays = {}
    for name, spec in raw.items():
        if not isinstance(spec, dict):
            raise ValueError(f"pages array {name!r} is not an object")
        try:
            dtype = np.dtype(spec["dtype"])
            shape = tuple(int(d) for d in spec["shape"])
            data = base64.b64decode(spec["data"], validate=True)
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"malformed pages array {name!r}: {e}") \
                from None
        expect = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        if len(data) != expect:
            raise ValueError(
                f"pages array {name!r}: {len(data)} bytes does not "
                f"match shape {shape} of {dtype}"
            )
        arrays[name] = np.frombuffer(data, dtype).reshape(shape)
    return meta, arrays
