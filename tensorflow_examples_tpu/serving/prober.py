"""Synthetic canary prober (ISSUE 19).

Organic traffic tells you about the requests users already sent; it is
silent about the replica that would fail the NEXT one, and on a quiet
fleet it is silent entirely. The canary prober closes that gap with
low-rate deterministic known-answer requests driven from the OUTSIDE —
plain HTTP against the router frontend and against every replica
frontend directly, exactly the path a client takes — so the fleet's
availability and black-box TTFT are measured per replica even at zero
organic load, and a sick replica feeds the :class:`AlertEngine` AHEAD
of the users who would have discovered it.

**Known-answer.** Generation in this stack is a pure function of
(params, prompt, seed) — ``temperature=0`` with a fixed prompt and
seed produces the same token stream on every healthy replica, every
time. The first successful probe of each target BANKS that stream as
the expected answer; every later probe compares. A mismatch is a
failed probe even with a 200 status — the silently-corrupted-replica
case no status code catches.

**Exclusion.** Every probe body carries ``"probe": true``. The router
strips the tag and excludes the request from the journal (no
dedupe-window entry, no tenant intent record), from
``router/requests_total``, and from its organic AlertEngine feed;
replica frontends tolerate and ignore the tag (``_request_from_body``).
Probe traffic is accounted ONLY under the ``probe/`` instruments and
through :meth:`AlertEngine.observe_probe` — it can never inflate a
banked bench record or replay after a crash.

**Compiled paths.** Probes are ordinary generate requests over the
replica's warmed buckets (the default probe prompt is short and the
token budget tiny), so they ride the compiled serving path — zero
post-warmup recompiles is part of the chaos acceptance golden.

The prober owns one daemon thread (``canary-prober``); tests call
:meth:`probe_once` directly for determinism. Firing alerts are the
advisory signal the autoscaler consumes (``advisory()``).

Stdlib + repo only; no device.
"""

from __future__ import annotations

import logging
import threading
import time

from tensorflow_examples_tpu.serving.router import post_json
from tensorflow_examples_tpu.telemetry import registry as registry_mod

log = logging.getLogger(__name__)

# The default known-answer request: a short fixed prompt inside every
# engine's vocab floor (the smoke model's vocab is 211), zero
# temperature, a fixed seed, and a tiny token budget — cheap enough to
# run at probe rate forever, deterministic enough to bank.
DEFAULT_PROBE_PROMPT = (11, 13, 17, 19)
DEFAULT_PROBE_TOKENS = 4


class CanaryProber:
    """Low-rate black-box prober over a router + its replicas.

    ``targets`` is ``{name: base_url}`` — conventionally the router
    under ``"router"`` plus each replica under its URL (see
    :func:`fleet_targets`). Results feed ``alerts.observe_probe`` (the
    availability budget, per the target's SLO class) and the engine is
    evaluated after every sweep, so a dead replica's alert fires on
    the PROBE cadence, not the organic-traffic cadence."""

    def __init__(
        self,
        targets: dict,
        *,
        alerts=None,
        registry=None,
        interval_s: float = 1.0,
        timeout_s: float = 10.0,
        prompt=DEFAULT_PROBE_PROMPT,
        max_new_tokens: int = DEFAULT_PROBE_TOKENS,
        seed: int = 1234,
        slo: str = "interactive",
    ):
        if not targets:
            raise ValueError("prober needs at least one target")
        self.targets = {
            str(name): url.rstrip("/") for name, url in targets.items()
        }
        self.alerts = alerts
        self.registry = (
            registry if registry is not None
            else registry_mod.MetricsRegistry()
        )
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.seed = int(seed)
        self.slo = str(slo)
        self._expected: dict[str, list[int]] = {}  # guard: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.sweeps = 0

    # ------------------------------------------------------------ body

    def probe_body(self) -> dict:
        return {
            "prompt": list(self.prompt),
            "max_new_tokens": self.max_new_tokens,
            "temperature": 0.0,
            "seed": self.seed,
            "slo": self.slo,
            # The exclusion tag (stripped by the router, tolerated by
            # replicas): synthetic traffic must never enter the
            # journal dedupe window or the organic counters.
            "probe": True,
        }

    # ----------------------------------------------------------- sweep

    def probe_one(self, name: str, url: str) -> dict:
        """One probe of one target; returns the result doc and feeds
        the AlertEngine."""
        reg = self.registry
        reg.counter("probe/sent_total").inc()
        t0 = time.monotonic()
        status, reply = post_json(
            url + "/generate", self.probe_body(), self.timeout_s
        )
        wall = time.monotonic() - t0
        tokens = reply.get("tokens") if isinstance(reply, dict) else None
        ok = status == 200 and isinstance(tokens, list) and bool(tokens)
        mismatch = False
        if ok:
            with self._lock:
                expected = self._expected.get(name)
                if expected is None:
                    # First success banks the known answer (generation
                    # is deterministic by seeding, so any healthy
                    # target of the same build reproduces it).
                    self._expected[name] = list(tokens)
                elif list(tokens) != expected:
                    mismatch = True
        if mismatch:
            ok = False
            reg.counter("probe/mismatch_total").inc()
        if not ok:
            reg.counter("probe/failed_total").inc()
        # Black-box TTFT: prefer the replica's own measurement when
        # the reply carries one; the client-observed wall is the
        # fallback (and is what a router-path probe sees).
        ttft = reply.get("ttft_s") if isinstance(reply, dict) else None
        if not isinstance(ttft, (int, float)) or isinstance(ttft, bool):
            ttft = wall
        if ok:
            reg.histogram("probe/ttft").record(float(ttft))
        result = {
            "target": name, "ok": ok, "status": status,
            "mismatch": mismatch, "ttft_s": float(ttft),
            "wall_s": wall,
            "trace_id": reply.get("trace_id")
            if isinstance(reply, dict) else None,
        }
        if self.alerts is not None:
            self.alerts.observe_probe(
                slo=self.slo, ok=ok, replica=name,
                ttft_s=float(ttft) if ok else None,
                trace_id=result["trace_id"],
            )
        return result

    def probe_once(self) -> list[dict]:
        """One synchronous sweep over every target (the background
        loop's body; tests call it directly), followed by one
        AlertEngine evaluation — probe failures raise alerts on THIS
        cadence, ahead of organic traffic."""
        results = [
            self.probe_one(name, url)
            for name, url in self.targets.items()
        ]
        self.sweeps += 1
        if self.alerts is not None:
            self.alerts.evaluate()
        failed = [r["target"] for r in results if not r["ok"]]
        if failed:
            log.warning("canary probe failures: %s", failed)
        return results

    # --------------------------------------------------------- advisory

    def advisory(self) -> bool:
        """True while any alert is firing — the signal the PR-12
        autoscaler/brownout ladder consumes (``Autoscaler(alerts=...)``
        treats it as a hot fleet)."""
        if self.alerts is None:
            return False
        return self.alerts.stats()["alerts_firing"] > 0

    # -------------------------------------------------------- lifecycle

    def start(self) -> "CanaryProber":
        self._thread = threading.Thread(
            target=self._loop, name="canary-prober", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_once()
            except Exception:  # pragma: no cover - defensive
                log.exception("canary probe sweep failed")
            self._stop.wait(self.interval_s)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


def fleet_targets(router_url: str | None,
                  replica_urls: list[str]) -> dict:
    """The conventional target map: the router (end-to-end path) under
    ``"router"`` plus every replica under its own URL (per-replica
    black-box availability — a router would mask a single sick replica
    by failing over around it)."""
    targets: dict = {}
    if router_url:
        targets["router"] = router_url
    for url in replica_urls:
        targets[url.rstrip("/")] = url
    return targets
