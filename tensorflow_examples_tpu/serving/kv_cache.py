"""Slot-granular KV cache pool + variable-length decode attention.

The training-side decode path (``models/transformer.py`` flax ``cache``
collection) keys the whole batch off ONE scalar index — fine for
sampling a fixed batch in lockstep, useless for continuous batching
where every concurrent request sits at a different position. This
module owns the serving-side replacement:

* ``KVCachePool`` preallocates the worst-case cache ONCE —
  ``[layers, slots, heads, max_len, head_dim]`` for K and V — and hands
  out *slots* (one per in-flight request) with host-side alloc/free and
  per-slot populated-length tracking. Slot state is published as
  ``serving/kv_occupancy`` / ``serving/kv_tokens`` gauges on every
  transition, so a scrape always sees live cache pressure.
* ``varlen_decode_attention`` is the per-slot generalization of
  ``ops/decode.flash_decode_attention``'s contract: each slot's query
  attends over exactly its own populated prefix (``lengths`` rides in
  as a vector, not a scalar). The bucket discipline lives in the
  caller (``engine.py``): the cache is sliced to the smallest
  power-of-two KV bucket covering the longest active request before
  this runs, so a step over mostly-short requests reads O(bucket)
  cache bytes, not O(max_len) — the same populated-prefix economics as
  the flash-decode bucket ladder, expressed through XLA slicing
  instead of a Pallas grid (scalar-prefetch index maps cannot see a
  per-slot length vector; the single-length case — prefill — reuses
  the Pallas kernel directly, see ``engine._prefill_attend``).

Everything here is functionally pure on the device side: the pool's
arrays are replaced wholesale by the jitted steps that update them, so
the engine composes with donation on backends that support it.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_examples_tpu.ops.attention import NEG_INF
from tensorflow_examples_tpu.telemetry import registry as registry_mod


def bucket_ladder(floor: int, max_len: int) -> list[int]:
    """Power-of-two padding buckets: ``floor, 2*floor, ...`` capped at
    (and always including) ``max_len``. One compiled program per rung;
    the smallest sufficient rung serves each request."""
    if floor < 1 or max_len < 1:
        raise ValueError(f"floor={floor} and max_len={max_len} must be >= 1")
    ladder: list[int] = []
    b = min(floor, max_len)
    while b < max_len:
        ladder.append(b)
        b *= 2
    ladder.append(max_len)
    return ladder


def pick_bucket(ladder: list[int], needed: int) -> int:
    """Smallest rung >= needed (ladder is ascending; last rung = max)."""
    for b in ladder:
        if b >= needed:
            return b
    raise ValueError(
        f"needed={needed} exceeds the largest bucket {ladder[-1]}"
    )


def gather_block_kv(blocks: jax.Array, block_tables: jax.Array) -> jax.Array:
    """Gather per-slot contiguous cache views out of a paged block pool.

    blocks: [NB, H, BS, D] — one layer's block pool (NB physical
    blocks of BS token rows each). block_tables: [S, nb] int32 — each
    slot's logical-block -> physical-block map for the active KV
    bucket (nb = bucket // BS; entries past a slot's allocation point
    at the reserved null block 0, whose rows length-masking never
    lets through). Returns [S, H, nb*BS, D] — exactly the dense-pool
    slice :func:`varlen_decode_attention` consumes.
    """
    s, nb = block_tables.shape
    _, h, bs, d = blocks.shape
    g = blocks[block_tables]             # [S, nb, H, BS, D]
    return g.transpose(0, 2, 1, 3, 4).reshape(s, h, nb * bs, d)


def varlen_decode_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    lengths: jax.Array,
    *,
    sm_scale: float | None = None,
    block_tables: jax.Array | None = None,
) -> jax.Array:
    """Single-token attention over per-slot populated cache prefixes.

    q: [S, H, D] — one new query per slot, sitting at global position
    ``lengths[s] - 1`` (its own K/V already written to the cache).
    k_cache / v_cache: [S, H, Kb, D] — the cache sliced to the active
    KV bucket; slots' rows >= their length are garbage and masked.
    lengths: [S] int32 populated lengths INCLUDING the new token.

    With ``block_tables`` ([S, nb] int32, ISSUE 8), k_cache/v_cache
    are instead a paged block pool ([NB, H, BS, D]) and each slot's
    view is gathered by its block table first
    (:func:`gather_block_kv`) — the paged mirror of the dense slice,
    same masking contract downstream.

    Returns [S, H, D]. Numerics mirror
    ``ops/decode.decode_attention_reference`` (f32 scores/softmax,
    output cast back to q.dtype) with the scalar length promoted to a
    vector — slot s sees columns < lengths[s], nothing else.
    """
    if block_tables is not None:
        k_cache = gather_block_kv(k_cache, block_tables)
        v_cache = gather_block_kv(v_cache, block_tables)
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "shd,shkd->shk", q, k_cache, preferred_element_type=jnp.float32
    ) * sm_scale
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    s = jnp.where(col < lengths[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    return jnp.einsum(
        "shk,shkd->shd", p, v_cache, preferred_element_type=jnp.float32
    ).astype(q.dtype)


def varlen_verify_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    positions: jax.Array,
    *,
    sm_scale: float | None = None,
    block_tables: jax.Array | None = None,
) -> jax.Array:
    """Multi-token generalization of :func:`varlen_decode_attention`
    for the speculative ``verify_k`` step (ISSUE 11).

    q: [S, T, H, D] — T new queries per slot (the launch token plus
    T-1 draft tokens), occupying global positions
    ``positions[s] .. positions[s] + T - 1``; their K/V rows are
    already written to the cache. Row t of slot s attends columns
    ``<= positions[s] + t`` — its own populated prefix INCLUDING
    itself, the verify-time mirror of continuous decode's per-slot
    length vector (T=1 reduces to exactly
    ``varlen_decode_attention(..., lengths=positions + 1)``).

    k_cache / v_cache: [S, H, Kb, D] bucket-sliced caches, or the
    paged block pool ([NB, H, BS, D]) when ``block_tables`` is given —
    same gather contract as the decode path. Returns [S, T, H, D];
    numerics mirror the decode path (f32 scores/softmax, probabilities
    cast to the value dtype, f32 accumulation) so a verify step's
    sampled tokens match what T single-token steps would have drawn —
    the property every token-identical golden with speculation on
    rests on.
    """
    if block_tables is not None:
        k_cache = gather_block_kv(k_cache, block_tables)
        v_cache = gather_block_kv(v_cache, block_tables)
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "sthd,shkd->shtk", q, k_cache,
        preferred_element_type=jnp.float32,
    ) * sm_scale
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
    row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    limit = positions[:, None, None, None] + row
    s = jnp.where(col <= limit, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum(
        "shtk,shkd->shtd", p, v_cache,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)
    return out.transpose(0, 2, 1, 3)


class KVCachePool:
    """Preallocated per-request KV slots with host-side bookkeeping.

    Device state: ``k``/``v`` [L, S, H, max_len, D], replaced wholesale
    by the engine's jitted steps. Host state: a free-slot list and the
    per-slot populated lengths (the numpy mirror the engine feeds back
    into every decode step). Thread-safe: the batcher loop allocates
    and frees while frontend threads read occupancy.
    """

    def __init__(
        self,
        *,
        num_layers: int,
        num_slots: int,
        num_heads: int,
        max_len: int,
        head_dim: int,
        dtype=jnp.float32,
        registry=None,
        sharding=None,
    ):
        if num_slots < 1:
            raise ValueError(f"num_slots={num_slots} must be >= 1")
        self.num_layers = num_layers
        self.num_slots = num_slots
        self.num_heads = num_heads
        self.max_len = max_len
        self.head_dim = head_dim
        self.dtype = dtype
        self._registry = registry
        # Optional NamedSharding for the [L, S, H, max_len, D] device
        # arrays (ISSUE 7): the engine derives it from its
        # ShardingConfig — heads over `model` is the tensor-parallel
        # layout — so the cache is born (and reallocated) in the same
        # placement the compiled steps consume. None = single-device
        # default placement, today's behavior.
        self._sharding = sharding
        self.k = self._zeros()
        self.v = self._zeros()
        self.lengths = np.zeros((num_slots,), np.int32)
        self._free = list(range(num_slots - 1, -1, -1))  # pop() -> slot 0 first
        self._lock = threading.Lock()
        self._publish()

    # ------------------------------------------------------------- slots

    def _reg(self):
        return (
            self._registry
            if self._registry is not None
            else registry_mod.default_registry()
        )

    def _publish(self) -> None:
        reg = self._reg()
        active = self.num_slots - len(self._free)
        # Dense pool: a claimed slot IS max_len of committed cache, so
        # slot occupancy and capacity occupancy are the same number.
        # The paged pool (paged_kv.py) splits them — kv_occupancy
        # becomes used-block fraction there — and publishes both.
        reg.gauge("serving/kv_occupancy").set(active / self.num_slots)
        reg.gauge("serving/kv_slot_occupancy").set(active / self.num_slots)
        reg.gauge("serving/kv_slots_active").set(active)
        reg.gauge("serving/kv_tokens").set(int(self.lengths.sum()))

    def alloc(self) -> int | None:
        """Claim a free slot (None when the pool is full). The slot's
        length starts at 0; the engine's prefill sets it."""
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self.lengths[slot] = 0
            self._publish()
            return slot

    def free(self, slot: int) -> None:
        with self._lock:
            if slot in self._free:  # double-free is a caller bug
                raise ValueError(f"slot {slot} is already free")
            self.lengths[slot] = 0
            self._free.append(slot)
            self._publish()

    def _zeros(self):
        shape = (self.num_layers, self.num_slots, self.num_heads,
                 self.max_len, self.head_dim)
        if self._sharding is None:
            return jnp.zeros(shape, self.dtype)
        # Born sharded: zeros are created per-shard in place — the full
        # pool never materializes on one device (it may only fit split).
        return jnp.zeros(shape, self.dtype, device=self._sharding)

    def reallocate(self) -> None:
        """Replace ``k``/``v`` with fresh zeroed device arrays (in the
        pool's sharding). The engine calls this when a donated compiled
        step fails at runtime: donation consumed the old buffers, so
        without replacement every later step would hit 'Array has been
        deleted'. Slot bookkeeping is untouched — the batcher fails and
        frees the whole in-flight set (its KV is gone) right after."""
        self.k = self._zeros()
        self.v = self._zeros()

    def reset(self) -> None:
        """Release every slot and zero the length mirror (the device
        arrays keep whatever garbage they hold — unpopulated rows are
        never read). Used after engine warmup."""
        with self._lock:
            self.lengths[:] = 0
            self._free = list(range(self.num_slots - 1, -1, -1))
            self._publish()

    @property
    def active_slots(self) -> int:
        with self._lock:
            return self.num_slots - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.active_slots / self.num_slots

    def max_active_length(self) -> int:
        """Longest populated prefix over all slots (0 when idle) — the
        engine picks the decode KV bucket from this."""
        with self._lock:
            return int(self.lengths.max(initial=0))

    # -------------------------------------------------- byte accounting

    @property
    def kv_bits(self) -> int:
        """Storage bits per cache element (uniform with the paged
        pool's quantization-aware figure)."""
        return jnp.dtype(self.dtype).itemsize * 8

    def bytes_per_slot(self) -> int:
        """K+V device bytes one claimed slot commits (the dense pool
        commits the full ``max_len`` extent per slot, used or not —
        the economics the paged pool exists to beat)."""
        return int(
            2 * self.num_layers * self.num_heads * self.max_len
            * self.head_dim * jnp.dtype(self.dtype).itemsize
        )

    def used_bytes(self) -> int:
        """Cache bytes committed to the currently active request set
        (tier-1 asserts the paged pool's figure for a mixed-length set
        is <= 1/2 of this one at equal concurrency)."""
        return self.active_slots * self.bytes_per_slot()
