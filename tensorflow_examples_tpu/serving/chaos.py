"""In-proc chaos fleet: restartable serving replicas the fault engine
can kill (ISSUE 10 tentpole (3)/(4)).

``serve_bench --router`` already stands N full serving stacks up in one
process; this module makes those stacks *units of failure*:

* :class:`InProcReplica` — one engine + batcher + HTTP frontend on a
  **pinned port**, with ``kill()`` (die like a SIGKILLed process: the
  frontend resets every in-flight connection, nothing answers politely)
  and ``restart()`` (fresh engine, full AOT warmup, same URL — the
  supervisor's unit of work). Each start registers its ``kill`` as the
  replica's ``crash@R:N`` callback (``utils.faults``), so a scripted
  fault schedule can kill it mid-decode deterministically.
* :class:`ChaosFleet` — N replicas (warmed concurrently, like
  ``serve_bench --router``), a hardened :class:`~.router.Router` in
  front, and a :class:`~.supervisor.Supervisor` watching the handles.
  One object = the whole failure-domain under test; the chaos
  acceptance tier (tests/test_chaos.py) and ``serve_bench --chaos``
  both build exactly this.

Failure semantics the harness guarantees (and the tier-1 golden
asserts): a ``kill()`` mid-decode surfaces to the router as a
*transport* failure — the router's in-flight failover replays the
victim requests from the prompt on a survivor, the per-request
``fold_in`` seeding makes the replayed streams token-identical to the
unbatched reference, the survivors take zero post-warmup recompiles,
and the supervisor restores the fleet (restart → re-warm → /health
green → readmit) without operator action.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable

from tensorflow_examples_tpu.serving.batcher import ContinuousBatcher
from tensorflow_examples_tpu.serving.frontend import ServingFrontend
from tensorflow_examples_tpu.serving.journal import (
    Lease,
    RequestJournal,
    StandbyMonitor,
)
from tensorflow_examples_tpu.serving.router import (
    Router,
    RouterConfig,
    RouterFrontend,
)
from tensorflow_examples_tpu.serving.supervisor import Supervisor
from tensorflow_examples_tpu.telemetry import registry as registry_mod
from tensorflow_examples_tpu.telemetry import tracing as tracing_mod
from tensorflow_examples_tpu.utils import faults as faults_mod

log = logging.getLogger(__name__)


class InProcReplica:
    """One full serving stack, rebuildable on a pinned port.

    ``build_engine`` returns a FRESH, un-warmed engine each call (its
    own registry — replicas must not share counters, or fleet-summed
    recompile accounting lies). The first ``start()`` binds an OS-
    assigned port and pins it; every restart re-binds the same port so
    the replica's URL — what the router and supervisor key on — is
    stable across its lifetimes.
    """

    def __init__(self, build_engine: Callable, *, replica_id: int,
                 port: int = 0):
        self.build_engine = build_engine
        self.replica_id = int(replica_id)
        self._port = int(port)  # 0 until the first bind pins it
        self.engine = None
        self.batcher: ContinuousBatcher | None = None
        self.frontend: ServingFrontend | None = None
        self._dead = True
        self._lock = threading.Lock()

    # ------------------------------------------------------- lifecycle

    def start(self) -> "InProcReplica":
        engine = self.build_engine()
        engine.replica_id = self.replica_id
        engine.warmup()  # the full AOT ladder, BEFORE any traffic
        batcher = ContinuousBatcher(engine).start()
        frontend = ServingFrontend(batcher, port=self._port).start()
        with self._lock:
            self.engine, self.batcher, self.frontend = (
                engine, batcher, frontend,
            )
            self._port = frontend.port
            self._dead = False
        # (Re-)register the crash verb: a ``crash@R:N`` fault on this
        # replica id now kills THIS incarnation's transport.
        faults_mod.register_serve_crash(self.replica_id, self.kill)
        log.info(
            "in-proc replica %d live at %s", self.replica_id, self.url
        )
        return self

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self._port}"

    def alive(self) -> bool:
        return not self._dead

    def kill(self) -> None:
        """Die like a killed process, NOW, from any thread (including
        this replica's own batcher loop mid-decode): reset every
        in-flight connection, stop listening. No drain, no 503s —
        clients observe transport failures. The batcher thread is left
        running (the crash fault raises InjectedCrash right after,
        failing its in-flight set into dead sockets); ``restart()``
        does the actual cleanup."""
        with self._lock:
            if self._dead:
                return
            self._dead = True
            frontend = self.frontend
        if frontend is not None:
            frontend.abort()
        log.warning(
            "in-proc replica %d KILLED (transport reset)",
            self.replica_id,
        )

    def restart(self) -> None:
        """Supervisor verb: tear down whatever is left of the previous
        incarnation, then bring up a fresh one (new engine, full
        warmup) on the same port. Blocking — the caller re-admits only
        after this returns and /health is green."""
        self._teardown()
        self.start()

    def _teardown(self) -> None:
        with self._lock:
            batcher, self.batcher = self.batcher, None
            frontend, self.frontend = self.frontend, None
            self.engine = None
            self._dead = True
        if frontend is not None:
            frontend.abort()
        if batcher is not None:
            # No drain: the incarnation is dead; fail anything left so
            # no future is ever abandoned unresolved.
            batcher.close(drain=False)

    def close(self) -> None:
        self._teardown()

    def stop(self) -> None:
        """GRACEFUL teardown (the autoscaler's scale-down verb, ISSUE
        13): finish everything already accepted, close the port
        politely — the opposite of ``kill()``/``close()``, which die
        like a SIGKILLed process. Callers drain at the router first,
        so by the time this runs the replica should already be idle."""
        with self._lock:
            batcher, self.batcher = self.batcher, None
            frontend, self.frontend = self.frontend, None
            self.engine = None
            self._dead = True
        if batcher is not None:
            batcher.close(drain=True)
        if frontend is not None:
            frontend.close()


class ChaosFleet:
    """N in-proc replicas + hardened router + supervisor, as one unit.

    ``engine_factories[k]`` builds replica k's engine. Warmups run
    concurrently (XLA compilation releases the GIL). ``router_cfg``
    defaults to chaos-appropriate hardening: fast probes, eject after 2
    consecutive dispatch failures, short cooldown.
    """

    def __init__(
        self,
        engine_factories: list,
        *,
        router_cfg: RouterConfig | None = None,
        supervisor_kw: dict | None = None,
    ):
        self.replicas = [
            InProcReplica(f, replica_id=k)
            for k, f in enumerate(engine_factories)
        ]
        self.router_cfg = router_cfg or RouterConfig(
            probe_interval_s=0.1,
            retry_budget_s=30.0,
            max_retries=4,
            eject_after=2,
            eject_cooldown_s=1.0,
        )
        self.supervisor_kw = dict(
            poll_s=0.1, health_stall_s=3.0, warm_timeout_s=300.0,
        )
        self.supervisor_kw.update(supervisor_kw or {})
        self.router: Router | None = None
        self.supervisor: Supervisor | None = None

    def start(self) -> "ChaosFleet":
        t0 = time.perf_counter()
        errors: list = [None] * len(self.replicas)

        def build(k):
            try:
                self.replicas[k].start()
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors[k] = e

        threads = [
            threading.Thread(target=build, args=(k,), daemon=True)
            for k in range(len(self.replicas))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for e in errors:
            if e is not None:
                self.close()
                raise e
        log.info(
            "chaos fleet: %d replicas warm in %.1fs (roles %s)",
            len(self.replicas), time.perf_counter() - t0,
            self.role_census(),
        )
        self.router = Router(
            [r.url for r in self.replicas], cfg=self.router_cfg
        ).start()
        self.supervisor = Supervisor(
            self.router, self.replicas, **self.supervisor_kw
        ).start()
        return self

    @property
    def urls(self) -> list:
        return [r.url for r in self.replicas]

    def role_census(self) -> dict:
        """{role: count} over the live replicas (ISSUE 12):
        heterogeneous prefill/decode fleets are first-class chaos
        subjects — the hetero golden asserts the topology it built."""
        census: dict = {}
        for rep in self.replicas:
            role = "mixed"
            if rep.engine is not None:
                role = getattr(rep.engine.cfg, "role", "mixed")
            census[role] = census.get(role, 0) + 1
        return census

    def healthy_count(self) -> int:
        if self.router is None:
            return 0
        return sum(
            r.eligible(self.router.cfg.unhealthy_after)
            for r in self.router.replicas
        )

    def await_fleet_green(self, n: int, timeout_s: float = 300.0) -> bool:
        """Block until ``n`` replicas are eligible again (the
        supervisor finished its restart cycle), or the timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.healthy_count() >= n:
                return True
            time.sleep(0.05)
        return False

    def close(self) -> None:
        if self.supervisor is not None:
            self.supervisor.close()
        if self.router is not None:
            self.router.close()
        for r in self.replicas:
            r.close()


class RouterPair:
    """Primary + warm-standby routers over one journal + lease (ISSUE
    16): the control plane as a unit of failure, the way
    :class:`ChaosFleet` makes replicas one.

    Both routers share ONE :class:`RequestJournal` instance (in-proc,
    the standby's tail-follow ``refresh()`` is a no-op because the
    primary's appends advance the shared read offset — the file is
    still written crash-safe, and ``serve_fleet --standby`` tails the
    same file across processes) and one metrics registry, so the
    journal/takeover counters survive the switch and a post-takeover
    stats line tells the whole story.

    Lifecycle: ``start()`` grants the primary the lease's first
    fencing token, replays any incomplete intents left by a previous
    incarnation, and brings up BOTH HTTP frontends — the standby's
    answers fenced 503s (retryable) until its monitor promotes it, so
    a client's failover retry loop needs no coordination beyond two
    URLs. ``kill_primary`` is registered as the ``killrouter@T``
    verb; on promotion the kill verb re-registers onto the new active
    router and the supervisor (if any) is re-pointed via
    ``adopt_router``.
    """

    def __init__(
        self,
        urls: list,
        *,
        journal_path: str,
        lease_path: str,
        router_cfg: RouterConfig | None = None,
        supervisor: Supervisor | None = None,
        primary_port: int = 0,
        standby_port: int = 0,
        standby_interval_s: float = 0.25,
        miss_budget_s: float = 1.5,
        dedup_window: int = 256,
    ):
        self.registry = registry_mod.MetricsRegistry()
        self.journal = RequestJournal(
            journal_path, dedup_window=dedup_window,
            registry=self.registry,
        )
        self.lease = Lease(lease_path)
        self.supervisor = supervisor
        self.cfg = router_cfg or RouterConfig(
            probe_interval_s=0.1,
            retry_budget_s=30.0,
            max_retries=4,
            eject_after=2,
            eject_cooldown_s=1.0,
        )
        # ONE trace recorder for both incarnations (ISSUE 18): the
        # journal stamps each intent/done with its trace_id, so a
        # takeover-survived request's post-promotion spans MERGE into
        # the trace the dead primary opened — a shared recorder is
        # what makes that merge land in one stitched tree (and keeps
        # /trace/{id} answering on whichever frontend is asked).
        self.recorder = tracing_mod.TraceRecorder(registry=self.registry)
        self.primary = Router(
            list(urls), cfg=self.cfg, registry=self.registry,
            journal=self.journal, lease=self.lease,
            recorder=self.recorder,
        )
        self.standby = Router(
            list(urls), cfg=self.cfg, registry=self.registry,
            journal=self.journal, recorder=self.recorder,
        )
        self.primary_frontend = RouterFrontend(
            self.primary, port=primary_port
        )
        self.standby_frontend = RouterFrontend(
            self.standby, port=standby_port
        )
        # Constructing the monitor fences the standby (token 0) — it
        # refuses dispatch until promoted.
        self.monitor = StandbyMonitor(
            self.standby, lease=self.lease, journal=self.journal,
            interval_s=standby_interval_s,
            miss_budget_s=miss_budget_s,
            on_promote=self._on_promote,
        )
        self.replayed_at_start = 0

    def start(self) -> "RouterPair":
        token = self.lease.acquire()
        self.primary.attach_lease(self.lease, token)
        self.journal.refresh()
        self.primary.start()
        # A previous incarnation may have died with accepted requests
        # un-served — drain them before taking traffic.
        self.replayed_at_start = self.primary.replay_incomplete()
        self.primary_frontend.start()
        self.standby_frontend.start()
        self.monitor.primary_url = self.primary_frontend.url("")
        faults_mod.register_router_kill(self.kill_primary)
        self.monitor.start()
        log.info(
            "router pair live: primary %s (token %d), standby %s "
            "(fenced), %d intent(s) replayed",
            self.primary_frontend.url(""), token,
            self.standby_frontend.url(""), self.replayed_at_start,
        )
        return self

    # ------------------------------------------------------- fault verbs

    def kill_primary(self) -> None:
        """Die like a SIGKILLed router process (the ``killrouter@T``
        verb): reset every in-flight client connection, stop the
        probe loop — and with it the lease heartbeats the standby's
        monitor is watching."""
        self.primary_frontend.abort()
        self.primary.close()
        log.warning("router pair: PRIMARY KILLED (transport reset)")

    def kill_standby(self) -> None:
        self.standby_frontend.abort()
        self.standby.close()
        log.warning("router pair: standby killed (transport reset)")

    def _on_promote(self, monitor: StandbyMonitor) -> None:
        if self.supervisor is not None:
            self.supervisor.adopt_router(self.standby)
        # The kill verb always lands on the ACTIVE router.
        faults_mod.register_router_kill(self.kill_standby)

    # -------------------------------------------------------- inspection

    @property
    def active_router(self) -> Router:
        return (
            self.standby if self.monitor.promoted.is_set()
            else self.primary
        )

    @property
    def active_frontend(self) -> RouterFrontend:
        return (
            self.standby_frontend if self.monitor.promoted.is_set()
            else self.primary_frontend
        )

    def endpoints(self) -> list:
        """Both generate URLs, primary first — a client retries in
        this order, and the fenced loser answers a retryable 503."""
        return [
            self.primary_frontend.url("/generate"),
            self.standby_frontend.url("/generate"),
        ]

    def close(self) -> None:
        faults_mod.register_router_kill(None)
        self.monitor.close()
        self.primary_frontend.close()
        self.standby_frontend.close()
        self.primary.close()
        self.standby.close()
        self.journal.close()
