"""Multi-replica router tier: one endpoint over N serving replicas.

PR 5 ended with one engine process per endpoint; the ROADMAP's
millions-of-users traffic needs N replicas behind one address with the
operational verbs a fleet actually uses (ISSUE 8). This module is that
tier, deliberately stdlib-only like every HTTP surface in the repo:

* **Load-aware dispatch** — a background thread probes each replica's
  ``/health`` (the PR 5 frontend already publishes queue depth, KV
  occupancy, active requests, drain state); requests go to the
  eligible replica with the lowest load score
  ``queue_depth + kv_occupancy`` (queue pressure dominates; the paged
  pool's ``kv_occupancy`` is used-block fraction, so short-prompt
  replicas correctly read as roomy — the ISSUE 8 gauge-semantics fix
  is what makes this signal honest), ties broken by fewest dispatches.
* **Prefix-affinity dispatch** (ISSUE 12, ``prefix_affinity`` on by
  default) — paged replicas also publish a prefix digest (content
  chain keys of their cached blocks, ``serving/scheduler.py``); the
  router hashes the prompt's block-aligned prefix chain and prefers
  the replica already holding the longest cached chain
  (``router/affinity_hits_total``), load-guarded by
  ``affinity_load_gap`` so affinity never starves a hot replica.
* **Disaggregated roles** (ISSUE 12) — replicas publish a ``role``
  (``mixed`` | ``prefill`` | ``decode``); when the fleet has both
  specialist roles, generate traffic routes prefill-leg ->
  KV-page handoff -> decode-leg (``/prefill`` -> ``/resume``,
  ``router/handoffs_total``), falling back to the full path on any
  leg failure (``router/handoff_fallbacks_total``) — roles are
  advisory, every replica still serves a full ``/generate``, so a
  dead role-holder is an ordinary in-flight failover.
* **Drain-aware rollout** — ``drain(url)`` (or ``POST /drain``) stops
  NEW dispatch to a replica while its in-flight requests finish on the
  replica itself; a replica that starts draining on its own (SIGTERM —
  its ``/health`` flips 503 with ``draining: true``) is detected by
  the probe and likewise rotated out without failing anything. Roll a
  fleet by draining one replica, restarting it, undraining, repeating.
* **Bounded retry with backoff** (ISSUE 10, replacing PR 8's
  retry-once) — a dispatch answered 503 (shed/draining) or a transport
  failure is retried up to ``max_retries`` times on different replicas
  of the same set with exponential backoff, all within a per-request
  wall budget (``retry_budget_s``); anything else a replica *answers*
  (400/404/504/500) passes through untouched — the router never
  re-runs a request a replica actually executed. A transport failure
  after dispatch is **in-flight failover**: the replica may have died
  mid-decode, and the re-dispatch replays the request from the prompt
  on another replica (``router/failovers_total``). Replay is safe and
  token-identical by construction — generation is a pure function of
  (params, prompt, seed) via the engine's per-request ``fold_in``
  seeding, so the failed-over stream matches what the dead replica
  would have produced, and the survivors' prefix cache makes the
  re-prefill cheap.
* **Per-replica circuit breaker** (ISSUE 10) — ``eject_after``
  consecutive dispatch failures eject the replica
  (``router/ejections_total``; breaker *open*, no dispatch); after
  ``eject_cooldown_s`` the breaker goes *half-open* and admits exactly
  one trial (a successful ``/health`` probe or one live request);
  success readmits (``router/readmits_total``, breaker closed),
  failure re-ejects for another cooldown.
* **Hedged dispatch** (ISSUE 10, opt-in ``hedge_after_s > 0``) — a
  request still unanswered after the hedge deadline is sent a second
  time to another replica; the first 200 wins and the loser is
  abandoned (``router/hedges_total`` / ``hedge_wins_total`` /
  ``hedge_cancelled_total``). Requests are idempotent-by-seeding, so
  hedging can never produce divergent streams — it only caps p99.
* **Fleet-down fast-fail** (ISSUE 13 satellite) — when not one replica
  is eligible and at least one is hard-down (every breaker open, probes
  failing, quarantined), requests shed immediately with their own
  counter (``router/fleet_down_total``) instead of burning
  ``retry_budget_s`` each rediscovering the same dead fleet; a
  fully-drained fleet (operator rollout, no failure) still gets the
  plain no-replica 503.
* **Elastic fleet verbs** (ISSUE 13) — ``add_replica(url)`` /
  ``remove_replica(url)`` let the autoscaler
  (``serving/supervisor.py``) resize the fleet at runtime; the probe
  also learns each replica's ``brownout_level`` from ``/health``, so
  the router's ``/health``/``/replicas``/stats line carry the fleet
  overload view (worst level, summed transitions).
* **Supervision hooks** — ``quarantine(url)`` / ``readmit(url)`` let
  ``serving/supervisor.py`` rotate a dead replica out while it is
  restarted and re-warmed, and re-admit it only after its ``/health``
  has gone green (``router/restarts_total`` counts completed
  restart cycles).
* **Canary compare** — replicas are grouped into sets (``base`` and
  ``canary``); a configured fraction of traffic goes to the canary
  set and per-set latency/throughput records
  (:meth:`Router.canary_records`) feed ``tools/run_diff.py``, whose
  serving-aware GATE_KEYS rank TTFT/TPOT/prefix-hit regressions first.

The router publishes its own observability surface
(:class:`RouterFrontend`): ``/metrics`` (Prometheus), ``/health``,
``/replicas``, ``/window`` (a schema-v6 ``kind="serving"`` line whose
serving object carries the v6 router fields), and the admin verbs
``POST /drain`` / ``POST /undrain``. ``tools/serve_fleet.py`` is the
CLI wrapper; ``tools/serve_bench.py --router`` measures the whole tier
and banks the ``serve_router`` record ``bench_gate`` accepts.
"""

from __future__ import annotations

import dataclasses
import http.server
import json
import logging
import queue
import socket
import threading
import time
import urllib.error
import urllib.request
import uuid

from tensorflow_examples_tpu.telemetry import registry as registry_mod
from tensorflow_examples_tpu.telemetry import schema
from tensorflow_examples_tpu.telemetry import slo as slo_mod
from tensorflow_examples_tpu.telemetry import timeseries as timeseries_mod
from tensorflow_examples_tpu.telemetry import tracing as tracing_mod
from tensorflow_examples_tpu.telemetry.serve import (
    json_safe,
    render_prometheus,
)
from tensorflow_examples_tpu.utils import faults as faults_mod

log = logging.getLogger(__name__)

_MAX_BODY = 1 << 20
_MAX_SAMPLES = 8192  # per-set latency samples kept for canary records


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    probe_interval_s: float = 0.5   # /health poll cadence per replica
    probe_timeout_s: float = 2.0
    request_timeout_s: float = 120.0
    retry_budget_s: float = 10.0    # wall budget for ALL retry attempts
    max_retries: int = 2            # bounded retry (ISSUE 10): total
    #                                 re-dispatches after the first try
    retry_backoff_s: float = 0.05   # base backoff, doubled per retry
    eject_after: int = 3            # consecutive DISPATCH failures ->
    #                                 circuit breaker opens (ejected)
    eject_cooldown_s: float = 3.0   # open -> half-open (one trial)
    hedge_after_s: float = 0.0      # >0: hedged dispatch for p99 — a
    #                                 request unanswered this long is
    #                                 sent again elsewhere, first 200
    #                                 wins, loser abandoned
    unhealthy_after: int = 3        # consecutive probe failures
    canary_fraction: float = 0.25   # traffic share when a canary set
    #                                 is configured
    prefix_affinity: bool = True    # ISSUE 12: prefer the replica
    #                                 already holding the longest cached
    #                                 chain of this prompt's blocks
    #                                 (probe-published prefix digests)
    affinity_load_gap: float = 2.0  # affinity never starves a hot
    #                                 replica: a cached-chain holder is
    #                                 only preferred while its load
    #                                 score is within this gap of the
    #                                 least-loaded eligible replica
    trace_sample_fraction: float = 0.01  # ISSUE 18 tail sampler: the
    #                                 seeded deterministic share of
    #                                 NORMAL traffic kept (slow/error/
    #                                 retried/failed-over/hedged/
    #                                 preempted/deduped/resumed/
    #                                 brownout traces are ALWAYS kept)
    trace_seed: int = 0             # the seeded fraction's hash salt


def _as_object(status: int, body) -> tuple[int, dict]:
    """Coerce a parsed reply to the (status, dict) contract. A replica
    answering valid-but-non-object JSON (a bare list/string/number) is
    as malformed as a torn body: status 0, so probes mark it unhealthy
    and dispatches treat it as retryable — never an AttributeError
    inside the probe loop (ISSUE 10 satellite)."""
    if isinstance(body, dict):
        return status, body
    return 0, {"error": f"non-object JSON reply: {type(body).__name__}"}


def _get_json(url: str, timeout: float) -> tuple[int, dict]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return _as_object(resp.status, json.loads(resp.read()))
    except urllib.error.HTTPError as e:
        try:
            return _as_object(e.code, json.loads(e.read() or b"{}"))
        except (ValueError, OSError):
            return e.code, {}
    except (OSError, ValueError) as e:
        return 0, {"error": f"{type(e).__name__}: {e}"}


def post_json(url: str, body: dict, timeout: float) -> tuple[int, dict]:
    """POST a JSON body, always returning ``(status, reply_dict)`` —
    status 0 on transport failure (reset, timeout, refused, torn
    body). The one JSON-over-HTTP client in the serving stack: the
    dispatcher, the probe loop's writes, and tools/serve_bench.py all
    route through it, so the status-0 contract cannot drift."""
    data = json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return _as_object(resp.status, json.loads(resp.read()))
    except urllib.error.HTTPError as e:
        try:
            return _as_object(e.code, json.loads(e.read() or b"{}"))
        except (ValueError, OSError):
            return e.code, {}
    except (OSError, ValueError) as e:
        # Transport failure: status 0 — the dispatcher treats it like a
        # 503 (retryable on another replica) and the probe loop will
        # notice a dead replica on its own.
        return 0, {"error": f"{type(e).__name__}: {e}"}


class _TraceState:
    """Per-request trace bookkeeping threaded through the dispatch
    path (ISSUE 18): the trace id, the router's root ``request`` span
    id, the incoming parent span (when the CLIENT originated the
    context), the SLO class, and the forced-keep flags the dispatch
    loop accumulates (retried / failover / hedged)."""

    __slots__ = ("trace_id", "root_id", "parent_id", "slo", "flags")

    def __init__(self, trace_id: str, root_id: str,
                 parent_id: str | None, slo: str):
        self.trace_id = trace_id
        self.root_id = root_id
        self.parent_id = parent_id
        self.slo = slo
        self.flags: set = set()


class ReplicaState:
    """One replica as the router sees it: probe-sourced load numbers +
    router-side rollout state."""

    # Mutable fields are written by the probe loop, the dispatcher, and
    # the rollout/supervision verbs — three thread families — so every
    # write (and every multi-field read that must not tear) happens
    # under the owning Router's lock. The `# guard:` annotations below
    # cover the state-machine/bookkeeping fields and make that contract
    # machine-checked (graftlint lock pass, ISSUE 14); the accepted
    # lock-free reads inside eligible() (called from the autoscaler/
    # chaos threads, where one stale decision is harmless) live in the
    # committed baseline. The probe-sourced load numbers (queue_depth,
    # kv_occupancy, active_requests, role, prefix digest, ...) are
    # deliberately UNANNOTATED: they are last-write-wins snapshots the
    # probe rewrites every sweep — the lint does not check them, and
    # cross-thread readers (load_score() from the supervisor tier)
    # accept staleness by design.
    def __init__(self, url: str, set_name: str = "base"):
        self.url = url.rstrip("/")
        self.set_name = set_name
        self.drained = False          # guard: Router._lock (operator rollout)
        self.draining_remote = False  # guard: Router._lock (replica SIGTERM)
        self.quarantined = False      # guard: Router._lock (being restarted)
        self.failures = 0             # guard: Router._lock (consecutive probe failures)
        self.probed = False           # guard: Router._lock
        self.last_probe_unix = 0.0
        self.queue_depth = 0.0
        self.kv_occupancy = 0.0
        self.active_requests = 0.0
        self.slots = 0
        self.post_warmup_recompiles = 0
        self.dispatched = 0           # guard: Router._lock
        self.completed = 0            # guard: Router._lock
        self.errors = 0               # guard: Router._lock
        # Cache-aware scheduling state (ISSUE 12), probe-sourced: the
        # replica's role (mixed serves everything — the pre-ISSUE-12
        # behavior), its prefix-cache block size, and the content chain
        # keys of the blocks it currently caches (the affinity digest).
        self.role = "mixed"
        self.block_size = 0
        self.prefix_digest: frozenset = frozenset()
        self.prefix_blocks = 0
        self.prefix_chains = 0
        # Overload state (ISSUE 13), probe-sourced: the replica's
        # brownout ladder level, its transition count, and its
        # digest-truncation flag.
        self.brownout_level = 0
        self.brownout_transitions = 0
        self.digest_truncated = False
        # Circuit breaker (ISSUE 10). States: "closed" (normal),
        # "open" (ejected — no dispatch until the cooldown expires),
        # "half_open" (cooldown expired — exactly ONE trial in flight
        # at a time; success readmits, failure re-ejects). Transitions
        # happen under the Router's lock.
        self.breaker = "closed"       # guard: Router._lock
        self.consec_errors = 0        # guard: Router._lock (consecutive dispatch failures)
        self.open_until = 0.0         # guard: Router._lock (monotonic: open -> half_open)
        self.half_open_trial = False  # guard: Router._lock (trial in flight)

    def breaker_poll_locked(self, now: float) -> None:
        """Open -> half-open once the cooldown expires (caller holds
        the router lock — the ``_locked`` suffix is the repo's
        caller-holds-the-lock convention, checked by graftlint)."""
        if self.breaker == "open" and now >= self.open_until:
            self.breaker = "half_open"
            self.half_open_trial = False

    def eligible(self, unhealthy_after: int,
                 now: float | None = None) -> bool:
        if (
            self.drained
            or self.draining_remote
            or self.quarantined
            or self.failures >= unhealthy_after
        ):
            return False
        if self.breaker == "closed":
            return True
        if now is None:
            now = time.monotonic()
        if self.breaker == "open":
            return now >= self.open_until  # pick() flips to half_open
        return not self.half_open_trial    # half_open: one trial only

    def load_score(self) -> float:
        """Least-loaded dispatch key: queued requests dominate, KV
        pressure (used-block fraction under paging) breaks near-ties."""
        return float(self.queue_depth) + float(self.kv_occupancy)

    def serves(self, role: str | None) -> bool:
        """Role capability filter: a ``mixed`` replica serves every
        leg; ``prefill``/``decode`` replicas serve their own leg.
        ``role=None`` (a full /generate) matches everyone — roles are
        a dispatch preference, not a capability wall, which is what
        makes killing a role-holder an ordinary failover."""
        return role is None or self.role in (role, "mixed")

    def snapshot_locked(self) -> dict:
        # Caller holds Router._lock (graftlint lock-pass convention).
        return {
            "url": self.url,
            "set": self.set_name,
            "role": self.role,
            "prefix_blocks": self.prefix_blocks,
            "prefix_chains": self.prefix_chains,
            "brownout_level": self.brownout_level,
            "digest_truncated": self.digest_truncated,
            "drained": self.drained,
            "draining_remote": self.draining_remote,
            "quarantined": self.quarantined,
            "breaker": self.breaker,
            "consec_errors": self.consec_errors,
            "probe_failures": self.failures,
            "queue_depth": self.queue_depth,
            "kv_occupancy": self.kv_occupancy,
            "active_requests": self.active_requests,
            "slots": self.slots,
            "post_warmup_recompiles": self.post_warmup_recompiles,
            "dispatched": self.dispatched,
            "completed": self.completed,
            "errors": self.errors,
        }


class _SetStats:
    """Per-replica-set client-side latency aggregates (the canary
    compare's raw material). Replies already carry the replica-measured
    ttft_s/total_s; tokens give TPOT."""

    def __init__(self):
        self.lock = threading.Lock()
        self.requests = 0             # guard: self.lock
        self.completed = 0            # guard: self.lock
        self.errors = 0               # guard: self.lock
        self.ttft: list[float] = []   # guard: self.lock
        self.tpot: list[float] = []   # guard: self.lock
        self.e2e: list[float] = []    # guard: self.lock
        self.tokens = 0               # guard: self.lock
        self.t0 = time.monotonic()

    def record(self, status: int, reply: dict) -> None:
        with self.lock:
            self.requests += 1
            if status != 200:
                self.errors += 1
                return
            self.completed += 1
            toks = len(reply.get("tokens") or ())
            self.tokens += toks
            ttft = reply.get("ttft_s")
            total = reply.get("total_s")
            if isinstance(ttft, (int, float)):
                self.ttft.append(float(ttft))
                if isinstance(total, (int, float)) and toks > 1:
                    self.tpot.append(
                        (float(total) - float(ttft)) / (toks - 1)
                    )
            if isinstance(total, (int, float)):
                self.e2e.append(float(total))
            for samples in (self.ttft, self.tpot, self.e2e):
                if len(samples) > _MAX_SAMPLES:
                    del samples[: len(samples) - _MAX_SAMPLES]

    @staticmethod
    def _pct(samples: list[float], q: float) -> float | None:
        if not samples:
            return None
        s = sorted(samples)
        idx = max(0, min(len(s) - 1, round(q / 100 * len(s) + 0.5) - 1))
        return round(s[int(idx)] * 1e3, 3)

    def record_doc(self, set_name: str) -> dict:
        with self.lock:
            wall = max(time.monotonic() - self.t0, 1e-9)
            return {
                "bench": "serve_router_set",
                "set": set_name,
                "requests": self.requests,
                "completed": self.completed,
                "errors": self.errors,
                "generated_tokens": self.tokens,
                "req_per_s": round(self.completed / wall, 3),
                "tok_per_s": round(self.tokens / wall, 3),
                "ttft_p50_ms": self._pct(self.ttft, 50),
                "ttft_p95_ms": self._pct(self.ttft, 95),
                "tpot_p50_ms": self._pct(self.tpot, 50),
                "tpot_p95_ms": self._pct(self.tpot, 95),
                "e2e_p95_ms": self._pct(self.e2e, 95),
            }


class Router:
    """Dispatcher + probe loop over replica sets (no sockets of its
    own — :class:`RouterFrontend` is the HTTP surface; tests drive
    ``handle()`` directly too)."""

    def __init__(
        self,
        replicas: list[str],
        *,
        canary: list[str] | None = None,
        cfg: RouterConfig | None = None,
        registry=None,
        journal=None,
        lease=None,
        fencing_token: int = 0,
        recorder=None,
        trace_path: str | None = None,
        slo_cfg=None,
        alert_path: str | None = None,
    ):
        if not replicas:
            raise ValueError("router needs at least one replica URL")
        self.cfg = cfg or RouterConfig()
        self.registry = (
            registry if registry is not None
            else registry_mod.MetricsRegistry()
        )
        self.replicas = [ReplicaState(u, "base") for u in replicas]
        self.replicas += [
            ReplicaState(u, "canary") for u in (canary or [])
        ]
        self.has_canary = any(
            r.set_name == "canary" for r in self.replicas
        )
        self._set_stats = {"base": _SetStats(), "canary": _SetStats()}
        self._lock = threading.Lock()
        self._req_counter = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._start_unix = time.time()
        # Control-plane durability (ISSUE 16): the request journal and
        # the active-router lease. Both optional — a journal-less
        # router still strips the client's request_id/resume_from
        # control fields (replicas reject unknown fields) and serves
        # resume by replay-and-skip; it just cannot dedupe or replay
        # across its own death.
        self.journal = journal
        if journal is not None and journal.registry is None:
            journal.registry = self.registry
        self._lease = lease
        self._fencing_token = int(fencing_token)
        # Per-request tracing (ISSUE 18): the recorder mints/accepts
        # trace contexts in handle(), assembles each request's span
        # tree from the router's own dispatch/leg spans plus the
        # replica-returned ones, and tail-samples at finish. Inject a
        # SHARED recorder (chaos.RouterPair does) so a takeover's
        # successor stitches onto the primary's traces in place.
        self._owns_recorder = recorder is None
        self.recorder = (
            recorder if recorder is not None
            else tracing_mod.TraceRecorder(
                registry=self.registry, path=trace_path,
                sample_fraction=self.cfg.trace_sample_fraction,
                seed=self.cfg.trace_seed,
            )
        )
        # SLO alerting (ISSUE 19): always on — a default SLOConfig is
        # deliberately generous, so the engine is silent until traffic
        # actually breaches an objective. Every finished ORGANIC
        # request feeds it (probe-tagged requests feed it through
        # serving/prober.py instead); firing/resolve transitions land
        # in ``alert_path`` as v14 ``kind="alert"`` lines and the
        # summary rides the stats line (the v14 keys).
        self.alerts = slo_mod.AlertEngine(
            slo_cfg, registry=self.registry, path=alert_path,
        )
        # In-process time-series store (ISSUE 19): sampled once per
        # stats_line() call — the existing stats cadence — and served
        # as GET /series by the frontend.
        self.series = timeseries_mod.TimeSeriesStore(self.registry)

    def attach_lease(self, lease, token: int) -> None:
        """(Re)bind this router to the active-router lease at fencing
        ``token``. Dispatch refuses once the lease holds a NEWER token
        (a promoted standby fenced this router out); the probe loop
        heartbeats the lease while the token is still the newest."""
        self._lease = lease
        self._fencing_token = int(token)

    # ------------------------------------------------------------ probes

    def probe_once(self) -> None:
        """One synchronous sweep (the background loop's body; tests
        call it directly for determinism)."""
        for r in self.replicas:
            status, body = _get_json(
                r.url + "/health", self.cfg.probe_timeout_s
            )
            r.last_probe_unix = time.time()
            if status == 0 or not isinstance(body, dict):
                # Transport failure OR a malformed/non-JSON body
                # (_get_json coerces the latter to status 0): the
                # replica is marked unhealthy and the sweep moves on to
                # the next one — garbage can fail a replica, never the
                # probe loop (ISSUE 10 satellite).
                with self._lock:
                    r.failures += 1
                    failures = r.failures
                if failures == self.cfg.unhealthy_after:
                    log.warning(
                        "replica %s unreachable or malformed after %d "
                        "probes — rotating out", r.url, failures,
                    )
                continue
            # Any HTTP answer means the process is alive; a 503 with
            # draining=true is the replica's own drain, not a failure.
            with self._lock:
                r.failures = 0
                r.probed = True
                r.draining_remote = bool(body.get("draining"))
                for field in ("queue_depth", "kv_occupancy",
                              "active_requests"):
                    v = body.get(field)
                    if isinstance(v, (int, float)):
                        setattr(r, field, float(v))
                for field in ("slots", "post_warmup_recompiles",
                              "prefix_blocks", "prefix_chains",
                              "brownout_level",
                              "brownout_transitions"):
                    v = body.get(field)
                    if isinstance(v, (int, float)):
                        setattr(r, field, int(v))
                r.digest_truncated = bool(body.get("digest_truncated"))
                # Cache-aware scheduling fields (ISSUE 12) — absent on
                # dense-pool or pre-ISSUE-12 replicas, which simply
                # never win an affinity preference.
                role = body.get("role")
                if isinstance(role, str) and role in (
                    "mixed", "prefill", "decode"
                ):
                    r.role = role
                bs = body.get("prefix_block_size")
                if isinstance(bs, (int, float)) and int(bs) > 0:
                    r.block_size = int(bs)
                digest = body.get("prefix_digest")
                if isinstance(digest, list):
                    r.prefix_digest = frozenset(
                        k for k in digest if isinstance(k, str)
                    )
                bloom = body.get("prefix_bloom")
                if isinstance(bloom, dict):
                    # ISSUE 15 satellite: a truncated replica ALSO
                    # publishes a bloom filter over its whole chain-key
                    # set — prefer it (the key list is capped; the
                    # filter is not). Malformed payloads fail THIS
                    # field only, never the sweep.
                    from tensorflow_examples_tpu.serving import (
                        scheduler,
                    )

                    try:
                        r.prefix_digest = scheduler.decode_bloom(bloom)
                    except ValueError:
                        pass  # keep the (truncated) key list
                # Half-open probe -> readmit (ISSUE 10): once the
                # breaker's cooldown has expired, a green /health is
                # the trial — the replica rejoins dispatch without
                # risking a live request on it.
                r.breaker_poll_locked(time.monotonic())
                if (
                    status == 200
                    and r.breaker == "half_open"
                    and not r.half_open_trial
                ):
                    r.breaker = "closed"
                    r.consec_errors = 0
                    self.registry.counter("router/readmits_total").inc()
                    log.info(
                        "replica %s readmitted (half-open /health probe "
                        "green)", r.url,
                    )
        with self._lock:
            eligible = sum(
                r.eligible(self.cfg.unhealthy_after)
                for r in self.replicas
            )
        self.registry.gauge("router/replicas_eligible").set(eligible)

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_once()
                self._heartbeat()
            except Exception:  # noqa: BLE001 — the probe must survive
                log.exception("replica probe sweep failed")
            self._stop.wait(self.cfg.probe_interval_s)

    def _heartbeat(self) -> None:
        """Refresh the active-router lease (ISSUE 16). Rides the probe
        cadence: a router whose probe loop stalls (or whose process
        dies) stops heartbeating, which is precisely the signal the
        warm standby promotes on. A fenced heartbeat is a no-op write-
        wise (the lease refuses it), so a stalled-then-revived primary
        can never clobber its successor's lease."""
        if self._lease is not None and self._fencing_token > 0:
            self._lease.heartbeat(self._fencing_token)

    def fenced(self) -> bool:
        """True when the lease holds a NEWER fencing token than ours:
        a standby promoted itself over this router, and every dispatch
        here must be refused (split-brain pin — no request is ever
        served by two routers). Also true for a never-promoted standby
        (token 0 vs any granted lease): passivity and fencing are the
        same check."""
        if self._lease is None:
            return False
        return self._lease.fenced(self._fencing_token)

    def start(self) -> "Router":
        self.probe_once()  # synchronous first sweep: never dispatch blind
        self._heartbeat()
        self._thread = threading.Thread(
            target=self._probe_loop, name="router-probe", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._owns_recorder:
            # An injected (shared) recorder outlives this router — the
            # RouterPair's successor is still finishing traces into it.
            self.recorder.close()
        self.alerts.close()

    # ------------------------------------------------ elastic fleet (ISSUE 13)

    def add_replica(self, url: str,
                    set_name: str = "base") -> ReplicaState:
        """Register a replica at runtime (the autoscaler's scale-up
        verb). Idempotent per URL. The replica list is replaced
        copy-on-write, so the probe sweep and pick() iterate a stable
        snapshot without holding the lock."""
        url = url.rstrip("/")
        with self._lock:
            for r in self.replicas:
                if r.url == url:
                    return r
            r = ReplicaState(url, set_name)
            self.replicas = self.replicas + [r]
            self.has_canary = any(
                rep.set_name == "canary" for rep in self.replicas
            )
        self.registry.counter("router/replicas_added_total").inc()
        log.info("replica %s added (fleet now %d)", url,
                 len(self.replicas))
        return r

    def remove_replica(self, url: str) -> bool:
        """Deregister a replica at runtime (the autoscaler's
        scale-down verb — callers drain first; removal itself never
        cancels anything)."""
        url = url.rstrip("/")
        with self._lock:
            keep = [r for r in self.replicas if r.url != url]
            if len(keep) == len(self.replicas):
                return False
            self.replicas = keep
            self.has_canary = any(
                r.set_name == "canary" for r in self.replicas
            )
        self.registry.counter("router/replicas_removed_total").inc()
        log.info("replica %s removed (fleet now %d)", url,
                 len(self.replicas))
        return True

    # ---------------------------------------------------------- rollout

    def _find(self, url: str) -> ReplicaState | None:
        url = url.rstrip("/")
        for r in self.replicas:
            if r.url == url:
                return r
        return None

    def drain(self, url: str) -> bool:
        """Stop dispatching to ``url`` (in-flight requests finish on
        the replica; nothing is cancelled). The rollout verb. The flag
        flips under the lock (ISSUE 14 lock-pass finding: an unlocked
        write here raced pick()'s locked eligibility read — quarantine/
        readmit always locked, drain/undrain had drifted)."""
        r = self._find(url)
        if r is None:
            return False
        with self._lock:
            r.drained = True
        log.info("replica %s drained (router-side)", r.url)
        return True

    def undrain(self, url: str) -> bool:
        r = self._find(url)
        if r is None:
            return False
        with self._lock:
            r.drained = False
            r.failures = 0
        return True

    # ------------------------------------------------------ supervision

    def quarantine(self, url: str) -> bool:
        """Rotate a replica out while the supervisor restarts it: no
        dispatch, no matter what its breaker or probe state says, until
        :meth:`readmit`."""
        r = self._find(url)
        if r is None:
            return False
        with self._lock:
            r.quarantined = True
        log.warning("replica %s quarantined (supervisor)", r.url)
        return True

    def readmit(self, url: str) -> bool:
        """Re-admit a restarted replica with a clean slate (the
        supervisor calls this only after its /health has gone green)."""
        r = self._find(url)
        if r is None:
            return False
        with self._lock:
            r.quarantined = False
            r.draining_remote = False
            r.failures = 0
            r.consec_errors = 0
            r.breaker = "closed"
            r.half_open_trial = False
        self.registry.counter("router/readmits_total").inc()
        log.info("replica %s readmitted (supervisor)", r.url)
        return True

    # --------------------------------------------------------- dispatch

    def pick(self, *, set_name: str | None = None,
             exclude: tuple = (), prompt=None,
             role: str | None = None,
             key_cache: dict | None = None) -> ReplicaState | None:
        """Least-loaded eligible replica (of ``set_name`` when the
        canary split is routing), ties broken by fewest dispatches. A
        half-open replica may be picked for exactly one trial request
        at a time (the dispatch outcome closes or re-opens its
        breaker).

        ISSUE 12: with ``prompt`` (token ids) and ``prefix_affinity``
        on, the replica already holding the longest cached chain of the
        prompt's block-aligned prefix wins — but only while its load
        score stays within ``affinity_load_gap`` of the least-loaded
        candidate, so affinity can never starve a hot replica.
        ``role`` narrows the pool to replicas serving that leg
        (mixed always qualifies)."""
        with self._lock:
            now = time.monotonic()
            pool = []
            for r in self.replicas:
                r.breaker_poll_locked(now)
                if (
                    r.eligible(self.cfg.unhealthy_after, now)
                    and r not in exclude
                    and (set_name is None or r.set_name == set_name)
                    and r.serves(role)
                ):
                    pool.append(r)
            if not pool:
                return None
            best = self._pick_locked(pool, prompt, key_cache)
            best.dispatched += 1
            if best.breaker == "half_open":
                best.half_open_trial = True
            return best

    def _pick_locked(self, pool: list, prompt,
                     key_cache: dict | None = None) -> ReplicaState:
        """Affinity-then-load choice over an eligible pool (caller
        holds the lock). ``key_cache`` ({block_size: chain keys},
        request-scoped when handle() passes one) keeps the prompt
        hashed at most once per block size per REQUEST — not per pick,
        retry, leg, and fallback."""
        least = min(pool, key=lambda r: (r.load_score(), r.dispatched))
        if not self.cfg.prefix_affinity or not prompt:
            return least
        from tensorflow_examples_tpu.serving import scheduler

        keys_by_bs = key_cache if key_cache is not None else {}
        best, best_aff = least, 0
        cap = least.load_score() + self.cfg.affinity_load_gap
        for r in pool:
            if not r.prefix_digest or r.block_size < 1:
                continue
            if r.load_score() > cap:
                continue  # affinity must not starve a hot replica
            keys = keys_by_bs.get(r.block_size)
            if keys is None:
                keys = scheduler.prompt_chain_keys(prompt, r.block_size)
                keys_by_bs[r.block_size] = keys
            aff = scheduler.affinity_blocks(keys, r.prefix_digest)
            if aff > best_aff or (
                aff == best_aff and aff > 0
                and (r.load_score(), r.dispatched)
                < (best.load_score(), best.dispatched)
            ):
                best, best_aff = r, aff
        if best_aff > 0:
            self.registry.counter("router/affinity_hits_total").inc()
        return best

    def fleet_down(self) -> bool:
        """True when NOT ONE replica is eligible AND at least one is
        hard-down — breaker open, probe-failed, or quarantined (ISSUE
        13 satellite). The fast-fail check: a total outage must shed
        each request in milliseconds, not burn ``retry_budget_s`` per
        queued request rediscovering the same dead fleet. A fleet
        that is merely drained everywhere (an operator rollout, no
        failure anywhere) is NOT an outage — that stays the plain
        no-replica 503."""
        now = time.monotonic()
        hard_down = False
        with self._lock:
            for r in self.replicas:
                r.breaker_poll_locked(now)
                if r.eligible(self.cfg.unhealthy_after, now):
                    return False
                if (
                    r.quarantined
                    or r.failures >= self.cfg.unhealthy_after
                    or r.breaker == "open"
                ):
                    hard_down = True
        return hard_down

    def _route_set(self) -> str | None:
        """Which set this request goes to (None = no split): the canary
        set receives ``canary_fraction`` of traffic, interleaved
        deterministically rather than sampled."""
        if not self.has_canary:
            return None
        with self._lock:
            n = self._req_counter
            self._req_counter += 1
        f = min(max(self.cfg.canary_fraction, 0.0), 1.0)
        return "canary" if int((n + 1) * f) != int(n * f) else "base"

    # -------------------------------------------- dispatch bookkeeping

    def _note_success(self, r: ReplicaState) -> None:
        with self._lock:
            r.completed += 1
            r.consec_errors = 0
            if r.breaker != "closed":
                r.breaker = "closed"
                r.half_open_trial = False
                self.registry.counter("router/readmits_total").inc()
                log.info(
                    "replica %s readmitted (half-open trial request "
                    "succeeded)", r.url,
                )

    def _note_failure(self, r: ReplicaState, *, transport: bool,
                      draining: bool, breaker: bool = True,
                      shed: bool = False) -> None:
        """Book one dispatch failure. ``transport`` also bumps the
        probe-failure count (the replica may be gone); ``draining``
        marks the replica's own drain instead of tripping the breaker
        (an orderly drain is not a fault); ``shed`` marks a POLICY 503
        (queue full / brownout — the replica answered, it is alive and
        healthy, just overloaded: under a flash crowd the breaker
        tripping on sheds would eject the whole fleet and turn correct
        batch-class shedding into an interactive outage, ISSUE 13);
        ``breaker=False`` for 4xx replies (the request's fault, not the
        replica's)."""
        now = time.monotonic()
        with self._lock:
            r.errors += 1
            if transport:
                r.failures += 1
            if draining:
                r.draining_remote = True
                r.half_open_trial = False
                return
            if shed:
                # An answered shed is PROOF of life: reset the breaker
                # streak (a replica alternating sheds and transport
                # errors is flapping, not dispatch-failing) and release
                # any half-open trial so the probe path can readmit.
                r.consec_errors = 0
                r.half_open_trial = False
                self.registry.counter(
                    "router/replica_sheds_total"
                ).inc()
                return
            if not breaker:
                return
            r.consec_errors += 1
            if r.breaker == "half_open":
                r.breaker = "open"
                r.open_until = now + self.cfg.eject_cooldown_s
                r.half_open_trial = False
                self.registry.counter("router/ejections_total").inc()
                log.warning(
                    "replica %s re-ejected (half-open trial failed); "
                    "next probe in %.1fs", r.url,
                    self.cfg.eject_cooldown_s,
                )
            elif (
                r.breaker == "closed"
                and r.consec_errors >= self.cfg.eject_after
            ):
                r.breaker = "open"
                r.open_until = now + self.cfg.eject_cooldown_s
                self.registry.counter("router/ejections_total").inc()
                log.warning(
                    "replica %s EJECTED after %d consecutive dispatch "
                    "failures (circuit breaker open, half-open probe "
                    "in %.1fs)", r.url, r.consec_errors,
                    self.cfg.eject_cooldown_s,
                )

    def _send_to(self, r: ReplicaState, body: dict,
                 kind: str) -> tuple[int, dict]:
        """One real dispatch to one replica, with breaker bookkeeping."""
        status, reply = post_json(
            r.url + "/" + kind, body, self.cfg.request_timeout_s
        )
        if status == 200:
            self._note_success(r)
        elif status in (0, 503):
            self._note_failure(
                r, transport=(status == 0),
                draining=bool(reply.get("draining")),
                # A policy shed (queue/brownout) is breaker-exempt; a
                # KV-exhaustion shed is NOT — a wedged-full pool sheds
                # forever and must still be ejectable.
                shed=(
                    status == 503
                    and bool(reply.get("shed"))
                    and not reply.get("exhausted")
                ),
            )
        else:
            # The replica ANSWERED (400/404/500/504): never re-run the
            # request elsewhere. 5xx still counts against the breaker —
            # a replica answering 500s is failing; a 4xx is the
            # request's own fault.
            self._note_failure(
                r, transport=False, draining=False,
                breaker=(status >= 500),
            )
        return status, reply

    def _dispatch(self, primary: ReplicaState, body: dict, kind: str,
                  set_name: str | None, tried: list, tr=None,
                  parent_span_id: str | None = None
                  ) -> tuple[int, dict]:
        """One dispatch attempt — hedged when ``hedge_after_s`` is set:
        if the primary has not answered by the hedge deadline, the
        request is sent again to another replica; the first 200 wins
        and the loser is abandoned (its eventual reply is discarded;
        idempotent-by-seeding makes the duplicate execution harmless).
        Any hedge replica used is appended to ``tried``."""
        if self.cfg.hedge_after_s <= 0:
            return self._send_to(primary, body, kind)
        results: queue.Queue = queue.Queue()

        def run(rep):
            results.put((rep, *self._send_to(rep, body, kind)))

        threading.Thread(
            target=run, args=(primary,), name="router-dispatch",
            daemon=True,
        ).start()
        try:
            _, status, reply = results.get(
                timeout=self.cfg.hedge_after_s
            )
            return status, reply  # answered before the hedge deadline
        except queue.Empty:
            pass
        hedge = self.pick(set_name=set_name, exclude=tuple(tried))
        if hedge is None and set_name is not None:
            hedge = self.pick(exclude=tuple(tried))
        if hedge is None:
            _, status, reply = results.get()  # nothing to hedge with
            return status, reply
        tried.append(hedge)
        self.registry.counter("router/hedges_total").inc()
        self.registry.counter("router/dispatched_total").inc()
        t_hedge = time.monotonic()
        if tr is not None:
            tr.flags.add("hedged")
        threading.Thread(
            target=run, args=(hedge,), name="router-hedge", daemon=True,
        ).start()

        def hedge_span(won: bool):
            # The hedge leg is router-side bookkeeping: its span hangs
            # off the ATTEMPT that spawned it, tagged with whether the
            # hedge's reply was the one that answered the client.
            if tr is not None:
                self.recorder.add_span(
                    tr.trace_id, tracing_mod.close_span(
                        "hedge", t_hedge, parent_id=parent_span_id,
                        tags={"replica": hedge.url, "won": won},
                    )
                )

        first_failure = None
        for arrival in range(2):
            rep, status, reply = results.get()
            if status == 200:
                if arrival == 0:
                    # The slower dispatch is still in flight: abandon
                    # it — its reply is discarded on arrival (only
                    # breaker bookkeeping runs).
                    self.registry.counter(
                        "router/hedge_cancelled_total"
                    ).inc()
                if rep is hedge:
                    self.registry.counter(
                        "router/hedge_wins_total"
                    ).inc()
                hedge_span(won=rep is hedge)
                return status, reply
            if first_failure is None:
                first_failure = (status, reply)
        hedge_span(won=False)
        return first_failure

    # ------------------------------------- disaggregated roles (ISSUE 12)

    @staticmethod
    def _clean_prompt(body: dict):
        """The request's token ids when hashable for affinity/handoff
        (a 'text' body has no ids until a replica tokenizes it)."""
        prompt = body.get("prompt")
        if (
            isinstance(prompt, list) and prompt
            and all(isinstance(t, int) and not isinstance(t, bool)
                    for t in prompt)
        ):
            return prompt
        return None

    def _disagg_ready(self) -> bool:
        """True when the fleet has BOTH an eligible prefill-role and an
        eligible decode-role replica — the topology the handoff path
        exists for. A dead prefill replica flips this off, and generate
        traffic falls back to the full path on whoever is left."""
        now = time.monotonic()
        with self._lock:
            roles = {
                r.role for r in self.replicas
                if r.eligible(self.cfg.unhealthy_after, now)
            }
        return "prefill" in roles and "decode" in roles

    def _leg(self, body: dict, kind: str, role: str | None,
             prompt, key_cache: dict | None = None,
             tr=None) -> dict | None:
        """One handoff leg with the same bounded-retry discipline as
        the full path (different replica per attempt, leg-scoped wall
        budget); None when the leg cannot complete — the caller falls
        back to a full /generate, which is always safe because
        generation is a pure function of (params, prompt, seed).

        Deliberately SIMPLER than handle()'s loop: no cross-set
        fallback and no wait-out-and-rescan on an empty pool — a leg
        that cannot find a role-holder right now should not burn the
        request's budget waiting for one, because the full path IS the
        retry continuation and every replica can serve it."""
        reg = self.registry
        t0 = time.monotonic()
        tried: list[ReplicaState] = []
        attempts = 0
        while True:
            within = time.monotonic() - t0 < self.cfg.retry_budget_s
            r = self.pick(
                prompt=prompt, role=role, exclude=tuple(tried),
                key_cache=key_cache,
            )
            if r is None:
                return None
            tried.append(r)
            reg.counter("router/dispatched_total").inc()
            send = body
            span_id = None
            t_att = time.monotonic()
            if tr is not None:
                # Same per-attempt discipline as the full path: each
                # leg attempt gets its own span and hands the replica
                # a context parented under it, so a handoff trace
                # shows prefill and resume legs side by side with
                # their replica-side segments nested inside.
                span_id = tracing_mod.new_span_id()
                send = dict(body)
                send["trace"] = {
                    "trace_id": tr.trace_id,
                    "parent_span_id": span_id,
                    "sampled": True,
                }
            status, reply = self._send_to(r, send, kind)
            if tr is not None:
                rspans = reply.pop("trace_spans", None) \
                    if isinstance(reply, dict) else None
                if rspans:
                    self.recorder.ingest(
                        tr.trace_id, rspans, parent_id=span_id
                    )
                self.recorder.add_span(
                    tr.trace_id, tracing_mod.close_span(
                        f"{kind}_leg", t_att, parent_id=tr.root_id,
                        span_id=span_id, tags={
                            "replica": r.url,
                            "role": role or "any",
                            "attempt": attempts + 1,
                            "status": int(status),
                        },
                    )
                )
            if status == 200:
                return reply
            if (
                status in (0, 503)
                and attempts < self.cfg.max_retries
                and within
            ):
                attempts += 1
                reg.counter("router/retries_total").inc()
                if tr is not None:
                    tr.flags.add("retried")
                if status == 0:
                    # The role-holder died mid-leg: in-flight failover,
                    # same accounting as the full path.
                    reg.counter("router/failovers_total").inc()
                    if tr is not None:
                        tr.flags.add("failover")
                backoff = self.cfg.retry_backoff_s * (2 ** (attempts - 1))
                remaining = self.cfg.retry_budget_s - (
                    time.monotonic() - t0
                )
                if backoff > 0 and remaining > 0:
                    time.sleep(min(backoff, remaining))
                continue
            return None

    def _decode_cached_tokens(self, prompt, key_cache: dict) -> int:
        """Digest exchange for the streaming delta handoff (ISSUE 15
        satellite): how many leading prompt tokens EVERY eligible
        resume-side replica already caches (per its last probe) — the
        skip that is safe whichever replica the affinity-routed resume
        leg lands on. Conservative by construction (the minimum over
        the tier); the importer still validates its cache actually
        covers the skip (probe staleness, bloom false positives) and a
        mismatch 400 falls back to the full path, never a torn cache."""
        from tensorflow_examples_tpu.serving import scheduler

        now = time.monotonic()
        with self._lock:
            candidates = [
                r for r in self.replicas
                if r.eligible(self.cfg.unhealthy_after, now)
                and r.serves("decode")
            ]
            best: int | None = None
            for r in candidates:
                if r.block_size < 1 or not r.prefix_digest:
                    return 0
                keys = key_cache.get(r.block_size)
                if keys is None:
                    keys = scheduler.prompt_chain_keys(
                        prompt, r.block_size
                    )
                    key_cache[r.block_size] = keys
                tokens = scheduler.affinity_blocks(
                    keys, r.prefix_digest
                ) * r.block_size
                best = tokens if best is None else min(best, tokens)
        return best or 0

    def _handle_disagg(self, body: dict, prompt,
                       key_cache: dict | None = None,
                       tr=None) -> tuple[int, dict] | None:
        """Prefill/decode handoff: run the prompt on a prefill-role
        replica (affinity applies — that is where the prefix caches
        live), ship the returned KV pages to a decode-role replica's
        /resume, and reply its stream. Replica-measured ttft_s/total_s
        both gain the prefill leg's wall so client-facing TPOT
        ((total - ttft) / (n - 1)) stays a pure decode number. None on
        any failure — the caller replays the request through the full
        path (token-identical by seeding), so a dead role-holder costs
        a failover, never a request."""
        # Streaming delta (ISSUE 15): tell the prefill leg how many
        # leading tokens the decode tier already caches — those pages
        # never enter the wire. The resume body stays untouched (the
        # skip is encoded in the pages' own start_block meta).
        pbody = body
        skip = self._decode_cached_tokens(
            prompt, key_cache if key_cache is not None else {}
        )
        if skip:
            pbody = dict(body)
            pbody["skip_tokens"] = skip
        preply = self._leg(pbody, "prefill", "prefill", prompt,
                           key_cache, tr)
        if (
            not isinstance(preply, dict)
            or not isinstance(preply.get("pages"), dict)
            or not isinstance(preply.get("first_token"), int)
        ):
            return None
        res_body = dict(body)
        res_body["pages"] = preply["pages"]
        res_body["first_token"] = preply["first_token"]
        # The resume leg is affinity-routed too: importers publish the
        # prompt into their own prefix cache, so repeated handoffs of a
        # shared prompt park on the decode replica already holding it
        # (one copy, cold-tail-only scatter) instead of spreading N
        # copies across the decode tier.
        dreply = self._leg(res_body, "resume", "decode", prompt,
                           key_cache, tr)
        if not isinstance(dreply, dict):
            return None
        self.registry.counter("router/handoffs_total").inc()
        if skip:
            # Counted only on a COMPLETED handoff: a fallback after a
            # stale-digest 400 saved nothing, and the "tokens kept off
            # the wire" metric must not overstate itself.
            self.registry.counter(
                "router/handoff_delta_tokens_total"
            ).inc(skip)
        pre_total = preply.get("total_s")
        if isinstance(pre_total, (int, float)):
            for key in ("ttft_s", "total_s"):
                if isinstance(dreply.get(key), (int, float)):
                    dreply[key] = dreply[key] + float(pre_total)
        return 200, dreply

    # ------------------------------------------------------ entry point

    def handle(self, body: dict, *, kind: str) -> tuple[int, dict]:
        """Dispatch one generate/classify request: least-loaded pick
        with prefix affinity, bounded retry with backoff on
        503/transport failure (different replica of the same set,
        within the per-request wall budget). A transport failure
        mid-request is an in-flight failover: the re-dispatch replays
        the request from the prompt on another replica,
        token-identical by the per-request seeding. On a fleet with
        disaggregated roles, generate requests route through the
        prefill->decode handoff first (canary split and hedging apply
        to the full path only), falling back to the full path whenever
        a leg cannot complete.

        ISSUE 16 control plane: generate bodies may carry the client
        fields ``request_id`` (idempotency key) and ``resume_from`` (a
        committed-token offset) — both stripped before dispatch
        (replica frontends reject unknown fields). With a journal
        attached, a duplicated ``request_id`` inside the dedupe window
        returns the ORIGINAL tokens (``router/dedup_hits_total``, no
        second generation); every accepted token-id request appends an
        intent record before dispatch and a progress+done record on
        completion; ``resume_from > 0`` answers with the remainder of
        the SAME stream (journal dedupe hit, or replay-and-skip — the
        re-dispatch is token-identical by seeding, so slicing off the
        committed prefix IS the original stream's tail). A router
        whose lease is fenced (a promoted standby holds a newer token)
        refuses every dispatch with a retryable 503.

        ISSUE 19 probes: a body carrying ``"probe": true`` (the
        synthetic canary prober's tag, stripped before dispatch) rides
        the NORMAL dispatch path — same compiled replica code, same
        retry machinery — but is excluded from the organic request
        accounting: it never touches the journal (no dedupe-window
        entry, no tenant intent record), never counts in
        ``router/requests_total``, and never feeds the AlertEngine's
        organic rules (the prober reports its own results through
        ``observe_probe``). Probe traffic counts only under the
        ``probe/`` instruments."""
        reg = self.registry
        is_probe = False
        if kind == "generate" and "probe" in body:
            body = dict(body)  # never mutate the caller's dict
            is_probe = bool(body.pop("probe"))
        if is_probe:
            reg.counter("probe/router_requests_total").inc()
        else:
            reg.counter("router/requests_total").inc()
        t0 = time.monotonic()
        request_id: str | None = None
        resume_from = 0
        if kind == "generate" and (
            "request_id" in body or "resume_from" in body
        ):
            body = dict(body)  # never mutate the caller's dict
            request_id = body.pop("request_id", None)
            resume_from = body.pop("resume_from", 0)
            if request_id is not None and (
                not isinstance(request_id, str) or not request_id
            ):
                return 400, {
                    "error": "'request_id' must be a non-empty string"
                }
            if (
                isinstance(resume_from, bool)
                or not isinstance(resume_from, int)
                or resume_from < 0
            ):
                return 400, {
                    "error": "'resume_from' must be a non-negative "
                             "committed-token offset"
                }
        # Per-request tracing (ISSUE 18): accept the client's wire
        # context or mint one; the "trace" body field is the router's
        # to own from here (each dispatch attempt re-issues it with
        # that attempt's span as the parent).
        tr: _TraceState | None = None
        if kind == "generate":
            wire = body.get("trace")
            if "trace" in body:
                body = dict(body)
                body.pop("trace")
            if not isinstance(wire, dict):
                wire = None
            ctx = self.recorder.new_context(wire)
            parent = (wire or {}).get("parent_span_id")
            tr = _TraceState(
                ctx.trace_id,
                tracing_mod.new_span_id(),
                parent if isinstance(parent, str) and parent else None,
                body.get("slo")
                if body.get("slo") in ("interactive", "batch")
                else "interactive",
            )
        if self.fenced():
            # Split-brain pin (ISSUE 16): a stalled-then-revived
            # primary must never dispatch against the fleet a promoted
            # standby now owns. Retryable — the client's next attempt
            # lands on the active router.
            reg.counter("router/fenced_dispatch_total").inc()
            reply = {
                "error": "router fenced: a newer lease token is "
                         "active (standby takeover)",
                "fenced": True, "retry": True, "shed": True,
            }
            reg.histogram("router/e2e").record(time.monotonic() - t0)
            self._trace_finish(tr, 503, reply, t0, probe=is_probe)
            return 503, reply
        # Probe exclusion (ISSUE 19): a canary probe must never enter
        # the dedupe window or leave tenant intent records — a fleet
        # restart would otherwise replay synthetic traffic.
        journal = (
            self.journal if kind == "generate" and not is_probe
            else None
        )
        if journal is not None and request_id is not None:
            hit = journal.lookup(request_id)
            if hit is not None:
                # Idempotency-key dedupe: the original stream answers
                # the retry — no second generation burned.
                reg.counter("router/dedup_hits_total").inc()
                tokens = list(hit["tokens"])
                reply = {
                    "tokens": tokens[resume_from:],
                    "request_id": request_id,
                    "dedup": True,
                }
                if resume_from:
                    reg.counter("router/resumed_streams_total").inc()
                    reply["resumed"] = True
                    reply["resume_from"] = resume_from
                reg.histogram("router/e2e").record(
                    time.monotonic() - t0
                )
                if tr is not None:
                    # The stitch (ISSUE 18): the journal's done record
                    # carries the ORIGINAL request's trace_id — adopt
                    # it, so the dedupe fast path's spans JOIN that
                    # trace (across routers too: a takeover successor
                    # shares the journal) instead of forking a new one.
                    self.recorder.add_span(
                        tr.trace_id, tracing_mod.close_span(
                            "dedupe_hit", t0, parent_id=tr.root_id,
                            tags={"request_id": request_id},
                        )
                    )
                    orig_tid = hit.get("trace_id")
                    if isinstance(orig_tid, str) and orig_tid:
                        self.recorder.adopt(tr.trace_id, orig_tid)
                        tr.trace_id = orig_tid
                self._trace_finish(tr, 200, reply, t0, probe=is_probe)
                return 200, reply
        if self.fleet_down():
            # Fast-fail (ISSUE 13 satellite): a fleet-wide outage
            # sheds NOW — no per-request retry-budget burn, no backoff
            # loop rediscovering the same dead fleet. Its own counter
            # so an operator can tell "total outage" from "one replica
            # briefly unpickable".
            reg.counter("router/fleet_down_total").inc()
            reply = {
                "error": "no healthy replica (fleet-wide outage)",
                "retry": True, "shed": True, "fleet_down": True,
            }
            self._set_stats["base"].record(503, reply)
            reg.histogram("router/e2e").record(time.monotonic() - t0)
            self._trace_finish(tr, 503, reply, t0, probe=is_probe)
            return 503, reply
        prompt = self._clean_prompt(body)
        if journal is not None and prompt is None:
            # A 'text' body has no token ids until a replica tokenizes
            # it — not replayable, so not journaled (dedupe above still
            # applied if the client keyed it).
            journal = None
        if journal is not None:
            if request_id is None:
                request_id = f"auto-{uuid.uuid4().hex[:12]}"
            if not journal.has_intent(request_id):
                # Accepted = journaled, BEFORE dispatch: if this router
                # dies mid-request, the successor's replay finds the
                # intent and finishes the stream — and the stamped
                # trace_id (ISSUE 18) makes that replay continue THIS
                # trace rather than start one of its own.
                journal.append_intent(
                    request_id, body,
                    trace_id=tr.trace_id if tr is not None else None,
                )
        # killrouter@T counts GENERATE dispatches only (the fault
        # grammar's spec): classify/score traffic must not advance T.
        feng = faults_mod.serve_active() if kind == "generate" else None
        if feng is not None and feng.router_dispatch():
            # killrouter@T just hard-aborted THIS router (ISSUE 16
            # satellite): the client's connection is already reset —
            # leave the intent incomplete for the successor's journal
            # replay instead of racing a dispatch against takeover.
            reply = {
                "error": "router killed (injected fault)", "retry": True,
            }
            self._trace_finish(tr, 503, reply, t0, probe=is_probe)
            return 503, reply
        status, reply = self._handle_dispatch(body, kind, t0, prompt, tr)
        if status == 200 and journal is not None and isinstance(
            reply.get("tokens"), list
        ):
            # Completion records — skipped once fenced: the successor
            # owns the journal now, and it will (re)complete the
            # intent itself. Duplicate done records for the same id
            # would be harmless (identical by seeding) but one writer
            # is one writer.
            if not self.fenced():
                journal.append_progress(
                    request_id, len(reply["tokens"])
                )
                journal.append_done(
                    request_id, reply["tokens"], status,
                    trace_id=tr.trace_id if tr is not None else None,
                )
        if status == 200 and isinstance(reply.get("tokens"), list):
            if resume_from:
                # Replay-and-skip (reusing the PR 9 failover
                # machinery): the re-dispatched stream is
                # token-identical by seeding, so the reconnecting
                # client gets the remainder of the SAME stream.
                reg.counter("router/resumed_streams_total").inc()
                reply["tokens"] = reply["tokens"][resume_from:]
                reply["resumed"] = True
                reply["resume_from"] = resume_from
            if request_id is not None:
                reply.setdefault("request_id", request_id)
        self._trace_finish(tr, status, reply, t0, probe=is_probe)
        return status, reply

    def _trace_finish(self, tr, status: int, reply: dict,
                      t0: float, *, probe: bool = False) -> None:
        """Close the request's root span and hand the trace to the
        tail sampler (ISSUE 18). Every handle() exit path for a traced
        request funnels through here exactly once — including the
        dedupe fast path, where finish() MERGES into the original
        request's stored trace instead of forking a new one."""
        if tr is None:
            return
        e2e = time.monotonic() - t0
        self.recorder.add_span(
            tr.trace_id, tracing_mod.close_span(
                "request", t0, span_id=tr.root_id,
                parent_id=tr.parent_id, tags={"status": int(status)},
            )
        )
        if reply.get("dedup"):
            tr.flags.add("deduped")
        if reply.get("resumed"):
            tr.flags.add("resumed")
        self.recorder.finish(
            tr.trace_id, slo=tr.slo, status=int(status), e2e_s=e2e,
            flags=tr.flags,
        )
        self.recorder.exemplars.record("router/e2e", e2e, tr.trace_id)
        reply.setdefault("trace_id", tr.trace_id)
        if not probe:
            # Feed the SLO engine (ISSUE 19): every organic request's
            # end-to-end latency and error outcome consumes (or
            # doesn't) its class's error budget; the trace_id rides
            # along so a firing alert can name its worst offender.
            # Engine lock is a leaf — no router lock is held here.
            self.alerts.observe(
                tr.slo, e2e_s=e2e, error=status >= 500,
                trace_id=tr.trace_id,
            )

    def _handle_dispatch(self, body: dict, kind: str, t0: float,
                         prompt, tr=None) -> tuple[int, dict]:
        """The dispatch core handle() wraps: disagg handoff first,
        then the canary-aware bounded-retry loop."""
        reg = self.registry
        key_cache: dict = {}  # prompt chain keys, hashed once per request
        if kind == "generate" and prompt is not None \
                and self._disagg_ready():
            out = self._handle_disagg(body, prompt, key_cache, tr)
            if out is not None:
                status, reply = out
                self._set_stats["base"].record(status, reply)
                self.registry.histogram("router/e2e").record(
                    time.monotonic() - t0
                )
                return status, reply
            reg.counter("router/handoff_fallbacks_total").inc()
        # The canary interleave slot is claimed only by requests that
        # actually reach the full path — a completed handoff records
        # under "base" without consuming one, so the canary set still
        # receives its exact fraction of full-path traffic.
        set_name = self._route_set()
        tried: list[ReplicaState] = []
        attempts = 0
        while True:
            within_budget = (
                time.monotonic() - t0 < self.cfg.retry_budget_s
            )
            r = self.pick(
                set_name=set_name, exclude=tuple(tried), prompt=prompt,
                key_cache=key_cache,
            )
            if r is None and tried and set_name is not None:
                # The preferred set has no further replica: the retry
                # may cross sets rather than fail the request (the
                # canary compare just loses one sample).
                r = self.pick(exclude=tuple(tried), prompt=prompt,
                              key_cache=key_cache)
            if r is None:
                if self.fleet_down():
                    # Mid-retry total outage (e.g. the last survivor's
                    # breaker just opened): shed immediately — the
                    # wait-and-rescan below exists for TRANSIENT
                    # ineligibility, not a dead fleet.
                    reg.counter("router/fleet_down_total").inc()
                    status, reply = 503, {
                        "error": "no healthy replica (fleet-wide "
                                 "outage)",
                        "retry": True, "shed": True, "fleet_down": True,
                    }
                    break
                if (
                    tried
                    and attempts <= self.cfg.max_retries
                    and within_budget
                ):
                    # Mid-failover with every replica momentarily
                    # ineligible (e.g. the supervisor is restarting
                    # one and the rest are shedding): wait out a slice
                    # of the budget and rescan the whole pool instead
                    # of failing a request we already accepted.
                    time.sleep(
                        min(0.05, self.cfg.retry_budget_s / 20)
                    )
                    tried = []
                    continue
                reg.counter("router/no_replica_total").inc()
                status, reply = 503, {
                    "error": "no live replica available", "retry": True,
                    "shed": True,
                }
                break
            tried.append(r)
            reg.counter("router/dispatched_total").inc()
            send = body
            span_id = None
            t_att = time.monotonic()
            if tr is not None:
                # Each attempt gets its OWN span and re-issues the
                # wire context with that span as the parent, so the
                # replica's spans nest under the attempt that actually
                # carried them — a failover trace shows both the dead
                # dispatch and the one that answered.
                span_id = tracing_mod.new_span_id()
                send = dict(body)
                send["trace"] = {
                    "trace_id": tr.trace_id,
                    "parent_span_id": span_id,
                    "sampled": True,
                }
            status, reply = self._dispatch(
                r, send, kind, set_name, tried, tr=tr,
                parent_span_id=span_id,
            )
            if tr is not None:
                rspans = reply.pop("trace_spans", None) \
                    if isinstance(reply, dict) else None
                if rspans:
                    self.recorder.ingest(
                        tr.trace_id, rspans, parent_id=span_id
                    )
                outcome = "ok" if status == 200 else (
                    "transport" if status == 0 else str(status)
                )
                self.recorder.add_span(
                    tr.trace_id, tracing_mod.close_span(
                        "dispatch", t_att, parent_id=tr.root_id,
                        span_id=span_id, tags={
                            "replica": r.url,
                            "set": r.set_name or "base",
                            "attempt": attempts + 1,
                            "status": int(status),
                            "outcome": outcome,
                        },
                    )
                )
            if status == 200:
                break
            if status in (0, 503):
                attempts += 1
                within_budget = (
                    time.monotonic() - t0 < self.cfg.retry_budget_s
                )
                if attempts <= self.cfg.max_retries and within_budget:
                    reg.counter("router/retries_total").inc()
                    if tr is not None:
                        tr.flags.add("retried")
                    if status == 0:
                        # The replica died with the request possibly
                        # mid-decode: replay it from the prompt
                        # elsewhere.
                        reg.counter("router/failovers_total").inc()
                        if tr is not None:
                            tr.flags.add("failover")
                    backoff = self.cfg.retry_backoff_s * (
                        2 ** (attempts - 1)
                    )
                    remaining = self.cfg.retry_budget_s - (
                        time.monotonic() - t0
                    )
                    if backoff > 0 and remaining > 0:
                        time.sleep(min(backoff, remaining))
                    continue
                status = 503
                break
            # 400/404/500/504: the replica processed (or rejected) the
            # request — never re-run it elsewhere.
            break
        stats = self._set_stats[
            (tried[-1].set_name if tried else None) or set_name or "base"
        ]
        stats.record(status, reply)
        self.registry.histogram("router/e2e").record(
            time.monotonic() - t0
        )
        return status, reply

    # -------------------------------------------- journal replay (ISSUE 16)

    def replay_incomplete(self) -> int:
        """Drain the journal's accepted-but-unfinished intents through
        the fleet (the restart/takeover verb): each incomplete intent
        re-dispatches as an ordinary generate — token-identical to
        what the dead router would have served, because generation is
        a pure function of (params, prompt, seed) — and its done
        record closes the intent. Returns the number replayed."""
        if self.journal is None:
            return 0
        replayed = 0
        for intent in self.journal.incomplete():
            body = {
                "prompt": intent["prompt"],
                "max_new_tokens": intent["max_new_tokens"],
                "temperature": intent["temperature"],
                "top_k": intent["top_k"],
                "seed": intent["seed"],
                "slo": intent["slo"],
                "request_id": intent["request_id"],
            }
            if intent.get("trace_id"):
                # Continue the dead router's trace (ISSUE 18): the
                # replay's spans MERGE into the original trace_id the
                # intent carries, so a takeover-survived request reads
                # as one tree across both routers.
                body["trace"] = {
                    "trace_id": intent["trace_id"], "sampled": True,
                }
            status, _ = self.handle(body, kind="generate")
            if status == 200:
                replayed += 1
                self.registry.counter(
                    "router/journal_replayed_total"
                ).inc()
            else:
                log.warning(
                    "journal replay of %s failed with status %d",
                    intent["request_id"], status,
                )
        return replayed

    # ------------------------------------------------------------ stats

    def canary_records(self) -> tuple[dict, dict]:
        """(base record, canary record) — two ``serve_router_set``
        docs ``tools/run_diff.py`` compares directly (its load_record
        accepts bench records; the serving GATE_KEYS rank TTFT/TPOT/
        prefix-hit regressions first)."""
        return (
            self._set_stats["base"].record_doc("base"),
            self._set_stats["canary"].record_doc("canary"),
        )

    def stats_line(self) -> dict:
        """A schema-v6 ``kind="serving"`` line for the router process:
        fleet-aggregated serving object plus the v6 router fields."""
        counters = {
            k: v for k, v in self.registry.counter_values().items()
            if k.startswith("router/")
        }
        gauges = {
            k: v for k, v in self.registry.gauge_values().items()
            if k.startswith("router/")
        }
        # Taken OUTSIDE self._lock: the recorder has its own lock and
        # nesting the two would order them router->recorder here while
        # the dispatch path orders recorder-only — keep them disjoint.
        tstats = self.recorder.stats()
        # Same discipline for the SLO engine (ISSUE 19): evaluate on
        # the stats cadence (the prober also evaluates on its own
        # tick), then read the v14 summary — engine lock is a leaf,
        # never nested inside self._lock. The time-series store
        # samples here too: one stats tick = one ring sample.
        self.alerts.evaluate()
        astats = self.alerts.stats()
        self.series.sample()
        with self._lock:
            # One consistent fleet snapshot: the probe loop rewrites
            # these fields mid-sweep, and a line aggregated across a
            # torn sweep would pair one replica's new occupancy with
            # another's stale brownout level (ISSUE 14 lock pass).
            probed = [r for r in self.replicas if r.probed]
            occ = [r.kv_occupancy for r in probed]
            serving = {
                "active_requests": int(
                    sum(r.active_requests for r in probed)
                ),
                "queue_depth": int(sum(r.queue_depth for r in probed)),
                "slots": int(sum(r.slots for r in probed)),
                "kv_occupancy": (sum(occ) / len(occ)) if occ else 0.0,
                "post_warmup_recompiles": int(
                    sum(r.post_warmup_recompiles for r in probed)
                ),
                "draining": 0,
                "replicas": len(self.replicas),
                "router_dispatched": int(
                    counters.get("router/dispatched_total", 0)
                ),
                "router_retries": int(
                    counters.get("router/retries_total", 0)
                ),
                "router_no_replica": int(
                    counters.get("router/no_replica_total", 0)
                ),
                # --- v7 (ISSUE 10): fault-tolerance counters ---
                "router_ejections": int(
                    counters.get("router/ejections_total", 0)
                ),
                "router_readmits": int(
                    counters.get("router/readmits_total", 0)
                ),
                "router_hedges": int(
                    counters.get("router/hedges_total", 0)
                ),
                "router_failovers": int(
                    counters.get("router/failovers_total", 0)
                ),
                "router_restarts": int(
                    counters.get("router/restarts_total", 0)
                ),
                # --- v9 (ISSUE 12): fleet-summed prefix-cache summary ---
                "prefix_blocks": int(
                    sum(r.prefix_blocks for r in probed)
                ),
                "prefix_chains": int(
                    sum(r.prefix_chains for r in probed)
                ),
                # --- v10 (ISSUE 13): fleet overload view — the WORST
                # replica's brownout level (one browning-out replica is an
                # incident, not an average), summed transitions, and
                # whether any affinity digest is capped.
                "brownout_level": int(
                    max((r.brownout_level for r in probed), default=0)
                ),
                "brownout_transitions": int(
                    sum(r.brownout_transitions for r in probed)
                ),
                "digest_truncated": int(
                    any(r.digest_truncated for r in probed)
                ),
                # --- v12 (ISSUE 16): control-plane durability — the
                # journal's append count, warm-standby takeovers and the
                # last takeover's detection-to-serving wall, resumed
                # client streams, and idempotency-key dedupe hits.
                "journal_appends": int(
                    counters.get("router/journal_appends_total", 0)
                ),
                "takeover_total": int(
                    counters.get("router/takeover_total", 0)
                ),
                "resumed_streams": int(
                    counters.get("router/resumed_streams_total", 0)
                ),
                "dedup_hits": int(
                    counters.get("router/dedup_hits_total", 0)
                ),
                "takeover_latency_s": float(
                    gauges.get("router/takeover_latency_s", 0.0)
                ),
                # --- v13 (ISSUE 18): tail-sampled tracing — kept vs
                # dropped trace counts, the resulting coverage
                # fraction, and how many kept traces were kept for
                # being SLOW (the p99-attribution feedstock).
                "traces_kept": tstats["traces_kept"],
                "traces_dropped": tstats["traces_dropped"],
                "trace_coverage": tstats["trace_coverage"],
                "slow_trace_count": tstats["slow_trace_count"],
                # --- v14 (ISSUE 19): the SLO engine's alerting
                # summary — rules currently firing, the worst rule's
                # error budget remaining, the canary prober's rolling
                # success rate, and cumulative firing transitions.
                "alerts_firing": astats["alerts_firing"],
                "error_budget_remaining": astats[
                    "error_budget_remaining"
                ],
                "probe_success_rate": astats["probe_success_rate"],
                "alert_count": astats["alert_count"],
            }
        return {
            "schema_version": schema.SERVING_SCHEMA_VERSION,
            "kind": "serving",
            "step": serving["router_dispatched"],
            "time_unix": time.time(),
            "session_start_unix": self._start_unix,
            "host": 0,
            "metrics": {},
            "counters": counters,
            "gauges": gauges,
            "derived": {},
            "serving": serving,
        }

    def replica_snapshots(self) -> list[dict]:
        """Per-replica state docs for ``/replicas`` — each snapshot
        taken under the lock so the probe loop cannot tear it
        mid-render (ISSUE 14 lock pass)."""
        with self._lock:
            return [r.snapshot_locked() for r in self.replicas]

    def health_payload(self) -> tuple[int, dict]:
        with self._lock:
            eligible = [
                r for r in self.replicas
                if r.eligible(self.cfg.unhealthy_after)
            ]
            body = {
                "ok": bool(eligible),
                "role": "router",
                "replicas": len(self.replicas),
                "eligible": len(eligible),
                "sets": sorted({r.set_name for r in self.replicas}),
                # Fleet overload view (ISSUE 13): worst replica's
                # brownout level + fleet-summed transition count, and
                # the fast-fail outage counter — the operator's "is the
                # fleet browning out or down" one-liner.
                "brownout_max": int(max(
                    (r.brownout_level for r in self.replicas), default=0
                )),
                "brownout_transitions": int(sum(
                    r.brownout_transitions for r in self.replicas
                )),
                "digest_truncated": bool(any(
                    r.digest_truncated for r in self.replicas
                )),
            }
        body["fleet_down_total"] = int(
            self.registry.counter_values().get(
                "router/fleet_down_total", 0
            )
        )
        return (200 if body["ok"] else 503), body


class _RouterHTTPServer(http.server.ThreadingHTTPServer):
    # The fleet's front door: a flash crowd's connection burst must
    # reach the dispatcher (which sheds by POLICY), not bounce off the
    # stdlib's 5-entry accept backlog as transport failures (ISSUE 13).
    request_queue_size = 128

    # In-flight client connections, tracked so RouterFrontend.abort()
    # can RESET them (the killrouter fault's PR-9 semantics: the
    # router dies like a SIGKILLed process, clients observe transport
    # failures — never a polite 503). Normal shutdown never touches
    # this.
    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.conn_lock = threading.Lock()
        self.live_connections: set = set()

    def process_request(self, request, client_address):
        with self.conn_lock:
            self.live_connections.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self.conn_lock:
            self.live_connections.discard(request)
        super().shutdown_request(request)


class RouterFrontend:
    """The router's HTTP surface: proxied POST /generate //classify,
    GET /metrics //health //replicas //window //trace/{id} (+ /canary
    with a canary set), admin POST /drain //undrain
    {"replica": url}."""

    def __init__(self, router: Router, *, port: int = 0,
                 bind_host: str = ""):
        self.router = router
        self.requested_port = int(port)
        self.bind_host = bind_host
        self.port: int | None = None
        self._httpd: http.server.ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    def start(self) -> "RouterFrontend":
        router = self.router

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, status, content_type, payload: bytes):
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _send_json(self, status, obj):
                self._send(
                    status,
                    "application/json",
                    (json.dumps(json_safe(obj)) + "\n").encode(),
                )

            def _body(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    return None
                if n < 0 or n > _MAX_BODY:
                    return None
                try:
                    return json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError:
                    return None

            def do_POST(self):  # noqa: N802 - http.server contract
                path = self.path.split("?", 1)[0].rstrip("/")
                try:
                    body = self._body()
                    if body is None or not isinstance(body, dict):
                        self._send_json(
                            400, {"error": "malformed JSON body"}
                        )
                        return
                    if path in ("/generate", "/classify"):
                        status, reply = router.handle(
                            body, kind=path[1:]
                        )
                        self._send_json(status, reply)
                    elif path in ("/drain", "/undrain"):
                        url = body.get("replica", "")
                        op = (
                            router.drain if path == "/drain"
                            else router.undrain
                        )
                        if not isinstance(url, str) or not op(url):
                            self._send_json(
                                404,
                                {"error": f"unknown replica {url!r}"},
                            )
                        else:
                            self._send_json(
                                200, {"ok": True, "replica": url}
                            )
                    else:
                        self._send_json(
                            404,
                            {"error": "POST: /generate /classify "
                                      "/drain /undrain"},
                        )
                except ConnectionError:
                    pass

            def do_GET(self):  # noqa: N802 - http.server contract
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        self._send(
                            200,
                            "text/plain; version=0.0.4; charset=utf-8",
                            render_prometheus(
                                router.registry,
                                exemplars=router.recorder.exemplars,
                            ).encode(),
                        )
                    elif path.startswith("/trace/"):
                        # Live trace lookup (ISSUE 18): the recorder
                        # keeps EVERY finished trace in its bounded
                        # ring (sampling only gates sink writes), so
                        # the operator can pull any recent request's
                        # span tree by the trace_id its reply carried.
                        tid = path[len("/trace/"):]
                        doc = router.recorder.get(tid)
                        if doc is None:
                            self._send_json(
                                404,
                                {"error": f"unknown trace {tid!r}"},
                            )
                        else:
                            self._send_json(200, doc)
                    elif path == "/health":
                        self._send_json(*router.health_payload())
                    elif path == "/replicas":
                        self._send_json(
                            200,
                            {"replicas": router.replica_snapshots()},
                        )
                    elif path == "/window":
                        self._send_json(200, router.stats_line())
                    elif path == "/canary":
                        base, canary = router.canary_records()
                        self._send_json(
                            200, {"base": base, "canary": canary}
                        )
                    elif path == "/alerts":
                        # Live alert state (ISSUE 19): every rule's
                        # burn rates and state machine position, plus
                        # the firing subset with exemplar trace ids —
                        # what tools/slo_watch.py polls.
                        self._send_json(200, router.alerts.payload())
                    elif path == "/series":
                        # The in-process time-series store (ISSUE 19):
                        # ring-buffered history of every router
                        # instrument, sampled on the stats cadence.
                        self._send_json(
                            200, router.series.to_payload()
                        )
                    else:
                        self._send(
                            404,
                            "text/plain; charset=utf-8",
                            b"GET: /metrics /health /replicas /window "
                            b"/canary /alerts /series /trace/{id}   "
                            b"POST: /generate /classify /drain "
                            b"/undrain\n",
                        )
                except ConnectionError:
                    pass

            def log_message(self, fmt, *args):  # quiet under load
                log.debug("router frontend: " + fmt, *args)

        self._httpd = _RouterHTTPServer(
            (self.bind_host, self.requested_port), Handler
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="router-frontend",
            daemon=True,
        )
        self._thread.start()
        log.info(
            "router live on port %d over %d replica(s)",
            self.port, len(self.router.replicas),
        )
        return self

    def url(self, path: str = "/generate") -> str:
        host = self.bind_host or "127.0.0.1"
        return f"http://{host}:{self.port}{path}"

    def close(self) -> None:
        with self._lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5)

    def abort(self) -> None:
        """Die like a killed router process (the ``killrouter@T``
        fault's verb, ISSUE 16 — same semantics as
        ``ServingFrontend.abort``): stop listening AND reset every
        in-flight client connection, so clients observe transport
        failures, never a drained 503. Handler threads hit the dead
        sockets on their own (ConnectionError, already swallowed);
        nothing is joined — safe from any thread, including a handler
        mid-dispatch."""
        with self._lock:
            httpd, self._httpd = self._httpd, None
            self._thread = None
        if httpd is None:
            return
        try:
            httpd.shutdown()
            httpd.server_close()
        except OSError:  # pragma: no cover - best-effort teardown
            pass
        with httpd.conn_lock:
            conns = list(httpd.live_connections)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # already gone
