"""Block-paged KV cache pool with prefix reuse + int8 KV (ISSUE 8).

The dense ``KVCachePool`` commits ``max_len`` rows per slot the moment
the slot is claimed: a 12-token request holds as much cache as a
1024-token one, and concurrency is capped by the worst case, not the
workload. This module replaces the storage layer behind the same
interface the engine/batcher already speak:

* **Paged blocks** — the device arrays are ``[L, NB, H, BS, D]`` pools
  of ``NB`` physical blocks of ``BS`` (power-of-two) token rows each.
  A slot holds a *block table* (logical block index -> physical block
  id); capacity scales with the tokens a request has actually used,
  so a mixed short/long request set commits a fraction of the dense
  pool's bytes (tier-1 asserts <= 1/2 via ``used_bytes()``).
  Physical block 0 is reserved as the **null block**: pad entries of
  every table point at it, parked decode slots write their discarded
  rows into it, and length masking guarantees its garbage is never
  read into a real request's attention.
* **Free-list allocator** — blocks are claimed from a free list and
  refcounted (prefix sharing means a block can back several slots).
  Exhaustion is LOUD: :class:`BlockExhausted` (after evicting
  reusable-but-unreferenced prefix blocks, LRU first) — admission
  rejects the request (HTTP 503) instead of anything silently
  stalling, and a mid-decode exhaustion fails only the requests that
  needed new blocks while the engine keeps serving the rest
  (tests pin both, mirroring the PR 5 ``EngineStepError`` contract).
* **Prefix cache** — immutable FULL blocks of a request's prompt are
  published for reuse, keyed by an exact chained key
  ``(parent physical block id, the BS token ids in this block)`` — a
  walk from the root reproduces the whole token prefix, so a hit can
  never serve another prompt's cache (no hash collisions by
  construction). A later request whose prompt starts with the same
  full blocks maps them into its table (refcount++) and prefills only
  the tail (``engine._extend_impl``): shared system prompts prefill
  once. The partial tail is copy-on-write by construction — cached
  blocks cover only ``[0, c)`` with ``c`` block-aligned and strictly
  below the prompt length, and every write a request ever makes lands
  at positions ``>= prompt_len > c``, i.e. in its own private blocks;
  a shared block is never written again while published.
* **int8 KV** (``kv_dtype="int8"``) — blocks store int8 with per-row
  f32 scales kept blockwise (``[L, NB, H, BS]``,
  ``core/precision.quantize_int8_rows``): rows append one decode step
  at a time without requantizing the block. fp32/bf16 paged serving
  stays token-identical to the dense reference; int8 is a measured
  bounded-divergence mode (tests pin both).

Occupancy telemetry splits what the dense pool conflated (ISSUE 8
satellite): ``serving/kv_occupancy`` is the **used-block fraction**
(the capacity signal the router tier load-balances on), while
``serving/kv_slot_occupancy`` tracks claimed slots — a pool with every
slot busy on short prompts no longer reads as full.
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict

import jax.numpy as jnp
import numpy as np

from tensorflow_examples_tpu.serving import scheduler
from tensorflow_examples_tpu.telemetry import registry as registry_mod

log = logging.getLogger(__name__)

NULL_BLOCK = 0  # physical block 0: pad/garbage target, never allocated


class BlockExhausted(RuntimeError):
    """The block free list is empty (even after evicting unreferenced
    prefix-cache blocks). At admission this rejects the request (503);
    mid-decode it names the slots that could not grow (``slots``) so
    the batcher fails exactly those and keeps serving the rest."""

    def __init__(self, msg: str, *, slots: tuple[int, ...] = ()):
        super().__init__(msg)
        self.slots = tuple(slots)


class PagedKVPool:
    """Paged drop-in for ``kv_cache.KVCachePool``: same slot interface
    (``alloc``/``free``/``reset``/``reallocate``/``lengths``/
    ``max_active_length``/``occupancy``), block-granular storage.

    Host bookkeeping (all under one lock; the batcher loop is the only
    writer, frontend threads read occupancy):

    * ``block_tables`` — int32 ``[num_slots, max_len // BS]``, physical
      block ids, ``NULL_BLOCK`` where unallocated.
    * ``_refcount``   — per physical block; prefix sharing makes this
      > 1. A block at refcount 0 returns to the free list unless it is
      published in the prefix cache, in which case it parks in the
      LRU evictable set (still hittable, reclaimed on pressure).
    * prefix cache    — chained exact-token map, see module docstring.
    """

    def __init__(
        self,
        *,
        num_layers: int,
        num_slots: int,
        num_heads: int,
        max_len: int,
        head_dim: int,
        block_size: int = 16,
        num_blocks: int = 0,
        dtype=jnp.float32,
        kv_dtype: str = "",
        prefix_cache: bool = True,
        registry=None,
        sharding=None,
    ):
        if num_slots < 1:
            raise ValueError(f"num_slots={num_slots} must be >= 1")
        if block_size < 1 or block_size & (block_size - 1):
            raise ValueError(
                f"block_size={block_size} must be a power of two"
            )
        if max_len % block_size:
            raise ValueError(
                f"block_size={block_size} must divide max_len={max_len}"
            )
        self.num_layers = num_layers
        self.num_slots = num_slots
        self.num_heads = num_heads
        self.max_len = max_len
        self.head_dim = head_dim
        self.block_size = block_size
        self.max_blocks_per_slot = max_len // block_size
        # Default capacity matches the dense pool's worst case (every
        # slot at max_len) so nothing that served before can fail now;
        # operators shrink it (ServeConfig.kv_blocks) to bank the
        # memory the paging exists to save. +1 for the null block.
        self.num_blocks = (
            int(num_blocks) if num_blocks
            else num_slots * self.max_blocks_per_slot + 1
        )
        if self.num_blocks < 2:
            raise ValueError("num_blocks must leave at least one "
                             "allocatable block beyond the null block")
        self.dtype = dtype
        self.kv_dtype = kv_dtype or ""
        if self.kv_dtype not in ("", "int8", "fp8"):
            raise ValueError(
                f"kv_dtype={kv_dtype!r} not in ('', 'int8', 'fp8')"
            )
        # fp8 KV (ISSUE 15): same blockwise per-row scales, the payload
        # stored as float8_e4m3fn — the precision registry's row
        # quantization is dtype-generic, so the whole int8 path (write,
        # gather-dequant, wire pages) serves fp8 unchanged. Gated
        # loudly on builds without a working fp8.
        if self.kv_dtype == "fp8":
            from tensorflow_examples_tpu.core import precision

            if not precision.fp8_supported():
                raise ValueError(
                    "kv_dtype='fp8' requested but this jax "
                    "build/backend has no working float8_e4m3fn — "
                    "use kv_dtype='int8'"
                )
        self.quantized = self.kv_dtype in ("int8", "fp8")
        self.prefix_cache_enabled = bool(prefix_cache)
        self._registry = registry
        self._sharding = sharding
        self._alloc_arrays()
        # Slot/block bookkeeping below is written by the batcher loop
        # and read by frontend threads (occupancy, paged_stats, the
        # /health digest) — all under self._lock; graftlint's lock pass
        # checks the annotations (ISSUE 14). ``lengths``/
        # ``block_tables`` are also READ by the engine from the loop
        # thread (same thread as every writer), which per-file analysis
        # does not see — documented in docs/static_analysis.md.
        self.lengths = np.zeros((num_slots,), np.int32)  # guard: self._lock
        self.block_tables = np.full(  # guard: self._lock
            (num_slots, self.max_blocks_per_slot), NULL_BLOCK, np.int32
        )
        self._slot_blocks = np.zeros((num_slots,), np.int32)  # guard: self._lock
        self._free_slots = list(range(num_slots - 1, -1, -1))  # guard: self._lock
        self._free_blocks = list(range(self.num_blocks - 1, 0, -1))  # guard: self._lock
        self._refcount = np.zeros((self.num_blocks,), np.int32)  # guard: self._lock
        # Prefix cache: (parent physical id | -1, tokens tuple) -> id;
        # reverse map for eviction; LRU order over refcount-0 cached
        # blocks ("evictable": published but unreferenced).
        self._cache: dict[tuple, int] = {}  # guard: self._lock
        self._cache_key: dict[int, tuple] = {}  # guard: self._lock
        # Content chain digests (ISSUE 12): per published block, the
        # replica- and restart-stable scheduler.chain_key of its whole
        # token prefix (+ its chain depth). The /health prefix digest
        # and the router's affinity score are built from these — never
        # from physical ids, which are meaningless across replicas.
        self._chain_hash: dict[int, str] = {}  # guard: self._lock
        self._chain_depth: dict[int, int] = {}  # guard: self._lock
        # Bloom-digest cache (ISSUE 15): generation counter bumped on
        # every published-chain change; the encoded filter is built
        # OUTSIDE the lock from a snapshot and reused until the
        # generation moves, so a /health probe never holds the
        # allocation lock for a full blake2b sweep of a huge cache.
        self._digest_gen = 0  # guard: self._lock
        self._bloom_cache: tuple | None = None  # guard: self._lock
        self._evictable: OrderedDict[int, None] = OrderedDict()  # guard: self._lock
        self.prefix_hits = 0  # guard: self._lock
        self.prefix_misses = 0  # guard: self._lock
        self._lock = threading.Lock()
        self._publish_locked()  # pre-sharing: no reader exists yet

    # ------------------------------------------------------ device state

    def _alloc_arrays(self) -> None:
        shape = (self.num_layers, self.num_blocks, self.num_heads,
                 self.block_size, self.head_dim)
        if self.kv_dtype == "fp8":
            from tensorflow_examples_tpu.core import precision

            store = precision.fp8_dtype()
        elif self.quantized:
            store = jnp.int8
        else:
            store = self.dtype
        kw = {} if self._sharding is None else {"device": self._sharding}
        self.k = jnp.zeros(shape, store, **kw)
        self.v = jnp.zeros(shape, store, **kw)
        if self.quantized:
            self.k_scale = jnp.ones(shape[:-1], jnp.float32, **kw)
            self.v_scale = jnp.ones(shape[:-1], jnp.float32, **kw)
        else:
            self.k_scale = self.v_scale = None

    def kv_state(self) -> tuple:
        """The device-array tuple the engine's compiled steps donate
        and return (``set_kv_state`` reassigns from the outputs)."""
        if self.quantized:
            return (self.k, self.v, self.k_scale, self.v_scale)
        return (self.k, self.v)

    def set_kv_state(self, state: tuple) -> None:
        if self.quantized:
            self.k, self.v, self.k_scale, self.v_scale = state
        else:
            self.k, self.v = state

    def reallocate(self) -> None:
        """Fresh zeroed device arrays after a failed donated step (the
        ``EngineStepError`` path — the old buffers were consumed).
        Every cached prefix lived in those buffers, so the prefix
        cache is invalidated wholesale; slot bookkeeping is untouched
        because the batcher fails and frees the whole in-flight set
        right after."""
        self._alloc_arrays()
        with self._lock:
            self._drop_cache_locked()
            self._publish_locked()

    def _drop_cache_locked(self) -> None:
        for bid in list(self._evictable):
            self._free_blocks.append(bid)
        self._evictable.clear()
        self._cache.clear()
        self._cache_key.clear()
        self._chain_hash.clear()
        self._chain_depth.clear()
        self._digest_gen += 1
        self._bloom_cache = None

    # ------------------------------------------------------------- slots

    def _reg(self):
        return (
            self._registry
            if self._registry is not None
            else registry_mod.default_registry()
        )

    def _publish_locked(self) -> None:
        reg = self._reg()
        active = self.num_slots - len(self._free_slots)
        usable = self.num_blocks - 1
        used = int((self._refcount > 0).sum())
        reg.gauge("serving/kv_occupancy").set(used / usable)
        reg.gauge("serving/kv_slot_occupancy").set(active / self.num_slots)
        reg.gauge("serving/kv_slots_active").set(active)
        reg.gauge("serving/kv_blocks_used").set(used)
        reg.gauge("serving/kv_blocks_total").set(usable)
        reg.gauge("serving/kv_tokens").set(int(self.lengths.sum()))
        reg.gauge("serving/prefix_cache_blocks").set(len(self._cache))

    def alloc(self) -> int | None:
        """Claim a free slot (None when every slot is taken). No blocks
        are committed yet — the engine's prefill allocates exactly what
        the prompt needs."""
        with self._lock:
            if not self._free_slots:
                return None
            slot = self._free_slots.pop()
            self.lengths[slot] = 0
            self.block_tables[slot, :] = NULL_BLOCK
            self._slot_blocks[slot] = 0
            self._publish_locked()
            return slot

    def free(self, slot: int) -> None:
        with self._lock:
            if slot in self._free_slots:  # double-free is a caller bug
                raise ValueError(f"slot {slot} is already free")
            for i in range(int(self._slot_blocks[slot])):
                self._release_block_locked(int(self.block_tables[slot, i]))
            self.block_tables[slot, :] = NULL_BLOCK
            self._slot_blocks[slot] = 0
            self.lengths[slot] = 0
            self._free_slots.append(slot)
            self._publish_locked()

    def reset(self) -> None:
        """Release every slot and every block (post-warmup; the device
        arrays keep their garbage — unpopulated rows are never read)."""
        with self._lock:
            self.lengths[:] = 0
            self.block_tables[:, :] = NULL_BLOCK
            self._slot_blocks[:] = 0
            self._free_slots = list(range(self.num_slots - 1, -1, -1))
            # Cache drop FIRST (it returns parked evictable blocks to
            # the free list), then the wholesale rebuild — the other
            # order would append those ids on top of a full list and
            # hand the same physical block out twice.
            self._drop_cache_locked()
            self._free_blocks = list(range(self.num_blocks - 1, 0, -1))
            self._refcount[:] = 0
            self.prefix_hits = 0
            self.prefix_misses = 0
            self._publish_locked()

    @property
    def active_slots(self) -> int:
        with self._lock:
            return self.num_slots - len(self._free_slots)

    @property
    def occupancy(self) -> float:
        """Used-block fraction — what ``/health`` reports and the
        router load-balances on. A full-slots pool of short prompts is
        NOT full (that is the satellite fix: slot occupancy is
        published separately as ``serving/kv_slot_occupancy``)."""
        with self._lock:
            return float((self._refcount > 0).sum()) / (self.num_blocks - 1)

    def max_active_length(self) -> int:
        with self._lock:
            return int(self.lengths.max(initial=0))

    # ------------------------------------------------------------ blocks

    def _alloc_block_locked(self) -> int:
        if self._free_blocks:
            return self._free_blocks.pop()
        if self._evictable:
            # Reclaim the least-recently-published unreferenced prefix
            # block: cache reuse is an optimization, never a reason to
            # refuse admission.
            bid, _ = self._evictable.popitem(last=False)
            key = self._cache_key.pop(bid)
            del self._cache[key]
            self._chain_hash.pop(bid, None)
            self._chain_depth.pop(bid, None)
            self._digest_gen += 1
            return bid
        self._reg().counter("serving/kv_exhausted_total").inc()
        log.warning(
            "KV block pool exhausted (%d/%d blocks referenced by "
            "active requests) — shedding",
            int((self._refcount > 0).sum()), self.num_blocks - 1,
        )
        raise BlockExhausted(
            f"KV block pool exhausted: {self.num_blocks - 1} blocks "
            f"({self.block_size} tokens each) all referenced by active "
            "requests — admission must shed load"
        )

    def _release_block_locked(self, bid: int) -> None:
        if bid == NULL_BLOCK:
            return
        self._refcount[bid] -= 1
        if self._refcount[bid] > 0:
            return
        if bid in self._cache_key:
            self._evictable[bid] = None  # published: park, reclaimable
        else:
            self._free_blocks.append(bid)

    def alloc_blocks(self, n: int) -> list[int]:
        """Claim ``n`` fresh private blocks (refcount 1 each) or raise
        :class:`BlockExhausted` having claimed none (all-or-nothing, so
        a rejected admission leaks nothing)."""
        with self._lock:
            got: list[int] = []
            try:
                for _ in range(n):
                    got.append(self._alloc_block_locked())
            except BlockExhausted:
                for bid in got:
                    self._free_blocks.append(bid)
                raise
            for bid in got:
                self._refcount[bid] = 1
            self._publish_locked()
            return got

    def assign(self, slot: int, blocks: list[int]) -> None:
        """Install a slot's block table (reused prefix blocks first,
        then its private blocks — refcounts were already taken by
        ``prefix_lookup``/``alloc_blocks``)."""
        with self._lock:
            if len(blocks) > self.max_blocks_per_slot:
                raise ValueError(
                    f"{len(blocks)} blocks exceed the per-slot table "
                    f"({self.max_blocks_per_slot})"
                )
            self.block_tables[slot, :] = NULL_BLOCK
            self.block_tables[slot, :len(blocks)] = blocks
            self._slot_blocks[slot] = len(blocks)
            self._publish_locked()

    def ensure_position(self, slot: int, position: int) -> None:
        """Grow the slot's table to cover ``position`` (one block per
        step in plain decode; a speculative verify step may need
        several — the spec window can cross block boundaries). Growth
        is all-or-nothing: on :class:`BlockExhausted` nothing was
        claimed and the caller fails THAT request."""
        need = position // self.block_size + 1
        with self._lock:
            have = int(self._slot_blocks[slot])
            if need <= have:
                return
            if need > self.max_blocks_per_slot:
                raise ValueError(
                    f"position {position} exceeds max_len {self.max_len}"
                )
            got: list[int] = []
            try:
                for _ in range(need - have):
                    got.append(self._alloc_block_locked())
            except BlockExhausted:
                for bid in got:
                    self._free_blocks.append(bid)
                raise
            for i, bid in enumerate(got):
                self._refcount[bid] = 1
                self.block_tables[slot, have + i] = bid
            self._slot_blocks[slot] = need
            self._publish_locked()

    def covered_positions(self, slot: int) -> int:
        """Token rows the slot's allocated blocks can hold — the cap on
        how many verify rows may COMMIT when a speculative window could
        not be fully backed (rows past it land in the null block and
        their tokens must not ship)."""
        with self._lock:
            return int(self._slot_blocks[slot]) * self.block_size

    # ------------------------------------------------------ prefix cache

    def prefix_lookup(self, prompt) -> tuple[list[int], int]:
        """Longest reusable cached prefix of ``prompt``: (physical
        block ids with refcounts ALREADY taken, covered token count
        ``c``). ``c`` is block-aligned and capped strictly below
        ``len(prompt)`` — at least one tail token always prefills, so
        the extend step has a real query row to sample the first token
        from."""
        if not self.prefix_cache_enabled:
            return [], 0
        bs = self.block_size
        max_full = (len(prompt) - 1) // bs  # cap: tail keeps >= 1 token
        with self._lock:
            blocks: list[int] = []
            parent = -1
            for i in range(max_full):
                block = tuple(int(t) for t in prompt[i * bs:(i + 1) * bs])
                bid = self._cache.get((parent, block))
                if bid is None:
                    break
                blocks.append(bid)
                parent = bid
            if blocks:
                for bid in blocks:
                    if self._refcount[bid] == 0:
                        self._evictable.pop(bid, None)
                    self._refcount[bid] += 1
                self.prefix_hits += 1
                self._reg().counter("serving/prefix_hits").inc()
            else:
                self.prefix_misses += 1
                self._reg().counter("serving/prefix_misses").inc()
            self._publish_locked()
            return blocks, len(blocks) * bs

    def release_prefix(self, blocks: list[int]) -> None:
        """Undo a ``prefix_lookup``'s refcounts (the admission that
        followed it failed before ``assign``)."""
        with self._lock:
            for bid in blocks:
                self._release_block_locked(bid)
            self._publish_locked()

    def claim_prompt_blocks(self, slot: int, prompt) -> tuple[int, list]:
        """Claim and install ``slot``'s whole prompt table — longest
        reusable cached prefix first (refcounts taken), fresh private
        blocks for the rest — all-or-nothing: on :class:`BlockExhausted`
        the reused refcounts are released and nothing is claimed.
        Returns ``(ctx, fresh)``: the cached token count and the fresh
        block ids (the table rows from ``ctx // block_size`` on). The
        ONE home of the claim discipline — the prefill, chunked-prefill,
        and page-import paths all route through it."""
        total = -(-len(prompt) // self.block_size)
        reused, ctx = self.prefix_lookup(prompt)
        try:
            fresh = self.alloc_blocks(total - len(reused))
        except BlockExhausted:
            self.release_prefix(reused)
            raise
        self.assign(slot, reused + fresh)
        return ctx, fresh

    def insert_prefix(self, slot: int, prompt) -> None:
        """Publish the slot's FULL prompt blocks for reuse. Idempotent
        per chain link; a block already published under a different
        physical id (a racing identical prompt) is left alone — first
        writer wins, both copies serve."""
        if not self.prefix_cache_enabled:
            return
        bs = self.block_size
        with self._lock:
            parent = -1
            parent_hash = ""
            for i in range(len(prompt) // bs):
                block = tuple(int(t) for t in prompt[i * bs:(i + 1) * bs])
                key = (parent, block)
                # The content chain digest walks alongside the physical
                # chain: same tokens -> same hash on every replica and
                # across resets (the /health digest contract).
                parent_hash = scheduler.chain_key(parent_hash, block)
                existing = self._cache.get(key)
                if existing is not None:
                    parent = existing
                    continue
                bid = int(self.block_tables[slot, i])
                if bid == NULL_BLOCK:
                    break
                self._cache[key] = bid
                self._cache_key[bid] = key
                self._chain_hash[bid] = parent_hash
                self._chain_depth[bid] = i + 1
                self._digest_gen += 1
                parent = bid
            self._publish_locked()

    def _chains_locked(self) -> int:
        """Distinct chain HEADS — root blocks (parent -1) of the
        published chains, i.e. how many distinct prompts' first blocks
        this cache holds (caller holds the lock)."""
        return sum(1 for key in self._cache if key[0] == -1)

    def prefix_digest(self, max_keys: int = scheduler.DIGEST_MAX_KEYS
                      ) -> dict:
        """The replica's published prefix summary (ISSUE 12): the
        content chain keys of every cached block (shallowest first,
        capped at ``max_keys`` — shared system prompts are the
        shallowest links, so the cap sheds the least-routable tails
        first), plus ``blocks`` (published block count) and ``chains``
        (distinct chain heads). Keys are pure functions of token
        content, so the digest is stable across ``reset()`` and replica
        restarts — the property the router's affinity match relies on
        (test-pinned). ``truncated`` says the cap actually bit (ISSUE
        13 satellite): on a very large cache the shed tail keys can
        never win an affinity match, so the flag makes those misses
        diagnosable on ``/health`` instead of invisible."""
        with self._lock:
            items = sorted(
                self._chain_hash.items(),
                key=lambda kv: (self._chain_depth[kv[0]], kv[1]),
            )
            truncated = len(items) > max_keys
            out = {
                "keys": [h for _, h in items[:max_keys]],
                "blocks": len(self._cache),
                "chains": self._chains_locked(),
                "truncated": truncated,
            }
            gen = self._digest_gen
            cached = self._bloom_cache
        if truncated:
            # ISSUE 15 satellite: past the cap, ALSO publish a bloom
            # filter over the ENTIRE chain-key set, so affinity
            # routing keeps working on very large caches (false
            # positives only overstate a load-guarded preference).
            # Built OUTSIDE the lock from the snapshot and cached per
            # generation — a probe of an unchanged huge cache reuses
            # the encoded filter instead of re-hashing every key, and
            # never stalls allocation while hashing.
            if cached is not None and cached[0] == gen:
                out["bloom"] = cached[1]
            else:
                bloom = scheduler.encode_bloom(h for _, h in items)
                with self._lock:
                    # Store only while still current: a slow build
                    # racing a fresher probe must not clobber the
                    # newer cached filter with an older-generation one
                    # (which would force a full re-hash per probe).
                    if self._digest_gen == gen:
                        self._bloom_cache = (gen, bloom)
                out["bloom"] = bloom
        return out

    # -------------------------------------------------- byte accounting

    def bytes_per_block(self) -> int:
        """K+V device bytes one physical block commits (int8 payload +
        its blockwise f32 row scales when quantized)."""
        row = self.num_heads * self.head_dim
        if self.quantized:
            per = self.block_size * row * 1 + self.block_size * self.num_heads * 4
        else:
            per = self.block_size * row * jnp.dtype(self.dtype).itemsize
        return int(2 * self.num_layers * per)

    def used_bytes(self) -> int:
        """Cache bytes committed to the active request set — blocks
        actually referenced, not slots claimed. The number the tier-1
        memory-claim test compares against the dense pool's."""
        with self._lock:
            return int((self._refcount > 0).sum()) * self.bytes_per_block()

    # ------------------------------------------------------------- stats

    @property
    def kv_bits(self) -> int:
        return 8 if self.quantized else jnp.dtype(self.dtype).itemsize * 8

    def paged_stats(self) -> dict:
        """Numeric paged-pool fields for the schema-v6 serving stats
        line (serving/batcher.stats_line) and the bench record."""
        with self._lock:
            used = int((self._refcount > 0).sum())
            usable = self.num_blocks - 1
            hits, misses = self.prefix_hits, self.prefix_misses
            chains = self._chains_locked()
            published = len(self._cache)
        looked = hits + misses
        return {
            "block_size": self.block_size,
            "blocks_total": usable,
            "blocks_used": used,
            "kv_block_occupancy": used / usable,
            "kv_slot_occupancy": (
                self.active_slots / self.num_slots
            ),
            "prefix_hits": hits,
            "prefix_misses": misses,
            "prefix_hit_rate": (hits / looked) if looked else 0.0,
            "kv_bits": self.kv_bits,
            # Schema v9 (ISSUE 12): the affinity digest's size — what
            # the router's /replicas summary aggregates fleet-wide.
            "prefix_blocks": published,
            "prefix_chains": chains,
            # Schema v10 (ISSUE 13 satellite): 1 when the published
            # /health digest is capped below the cached chain set —
            # affinity misses on the shed tails are expected, not a
            # routing bug.
            "digest_truncated": int(
                published > scheduler.DIGEST_MAX_KEYS
            ),
        }
