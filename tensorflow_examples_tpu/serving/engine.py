"""The compiled serving step: bucketed prefill + fixed-shape decode.

Shape discipline is the whole design (SURVEY.md's "as fast as the
hardware allows" applied to inference): XLA recompiles on any new
abstract shape, and a serving process that compiles mid-traffic turns
a p50 of milliseconds into a p95 of seconds. So every program the
engine runs comes from a FINITE, warmed-up ladder:

* **Prefill** pads each prompt to the smallest power-of-two length
  bucket (``ServeConfig.prefill_bucket_floor`` up to the model's
  ``max_len``) and runs batch-1: one compiled program per rung.
  Causal masking makes the pad rows inert — the true prompt length
  rides in as a traced scalar that only picks the logits row and the
  cache write extent.
* **Decode** always runs the full ``[max_slots]`` batch — continuous
  batching means the batch composition changes every step, so the
  batch *shape* must not. Per-slot state (token, position, sampling
  key/temperature/top-k) rides in as traced vectors; the KV cache is
  sliced to the smallest power-of-two bucket covering the longest
  active request (``kv_bucket_floor`` ladder), so short-context steps
  read O(bucket) cache bytes — the serving-side mirror of
  ``ops/decode.flash_decode_attention``'s populated-prefix ladder,
  which the prefill path reuses directly under ``attention="flash"``
  (its scalar-length contract matches prefill exactly; the per-slot
  length *vector* of continuous decode is what
  ``kv_cache.varlen_decode_attention`` generalizes).

``warmup()`` compiles the entire ladder ahead of traffic (the
AOT-compiled serving path: every program exists before the first
request) and every compiled variant is wrapped in the PR-3
``CompilationSentinel`` — a post-warmup recompile is a WARNING naming
the exact shape delta, and ``post_warmup_recompiles()`` is the number
CI asserts to be zero (tools/serve_bench.py banks it in the bench
record).

The forward math operates directly on the ``models/transformer.py``
param tree (same names: wte/wpe/h_i/ln_f) rather than through flax
``Transformer.apply``: the flax decode path keys the whole batch off
one scalar cache index, which continuous batching cannot use. Parity
with the flax model is pinned by tests/test_serving.py (engine vs
``transformer.generate`` greedy decode, token-identical).

Sampling reuses ``models.transformer.sample_tokens``'s exact math with
per-request keys (``fold_in(PRNGKey(seed), absolute_position)``), so a
request's tokens are a pure function of (params, prompt, seed) — the
batch it happened to be coalesced into cannot change its output, which
is what makes the continuous-batching golden test meaningful.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_examples_tpu.core import precision as precision_mod
from tensorflow_examples_tpu.core.precision import materialize as _w
from tensorflow_examples_tpu.core.precision import take_rows as _rows
from tensorflow_examples_tpu.models.transformer import TransformerConfig
from tensorflow_examples_tpu.ops.attention import NEG_INF, attention_reference
from tensorflow_examples_tpu.serving import kv_cache as kv_mod
from tensorflow_examples_tpu.telemetry import registry as registry_mod
from tensorflow_examples_tpu.telemetry.compilation import CompilationSentinel
from tensorflow_examples_tpu.telemetry.spans import span as host_span
from tensorflow_examples_tpu.utils import faults as faults_mod

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine + batcher knobs (one object configures the whole stack)."""

    max_slots: int = 8           # concurrent requests = decode batch shape
    prefill_bucket_floor: int = 16
    kv_bucket_floor: int = 64
    attention: str = "xla"       # xla | flash (Pallas prefill attend) |
    #                              paged_flash (fused Pallas paged-decode
    #                              kernel, ops/paged_decode.py; requires
    #                              the paged pool)
    cache_dtype: str = ""        # "" -> follow the params dtype
    # ---- weight quantization (core/precision.py registry; ISSUE 15) ----
    weight_dtype: str = ""       # "" (serve the tree as restored) |
    #                              "int8" | "fp8": weight-only
    #                              quantization at LOAD time via
    #                              PrecisionConfig.weight_only —
    #                              kernels/embeddings stored at
    #                              1 byte/elt with per-row f32 scales,
    #                              dequantized inside the compiled
    #                              matmuls. Bounded-divergence mode
    #                              (first token exact in practice,
    #                              streams may diverge within the
    #                              serve_quant gate); fp8 requires
    #                              backend float8_e4m3fn support.
    compile_warmup: int = 1      # expected compiles per sentinel-wrapped fn
    # ---- speculative decoding (serving/speculative.py; ISSUE 11) ----
    spec_decode_k: int = 0       # drafts verified per decode step; 0 off.
    #                              Output streams stay token-identical
    #                              (acceptance is seed-deterministic);
    #                              k buys TPOT, never changes tokens.
    draft: str = "ngram"         # draft source; "ngram" = self-
    #                              speculative (no second model)
    draft_ngram: int = 3         # longest n-gram the drafter matches
    # ---- paged KV (serving/paged_kv.py; ISSUE 8) ----
    kv_block_size: int = 0       # 0 -> dense pool (legacy); else paged,
    #                              power of two dividing both bucket
    #                              floors and max_len
    kv_blocks: int = 0           # physical blocks; 0 -> dense-equivalent
    #                              worst case (slots * max_len / block)
    kv_dtype: str = ""           # "" -> cache_dtype | "int8" (per-block
    #                              scales, bounded-divergence mode)
    prefix_cache: bool = True    # reuse immutable full prompt blocks
    # ---- cache-aware fleet scheduling (serving/scheduler.py; ISSUE 12) ----
    role: str = "mixed"          # mixed | prefill | decode — the fleet
    #                              scheduling role the replica publishes
    #                              on /health. "mixed" (default) keeps
    #                              every pre-ISSUE-12 behavior; prefill
    #                              replicas run prompts to completion-of-
    #                              prefill and export the KV pages,
    #                              decode replicas import them and
    #                              continue the stream. Advisory: any
    #                              role still serves a full /generate
    #                              (that is what makes role failover a
    #                              plain in-flight failover).
    prefill_chunk_tokens: int = 0  # >0: admission splits any cold
    #                              prompt tail longer than this into
    #                              block-aligned chunks run one per
    #                              decode-loop iteration through the
    #                              extend rungs, so a long prefill
    #                              interleaves with decode steps
    #                              instead of monopolizing them.
    #                              Requires the paged pool with
    #                              prefix_cache=True; must be a
    #                              multiple of kv_block_size.
    # ---- continuous batcher (serving/batcher.py) ----
    max_batch: int = 0           # admission cap; 0 -> max_slots
    max_queue: int = 64          # bounded queue PER SLO CLASS: beyond
    #                              this, load-shed
    max_delay_s: float = 0.002   # idle coalescing window before first prefill
    watchdog_secs: float = 0.0   # 0 disables the serve-loop watchdog
    # ---- brownout overload controller (serving/overload.py; ISSUE 13) ----
    brownout: bool = False       # enable the degradation ladder: shed
    #                              batch -> cap max_new_tokens -> skip
    #                              speculation -> shed interactive,
    #                              stepped with hysteresis as pressure
    #                              builds/clears
    brownout_queue_hi: int = 0   # queue-depth high watermark; 0 ->
    #                              2 * max_slots
    brownout_kv_hi: float = 0.92  # KV-occupancy high watermark
    brownout_ttft_hi_s: float = 0.0  # recent-window TTFT p95 high
    #                              watermark; 0 disables the signal
    brownout_clear_frac: float = 0.5  # clear watermark = frac * hi
    brownout_hold_s: float = 0.5  # hysteresis: min dwell per rung (up),
    #                              sustained-clear time per rung (down)
    brownout_max_new_tokens: int = 8  # the level-2 generation cap
    # ---- frontend ----
    request_timeout_s: float = 120.0


# --------------------------------------------------------------- forward
#
# Pure functions over the Transformer param tree. f32-by-default like the
# flax model (params dtype is the compute dtype); LayerNorm/softmax math
# mirrors flax defaults (eps 1e-5, gelu approximate). Every matmul weight
# is read through ``core/precision.materialize`` (``_w``) and embedding
# tables through ``take_rows`` (``_rows``): under a PrecisionConfig the
# leaf is a QuantizedWeight dequantized HERE, inside the jitted step —
# XLA fuses the scale-multiply into the consuming dot, so HBM holds the
# weights at 1 byte/element (ISSUE 15). Unquantized trees pass through
# unchanged (the helpers are identity on plain arrays).


def _layer_norm(x, p, eps=1e-5):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]


def _block_mlp(x, p):
    h = jnp.dot(x, _w(p["mlp_fc"]["kernel"])) + p["mlp_fc"]["bias"]
    h = jax.nn.gelu(h, approximate=True)
    return jnp.dot(h, _w(p["mlp_proj"]["kernel"])) + p["mlp_proj"]["bias"]


def _qkv(x, p):
    """[..., d] -> q, k, v each [..., H, hd]."""
    y = jnp.einsum("...d,dthc->...thc", x, _w(p["qkv"]["kernel"]))
    y = y + p["qkv"]["bias"]
    return y[..., 0, :, :], y[..., 1, :, :], y[..., 2, :, :]


def _attn_out(att, p):
    """[..., H, hd] attention output -> [..., d] residual contribution."""
    return jnp.einsum("...hc,hcd->...d", att, _w(p["proj"]["kernel"])) + p[
        "proj"
    ]["bias"]


def _prefill_attend(q, k, v, *, impl: str):
    """Causal self-attention for prefill, [B, L, H, hd] layout.

    ``impl="flash"`` reuses ``ops/decode.flash_decode_attention`` with
    its exact contract: the freshly-computed K/V ARE the populated
    cache and the static bucket length is the scalar ``length`` — a
    prefill is precisely the single-length case of cache attention.
    """
    swap = lambda t: t.transpose(0, 2, 1, 3)  # [B,L,H,D] -> [B,H,L,D]
    if impl == "flash":
        from tensorflow_examples_tpu.ops.decode import flash_decode_attention

        out = flash_decode_attention(swap(q), swap(k), swap(v), q.shape[1])
    else:
        out = attention_reference(swap(q), swap(k), swap(v), causal=True)
    return swap(out)


def forward_full(cfg: TransformerConfig, params, tokens, *, impl="xla"):
    """Full causal forward of ``tokens`` [B, L]: logits [B, L, V] plus
    the per-layer K/V ([2, num_layers, B, H, L, hd]) the prefill path
    writes into the cache. Also the engine's cacheless reference path
    (which recomputes attention over the whole prefix per emitted
    token)."""
    wte = params["wte"]["embedding"]
    positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)
    x = _rows(wte, tokens) + _rows(
        params["wpe"]["embedding"], positions
    )[None]
    ks, vs = [], []
    for layer in range(cfg.num_layers):
        p = params[f"h_{layer}"]
        y = _layer_norm(x, p["ln_1"])
        q, k, v = _qkv(y, p["attn"])
        ks.append(k)
        vs.append(v)
        x = x + _attn_out(_prefill_attend(q, k, v, impl=impl), p["attn"])
        x = x + _block_mlp(_layer_norm(x, p["ln_2"]), p)
    x = _layer_norm(x, params["ln_f"])
    return jnp.dot(x, _w(wte).T), jnp.stack(ks), jnp.stack(vs)


def _decode_forward(cfg: TransformerConfig, params, k_cache, v_cache,
                    tokens, positions, *, kv_bucket: int):
    """One continuous-decode step over every slot.

    tokens/positions: [S] — each slot's input token and the cache row
    it occupies (= the slot's pre-step populated length). Returns the
    updated caches and next-token logits [S, V]. Slots not actively
    decoding ride along with position 0: their write lands in a row a
    future prefill fully overwrites, and their output is discarded.
    """
    wte = params["wte"]["embedding"]
    x = _rows(wte, tokens) + _rows(params["wpe"]["embedding"], positions)
    idx = jnp.arange(tokens.shape[0])
    lengths = positions + 1  # populated length including the new token
    for layer in range(cfg.num_layers):
        p = params[f"h_{layer}"]
        y = _layer_norm(x, p["ln_1"])
        q, k, v = _qkv(y, p["attn"])  # [S, H, hd]
        k_cache = k_cache.at[layer, idx, :, positions, :].set(
            k.astype(k_cache.dtype)
        )
        v_cache = v_cache.at[layer, idx, :, positions, :].set(
            v.astype(v_cache.dtype)
        )
        att = kv_mod.varlen_decode_attention(
            q,
            jax.lax.slice_in_dim(k_cache[layer], 0, kv_bucket, axis=2),
            jax.lax.slice_in_dim(v_cache[layer], 0, kv_bucket, axis=2),
            lengths,
        )
        x = x + _attn_out(att, p["attn"])
        x = x + _block_mlp(_layer_norm(x, p["ln_2"]), p)
    x = _layer_norm(x, params["ln_f"])
    return k_cache, v_cache, jnp.dot(x, _w(wte).T)


def _verify_forward(cfg: TransformerConfig, params, k_cache, v_cache,
                    tokens, positions, *, kv_bucket: int):
    """The speculative ``verify_k`` step (ISSUE 11): score T = k+1
    tokens per slot in ONE forward. ``tokens`` [S, T] holds each slot's
    launch token followed by its k draft tokens; row t lands in cache
    row ``positions[s] + t`` and attends its own populated prefix
    (``kv_cache.varlen_verify_attention``). Returns the updated caches
    and logits [S, T, V]. T=1 is numerically the plain decode step.

    Rows past ``max_len`` (a short-budget slot padded to the fixed T)
    are dropped by scatter semantics and their logits discarded —
    acceptance (host side) never commits past the rows that landed.
    """
    wte = params["wte"]["embedding"]
    s_n, t_n = tokens.shape
    pos_grid = positions[:, None] + jnp.arange(t_n, dtype=jnp.int32)
    x = _rows(wte, tokens) + _rows(
        params["wpe"]["embedding"], jnp.minimum(pos_grid, cfg.max_len - 1)
    )
    idx = jnp.arange(s_n)
    for layer in range(cfg.num_layers):
        p = params[f"h_{layer}"]
        y = _layer_norm(x, p["ln_1"])
        q, k, v = _qkv(y, p["attn"])  # [S, T, H, hd]
        k_cache = k_cache.at[layer, idx[:, None], :, pos_grid, :].set(
            k.astype(k_cache.dtype)
        )
        v_cache = v_cache.at[layer, idx[:, None], :, pos_grid, :].set(
            v.astype(v_cache.dtype)
        )
        att = kv_mod.varlen_verify_attention(
            q,
            jax.lax.slice_in_dim(k_cache[layer], 0, kv_bucket, axis=2),
            jax.lax.slice_in_dim(v_cache[layer], 0, kv_bucket, axis=2),
            positions,
        )
        x = x + _attn_out(att, p["attn"])
        x = x + _block_mlp(_layer_norm(x, p["ln_2"]), p)
    x = _layer_norm(x, params["ln_f"])
    return k_cache, v_cache, jnp.dot(x, _w(wte).T)


# ---------------------------------------------------------- paged forward
#
# The paged mirrors of the dense cache ops (ISSUE 8): same math, but
# K/V land in [L, NB, H, BS, D] block pools addressed through per-slot
# block tables instead of a per-slot max_len extent. ``kv`` is the
# pool's device-state tuple — (k, v) or, under int8, (k, v, k_scale,
# v_scale) with per-row scales stored blockwise
# (core/precision.quantize_int8_rows).


def _paged_write_prompt(kv, ks, vs, block_ids, *, block_size):
    """Scatter a prefill's freshly computed K/V ([L, bucket, H, hd])
    into the blocks named by ``block_ids`` [bucket // BS] (pad entries
    point at the null block; their garbage is never read)."""
    from tensorflow_examples_tpu.core.precision import quantize_rows

    num_layers, bucket, h, hd = ks.shape
    nb = bucket // block_size

    def to_blocks(x):  # [L, bucket, H, hd] -> [L, nb, H, BS, hd]
        return x.reshape(
            num_layers, nb, block_size, h, hd
        ).transpose(0, 1, 3, 2, 4)

    kb, vb = to_blocks(ks), to_blocks(vs)
    if len(kv) == 4:
        # Quantized pool: the store dtype (int8 or fp8) rides on the
        # pool arrays themselves — one write path serves both.
        k, v, ksc, vsc = kv
        qk, sk = quantize_rows(kb, k.dtype)
        qv, sv = quantize_rows(vb, v.dtype)
        return (
            k.at[:, block_ids].set(qk),
            v.at[:, block_ids].set(qv),
            ksc.at[:, block_ids].set(sk),
            vsc.at[:, block_ids].set(sv),
        )
    k, v = kv
    return (
        k.at[:, block_ids].set(kb.astype(k.dtype)),
        v.at[:, block_ids].set(vb.astype(v.dtype)),
    )


def _paged_write_rows(kv, layer, write_blocks, offsets, k, v):
    """One decode step's per-slot rows ([S, H, hd]) into block
    ``write_blocks[s]`` at row ``offsets[s]``. Parked slots write into
    the null block (their table entry is 0) — discarded by masking."""
    from tensorflow_examples_tpu.core.precision import quantize_rows

    if len(kv) == 4:
        kk, vv, ksc, vsc = kv
        qk, sk = quantize_rows(k, kk.dtype)
        qv, sv = quantize_rows(v, vv.dtype)
        return (
            kk.at[layer, write_blocks, :, offsets, :].set(qk),
            vv.at[layer, write_blocks, :, offsets, :].set(qv),
            ksc.at[layer, write_blocks, :, offsets].set(sk),
            vsc.at[layer, write_blocks, :, offsets].set(sv),
        )
    kk, vv = kv
    return (
        kk.at[layer, write_blocks, :, offsets, :].set(k.astype(kk.dtype)),
        vv.at[layer, write_blocks, :, offsets, :].set(v.astype(vv.dtype)),
    )


def _paged_gather_dequant(kv, layer, tables, dtype):
    """int8 path: gather blocks + blockwise scales by table, dequantize
    to ``dtype`` -> (k, v) [S, H, nb*BS, D] (the fp paths instead hand
    ``varlen_decode_attention`` the raw pool via ``block_tables=``)."""
    from tensorflow_examples_tpu.core.precision import dequantize_int8_rows

    k, v, ksc, vsc = kv
    s, nb = tables.shape
    _, _, h, bs, d = k.shape

    def gather(blocks, scales):
        g = dequantize_int8_rows(blocks[layer][tables],
                                 scales[layer][tables], dtype)
        return g.transpose(0, 2, 1, 3, 4).reshape(s, h, nb * bs, d)

    return gather(k, ksc), gather(v, vsc)


def _paged_decode_forward(cfg: TransformerConfig, params, kv, tokens,
                          positions, tables, *, block_size: int,
                          attention: str = "xla"):
    """The paged twin of ``_decode_forward``: writes route through the
    block table, attention gathers by it (the
    ``varlen_decode_attention`` block-table path). Under
    ``attention="paged_flash"`` the gather + masked attention fuse into
    the ``ops/paged_decode`` Pallas kernel — one launch reading K/V
    straight through the table (int8 pools dequantize in-kernel); the
    XLA gather path stays as the selectable reference oracle."""
    wte = params["wte"]["embedding"]
    x = _rows(wte, tokens) + _rows(params["wpe"]["embedding"], positions)
    lengths = positions + 1
    write_blocks = jnp.take_along_axis(
        tables, (positions // block_size)[:, None], axis=1
    )[:, 0]
    offsets = positions % block_size
    fused = attention == "paged_flash"
    if fused:
        from tensorflow_examples_tpu.ops.paged_decode import (
            paged_decode_attention,
        )
    for layer in range(cfg.num_layers):
        p = params[f"h_{layer}"]
        y = _layer_norm(x, p["ln_1"])
        q, k, v = _qkv(y, p["attn"])  # [S, H, hd]
        kv = _paged_write_rows(kv, layer, write_blocks, offsets, k, v)
        if len(kv) == 4:
            if fused:
                att = paged_decode_attention(
                    q, kv[0][layer], kv[1][layer], lengths, tables,
                    k_scale=kv[2][layer], v_scale=kv[3][layer],
                )
            else:
                kk, vv = _paged_gather_dequant(kv, layer, tables, q.dtype)
                att = kv_mod.varlen_decode_attention(q, kk, vv, lengths)
        elif fused:
            att = paged_decode_attention(
                q, kv[0][layer], kv[1][layer], lengths, tables
            )
        else:
            att = kv_mod.varlen_decode_attention(
                q, kv[0][layer], kv[1][layer], lengths,
                block_tables=tables,
            )
        x = x + _attn_out(att, p["attn"])
        x = x + _block_mlp(_layer_norm(x, p["ln_2"]), p)
    x = _layer_norm(x, params["ln_f"])
    return kv, jnp.dot(x, _w(wte).T)


def _paged_verify_forward(cfg: TransformerConfig, params, kv, tokens,
                          positions, tables, *, block_size: int):
    """The paged twin of ``_verify_forward``: T rows per slot scattered
    through the block table (the spec window may cross block
    boundaries), attention over the slot's gathered view. Rows beyond a
    slot's allocated blocks — draft padding the pool could not or need
    not back — resolve to the null block, whose garbage acceptance
    never commits."""
    wte = params["wte"]["embedding"]
    s_n, t_n = tokens.shape
    nb = tables.shape[1]
    pos_grid = positions[:, None] + jnp.arange(t_n, dtype=jnp.int32)
    x = _rows(wte, tokens) + _rows(
        params["wpe"]["embedding"], jnp.minimum(pos_grid, cfg.max_len - 1)
    )
    blk = jnp.minimum(pos_grid // block_size, nb - 1)
    write_blocks = jnp.where(
        pos_grid < nb * block_size,
        jnp.take_along_axis(tables, blk, axis=1),
        0,
    )
    offsets = pos_grid % block_size
    for layer in range(cfg.num_layers):
        p = params[f"h_{layer}"]
        y = _layer_norm(x, p["ln_1"])
        q, k, v = _qkv(y, p["attn"])  # [S, T, H, hd]
        kv = _paged_write_rows(kv, layer, write_blocks, offsets, k, v)
        if len(kv) == 4:
            kk, vv = _paged_gather_dequant(kv, layer, tables, q.dtype)
            att = kv_mod.varlen_verify_attention(q, kk, vv, positions)
        else:
            att = kv_mod.varlen_verify_attention(
                q, kv[0][layer], kv[1][layer], positions,
                block_tables=tables,
            )
        x = x + _attn_out(att, p["attn"])
        x = x + _block_mlp(_layer_norm(x, p["ln_2"]), p)
    x = _layer_norm(x, params["ln_f"])
    return kv, jnp.dot(x, _w(wte).T)


def _extend_forward(cfg: TransformerConfig, params, kv, ctx_table,
                    tail_ids, tokens, ctx_len, *, block_size: int):
    """Chunked prefill on top of a cached context: run only the prompt
    TAIL (``tokens`` [1, tb], absolute positions ``ctx_len + i``), with
    each tail row attending over (a) the cached context gathered by
    ``ctx_table`` [max_blocks], masked to ``ctx_len`` columns, and (b)
    the tail itself, causally. This is what makes a prefix-cache hit a
    compute saving, not just a memory one: the shared prefix's layers
    are never re-run. Tail K/V is scattered into ``tail_ids``
    [tb // BS]. Numerics mirror ``varlen_decode_attention`` (f32
    scores/softmax, probabilities cast to the value dtype, f32
    accumulation) so hits stay token-identical at fp32 (test-pinned).
    """
    from tensorflow_examples_tpu.core.precision import dequantize_int8_rows

    wte = params["wte"]["embedding"]
    tb = tokens.shape[1]
    sm_scale = cfg.head_dim ** -0.5
    positions = ctx_len + jnp.arange(tb, dtype=jnp.int32)
    # Pad rows past the true tail may index past max_len; clip — they
    # are causally downstream of every real row and discarded.
    x = _rows(wte, tokens) + _rows(
        params["wpe"]["embedding"], jnp.minimum(positions, cfg.max_len - 1)
    )[None]
    quantized = len(kv) == 4
    nb = ctx_table.shape[0]
    ctx_cols = nb * block_size
    colc = jax.lax.broadcasted_iota(jnp.int32, (1, 1, tb, ctx_cols), 3)
    rowt = jax.lax.broadcasted_iota(jnp.int32, (1, 1, tb, tb), 2)
    colt = jax.lax.broadcasted_iota(jnp.int32, (1, 1, tb, tb), 3)
    ks, vs = [], []
    for layer in range(cfg.num_layers):
        p = params[f"h_{layer}"]
        y = _layer_norm(x, p["ln_1"])
        q, k, v = _qkv(y, p["attn"])  # [1, tb, H, hd]
        ks.append(k[0])
        vs.append(v[0])
        if quantized:
            kc = dequantize_int8_rows(
                kv[0][layer][ctx_table], kv[2][layer][ctx_table], q.dtype
            )
            vc = dequantize_int8_rows(
                kv[1][layer][ctx_table], kv[3][layer][ctx_table], q.dtype
            )
        else:
            kc = kv[0][layer][ctx_table].astype(q.dtype)
            vc = kv[1][layer][ctx_table].astype(q.dtype)
        # [nb, H, BS, hd] -> [H, nb*BS, hd]
        kc = kc.transpose(1, 0, 2, 3).reshape(-1, ctx_cols, cfg.head_dim)
        vc = vc.transpose(1, 0, 2, 3).reshape(-1, ctx_cols, cfg.head_dim)
        qh = q.transpose(0, 2, 1, 3)  # [1, H, tb, hd]
        s_ctx = jnp.einsum(
            "bhtd,hkd->bhtk", qh, kc, preferred_element_type=jnp.float32
        ) * sm_scale
        s_ctx = jnp.where(colc < ctx_len, s_ctx, NEG_INF)
        kh = k.transpose(0, 2, 1, 3)
        s_tail = jnp.einsum(
            "bhtd,bhkd->bhtk", qh, kh, preferred_element_type=jnp.float32
        ) * sm_scale
        s_tail = jnp.where(rowt >= colt, s_tail, NEG_INF)
        prob = jax.nn.softmax(
            jnp.concatenate([s_ctx, s_tail], axis=-1), axis=-1
        )
        p_ctx, p_tail = prob[..., :ctx_cols], prob[..., ctx_cols:]
        out = jnp.einsum(
            "bhtk,hkd->bhtd", p_ctx.astype(vc.dtype), vc,
            preferred_element_type=jnp.float32,
        ) + jnp.einsum(
            "bhtk,bhkd->bhtd", p_tail.astype(v.dtype),
            v.transpose(0, 2, 1, 3), preferred_element_type=jnp.float32,
        )
        att = out.astype(q.dtype).transpose(0, 2, 1, 3)
        x = x + _attn_out(att, p["attn"])
        x = x + _block_mlp(_layer_norm(x, p["ln_2"]), p)
    x = _layer_norm(x, params["ln_f"])
    kv = _paged_write_prompt(
        kv, jnp.stack(ks), jnp.stack(vs), tail_ids, block_size=block_size
    )
    return kv, jnp.dot(x, _w(wte).T)


# -------------------------------------------------------------- sampling


def _sample_row(key, logits, temp, top_k):
    """Traced-knob clone of ``models.transformer.sample_tokens`` for ONE
    row: temperature/top_k arrive as arrays (a batch mixes settings), so
    the static ``if``s become selects — same math, same keys, identical
    tokens (tests pin it)."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.where(temp > 0, temp, 1.0)
    kth = jax.lax.dynamic_index_in_dim(
        jnp.sort(scaled),
        jnp.maximum(scaled.shape[0] - top_k, 0),
        keepdims=False,
    )
    filtered = jnp.where(
        (top_k > 0) & (scaled < kth), NEG_INF, scaled
    )
    sampled = jax.random.categorical(key, filtered).astype(jnp.int32)
    return jnp.where(temp == 0.0, greedy, sampled)


_sample_batch = jax.vmap(_sample_row)


def request_key(seed: int, position: int) -> jax.Array:
    """The per-token sampling key: a pure function of (request seed,
    absolute position), so batched serving and the unbatched reference
    replay draw identical samples."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), position)


# Vmapped over per-slot (seed, position) vectors INSIDE the jitted
# decode step — eager per-slot fold_in dispatches on the batcher loop
# thread would sit between consecutive compiled decode steps, exactly
# where TPOT is won or lost. Seeds are int32 (the frontend caps them at
# 2**31 - 1) so the traced PRNGKey seeding matches the eager replay's.
_request_key_batch = jax.vmap(request_key)


def _sample_verify(seeds, positions, logits, temps, top_ks):
    """Sample every verify row with its request's own per-POSITION key:
    row t of slot s draws with ``fold_in(seed_s, positions[s] + t + 1)``
    — exactly the key a plain decode step would consume at that
    absolute position. That per-position (not per-step) key discipline
    is what keeps sampled streams token-identical with speculation on:
    acceptance changes which rows ship, never what any position draws.
    """
    s_n, t_n, _ = logits.shape
    pos = positions[:, None] + jnp.arange(t_n, dtype=jnp.int32) + 1
    keys = jax.vmap(_request_key_batch)(
        jnp.broadcast_to(seeds[:, None], (s_n, t_n)), pos
    )
    flat = _sample_batch(
        keys.reshape((s_n * t_n,) + keys.shape[2:]),
        logits.reshape(s_n * t_n, -1),
        jnp.repeat(temps, t_n),
        jnp.repeat(top_ks, t_n),
    )
    return flat.reshape(s_n, t_n)


# ---------------------------------------------------------------- engine


class EngineStepError(RuntimeError):
    """A compiled prefill/decode step failed at runtime. The KV caches
    were donated to the failed call (consumed on donation-honoring
    backends), so the engine has already reallocated them — every
    in-flight request's cache state is gone and the batcher must fail
    the whole active set, not just the request being stepped."""


class ChunkedPrefill:
    """In-progress chunked prefill (ISSUE 12): the slot's blocks are
    already allocated (prefix reuse applied); ``spans`` are the
    block-aligned chunk plan and ``idx`` the next chunk to run. The
    batcher holds one of these per mid-prefill request and calls
    ``engine.prefill_step`` once per decode-loop iteration."""

    __slots__ = ("slot", "prompt", "spans", "idx", "seed",
                 "temperature", "top_k")

    def __init__(self, slot, prompt, spans, seed, temperature, top_k):
        self.slot = slot
        self.prompt = prompt
        self.spans = spans
        self.idx = 0
        self.seed = seed
        self.temperature = temperature
        self.top_k = top_k


class InferenceEngine:
    """Loads params once, owns the KV pool, runs the compiled steps.

    Device-facing methods (``prefill`` / ``decode`` / ``warmup``) are
    single-threaded by contract — the continuous batcher's loop thread
    is the only caller. ``submit``-side concurrency lives in
    serving/batcher.py.
    """

    def __init__(
        self,
        model_cfg: TransformerConfig,
        params,
        *,
        cfg: ServeConfig | None = None,
        registry=None,
        sharding=None,
        precision=None,
    ):
        if model_cfg.moe_experts:
            raise NotImplementedError(
                "serving engine currently covers dense GPT-2 models only"
            )
        if model_cfg.attention not in ("xla", "flash"):
            # ring/ulysses are training-side context-parallel impls.
            raise ValueError(
                f"model attention={model_cfg.attention!r}; the serving "
                "forward supports 'xla' or 'flash'"
            )
        self.model_cfg = model_cfg
        self.cfg = cfg or ServeConfig()
        # Fleet identity (ISSUE 10): which replica this engine is in a
        # multi-replica process (serve_bench --router / the chaos
        # harness). The serve-side fault engine keys on it; 0 for a
        # standalone server.
        self.replica_id = 0
        if self.cfg.attention not in ("xla", "flash", "paged_flash"):
            raise ValueError(
                f"ServeConfig.attention={self.cfg.attention!r} not in "
                "('xla', 'flash', 'paged_flash')"
            )
        # Prefill always runs the full-prompt causal forward; the
        # paged-decode kernel only exists for the per-slot decode step.
        self._prefill_attn = (
            "flash" if self.cfg.attention == "flash" else "xla"
        )
        # Weight quantization at LOAD time (ISSUE 15): the precision
        # registry rewrites the host tree BEFORE any device placement
        # — quantized leaves are (q, scale) children under the
        # weight's own path, so the sharding rules below place them
        # like the weight they came from (scales by rank-clipped
        # spec). ``precision=`` takes a full PrecisionConfig; the
        # ``weight_dtype`` knob is sugar for the standard weight-only
        # registry. The registry's kv_dtype unifies the cache side:
        # ServeConfig.kv_dtype wins when both are set.
        self.precision = precision
        if self.precision is None and self.cfg.weight_dtype:
            self.precision = precision_mod.PrecisionConfig.weight_only(
                self.cfg.weight_dtype, kv_dtype=self.cfg.kv_dtype
            )
        self.kv_dtype = self.cfg.kv_dtype or (
            self.precision.kv_dtype if self.precision is not None else ""
        )
        if self.precision is not None:
            # Cast-only registries (bf16/f32 rules, no int8/fp8) apply
            # too; quantize_tree is the identity for an empty config.
            params = precision_mod.quantize_tree(params, self.precision)
        # Sharded serving (ISSUE 7): the SAME ShardingConfig training
        # persisted to workdir/sharding.json places the param tree by
        # its rules (instead of replicating) and the KV pool with heads
        # over `model`; GSPMD inserts the TP collectives into the
        # already-compiled prefill/decode ladder, so the zero-recompile
        # contract is untouched — the ladder is warmed with the
        # sharded placements it will serve with. sharding=None keeps
        # today's single-device placement exactly.
        self.sharding = sharding
        self.mesh = None
        self.param_sharding_digest = None
        if sharding is None:
            params = jax.tree.map(jnp.asarray, params)
        else:
            # No asarray pre-pass: shard_params device_puts the host
            # tree straight into the mesh layout — a model that only
            # fits sharded must never materialize on device 0 first.
            from tensorflow_examples_tpu.core.sharding import shard_params
            from tensorflow_examples_tpu.models.transformer import (
                GPT2_RULES,
            )
            from tensorflow_examples_tpu.sharding import resolve_params

            self.mesh = sharding.build_mesh()
            rules = sharding.sharding_rules(default=GPT2_RULES)
            params = shard_params(params, self.mesh, rules)
            self.param_sharding_digest = resolve_params(
                params, self.mesh, rules
            ).digest()
        self.params = params
        self.registry = (
            registry if registry is not None
            else registry_mod.default_registry()
        )
        self.sentinel = CompilationSentinel(
            warmup=self.cfg.compile_warmup, registry=self.registry
        )
        # precision/* instruments (ISSUE 15): the serving tier's own
        # record of what precision it is actually running — weight
        # payload bits, stored-vs-f32 param bytes, quantized leaf
        # count. Scraped via /metrics, stamped (when quantized) as the
        # schema-v11 serving keys.
        self._precision_stats = precision_mod.tree_precision_stats(
            self.params
        )
        self.quantized_weights = (
            self._precision_stats["quantized_params"] > 0
        )
        reg = self.registry
        reg.gauge("precision/weight_bits").set(
            self._precision_stats["weight_bits"]
        )
        reg.gauge("precision/param_bytes").set(
            self._precision_stats["param_bytes"]
        )
        reg.gauge("precision/param_bytes_f32").set(
            self._precision_stats["param_bytes_f32"]
        )
        reg.gauge("precision/quantized_params").set(
            self._precision_stats["quantized_params"]
        )
        wte = self.params["wte"]["embedding"]
        param_dtype = (
            jnp.float32
            if isinstance(wte, precision_mod.QuantizedWeight)
            else wte.dtype
        )
        cache_dtype = (
            jnp.dtype(self.cfg.cache_dtype)
            if self.cfg.cache_dtype
            else param_dtype
        )
        self.paged = self.cfg.kv_block_size > 0
        if self.cfg.attention == "paged_flash" and not self.paged:
            raise ValueError(
                "attention='paged_flash' is the fused paged-decode "
                "kernel — it requires the paged pool (set kv_block_size)"
            )
        if self.cfg.attention == "paged_flash" and self.kv_dtype == "fp8":
            raise ValueError(
                "attention='paged_flash' dequantizes int8 in-kernel; "
                "fp8 KV serves through the XLA gather path "
                "(attention='xla')"
            )
        if self.cfg.role not in ("mixed", "prefill", "decode"):
            raise ValueError(
                f"role={self.cfg.role!r} not in ('mixed', 'prefill', "
                "'decode')"
            )
        if self.cfg.prefill_chunk_tokens < 0:
            raise ValueError(
                f"prefill_chunk_tokens={self.cfg.prefill_chunk_tokens} "
                "must be >= 0"
            )
        if self.cfg.prefill_chunk_tokens:
            if not self.paged or not self.cfg.prefix_cache:
                raise ValueError(
                    "prefill_chunk_tokens requires the paged pool with "
                    "prefix_cache=True (the chunk program IS the "
                    "per-tail-bucket extend rung)"
                )
            if self.cfg.prefill_chunk_tokens % self.cfg.kv_block_size:
                raise ValueError(
                    f"prefill_chunk_tokens="
                    f"{self.cfg.prefill_chunk_tokens} must be a "
                    f"multiple of kv_block_size={self.cfg.kv_block_size}"
                    " (chunk boundaries scatter whole blocks)"
                )
        if self.cfg.spec_decode_k < 0:
            raise ValueError(
                f"spec_decode_k={self.cfg.spec_decode_k} must be >= 0"
            )
        if self.cfg.spec_decode_k + 1 > self.cfg.prefill_bucket_floor:
            # Parked slots write their discarded verify rows at
            # positions [0, k+1); any later prefill overwrites at least
            # the smallest bucket, which must cover them.
            raise ValueError(
                f"spec_decode_k={self.cfg.spec_decode_k} + 1 must not "
                f"exceed prefill_bucket_floor="
                f"{self.cfg.prefill_bucket_floor}"
            )
        if self.paged:
            bs = self.cfg.kv_block_size
            for name, val in (
                ("prefill_bucket_floor", self.cfg.prefill_bucket_floor),
                ("kv_bucket_floor", self.cfg.kv_bucket_floor),
                ("max_len", model_cfg.max_len),
            ):
                if val % bs:
                    raise ValueError(
                        f"kv_block_size={bs} must divide {name}={val} "
                        "(every compiled bucket is a whole number of "
                        "blocks)"
                    )
            from tensorflow_examples_tpu.serving.paged_kv import (
                PagedKVPool,
            )

            self.pool = PagedKVPool(
                num_layers=model_cfg.num_layers,
                num_slots=self.cfg.max_slots,
                num_heads=model_cfg.num_heads,
                max_len=model_cfg.max_len,
                head_dim=model_cfg.head_dim,
                block_size=bs,
                num_blocks=self.cfg.kv_blocks,
                dtype=cache_dtype,
                kv_dtype=self.kv_dtype,
                prefix_cache=self.cfg.prefix_cache,
                registry=self.registry,
                sharding=self._kv_sharding(),
            )
        else:
            if self.kv_dtype:
                raise ValueError(
                    "kv_dtype (quantized KV) requires the paged pool — "
                    "set kv_block_size"
                )
            self.pool = kv_mod.KVCachePool(
                num_layers=model_cfg.num_layers,
                num_slots=self.cfg.max_slots,
                num_heads=model_cfg.num_heads,
                max_len=model_cfg.max_len,
                head_dim=model_cfg.head_dim,
                dtype=cache_dtype,
                registry=self.registry,
                sharding=self._kv_sharding(),
            )
        self.prefill_ladder = kv_mod.bucket_ladder(
            self.cfg.prefill_bucket_floor, model_cfg.max_len
        )
        self.kv_ladder = kv_mod.bucket_ladder(
            self.cfg.kv_bucket_floor, model_cfg.max_len
        )
        # The KV caches are donated (the dense steps take k/v as args
        # 1/2 after partial binds the bucket; the paged steps take the
        # pool's whole device-state tuple as arg 1): every step returns
        # the updated caches and the pool unconditionally reassigns
        # from the outputs, so XLA can alias in place instead of
        # copying the pool per generated token. Backends without
        # donation support just ignore the hint.
        if self.paged:
            self._prefill_fns = {
                lb: self.sentinel.wrap(
                    jax.jit(
                        functools.partial(self._paged_prefill_impl, lb),
                        donate_argnums=(1,),
                    ),
                    f"serve_prefill_L{lb}",
                )
                for lb in self.prefill_ladder
            }
            self._decode_fns = {
                kb: self.sentinel.wrap(
                    jax.jit(
                        functools.partial(self._paged_decode_impl, kb),
                        donate_argnums=(1,),
                    ),
                    f"serve_decode_K{kb}",
                )
                for kb in self.kv_ladder
            }
            # One extend program per TAIL bucket; the cached context
            # always rides in as the slot's full block table (masked to
            # the true context length) — |prefill ladder| programs, not
            # a ladder product.
            self._extend_fns = {
                tb: self.sentinel.wrap(
                    jax.jit(
                        functools.partial(self._extend_impl, tb),
                        donate_argnums=(1,),
                    ),
                    f"serve_extend_T{tb}",
                )
                for tb in self.prefill_ladder
            } if self.cfg.prefix_cache else {}
            self._verify_fns = {
                kb: self.sentinel.wrap(
                    jax.jit(
                        functools.partial(self._paged_verify_impl, kb),
                        donate_argnums=(1,),
                    ),
                    f"serve_verify_K{kb}",
                )
                for kb in self.kv_ladder
            } if self.cfg.spec_decode_k > 0 else {}
        else:
            self._prefill_fns = {
                lb: self.sentinel.wrap(
                    jax.jit(
                        functools.partial(self._prefill_impl, lb),
                        donate_argnums=(1, 2),
                    ),
                    f"serve_prefill_L{lb}",
                )
                for lb in self.prefill_ladder
            }
            self._decode_fns = {
                kb: self.sentinel.wrap(
                    jax.jit(
                        functools.partial(self._decode_impl, kb),
                        donate_argnums=(1, 2),
                    ),
                    f"serve_decode_K{kb}",
                )
                for kb in self.kv_ladder
            }
            self._extend_fns = {}
            self._verify_fns = {
                kb: self.sentinel.wrap(
                    jax.jit(
                        functools.partial(self._verify_impl, kb),
                        donate_argnums=(1, 2),
                    ),
                    f"serve_verify_K{kb}",
                )
                for kb in self.kv_ladder
            } if self.cfg.spec_decode_k > 0 else {}
        self.warmed = False
        self._ref_fwd = None

    def _kv_sharding(self):
        """KV-pool NamedSharding from the ShardingConfig: heads (dim 2
        of [L, S, H, max_len, D]) shard over ``model`` — the layout
        that keeps per-slot attention local to the head shard the qkv
        projection already produced. A head count the model axis
        doesn't divide replicates instead (placement is an
        optimization, never a shape contract). None without a config
        (single-device placement, the pre-ISSUE-7 behavior)."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tensorflow_examples_tpu.core.mesh import AxisNames

        m = int(self.mesh.shape[AxisNames.MODEL])
        heads = (
            AxisNames.MODEL
            if m > 1 and self.model_cfg.num_heads % m == 0
            else None
        )
        return NamedSharding(self.mesh, P(None, None, heads, None, None))

    # ----------------------------------------------------- compiled fns

    def _prefill_impl(self, bucket, params, k_cache, v_cache, slot,
                      tokens, length, key, temp, top_k):
        """tokens [1, bucket] (right-padded), length = true prompt len.
        Writes the slot's cache rows [0, bucket) (pad rows carry
        garbage K/V that per-slot length masking never reads), samples
        the first generated token from the logits at row length-1."""
        del bucket  # static: encoded in tokens.shape
        logits, ks, vs = forward_full(
            self.model_cfg, params, tokens, impl=self._prefill_attn
        )
        # [L, 1, bucket, H, hd] -> [L, 1, H, bucket, hd] cache layout.
        kstack = ks.transpose(0, 1, 3, 2, 4).astype(k_cache.dtype)
        vstack = vs.transpose(0, 1, 3, 2, 4).astype(v_cache.dtype)
        start = (0, slot.astype(jnp.int32), 0, 0, 0)
        k_cache = jax.lax.dynamic_update_slice(k_cache, kstack, start)
        v_cache = jax.lax.dynamic_update_slice(v_cache, vstack, start)
        last = jax.lax.dynamic_index_in_dim(
            logits[0], length - 1, keepdims=False
        )
        return k_cache, v_cache, _sample_row(key, last, temp, top_k), last

    def _decode_impl(self, bucket, params, k_cache, v_cache, tokens,
                     positions, seeds, temps, top_ks):
        k_cache, v_cache, logits = _decode_forward(
            self.model_cfg, params, k_cache, v_cache, tokens, positions,
            kv_bucket=bucket,
        )
        # The sampled token lands at sequence index position + 1.
        keys = _request_key_batch(seeds, positions + 1)
        return k_cache, v_cache, _sample_batch(keys, logits, temps, top_ks)

    def _verify_impl(self, bucket, params, k_cache, v_cache, tokens,
                     positions, seeds, temps, top_ks):
        """Speculative verify (ISSUE 11): tokens [S, T] = launch token
        + k drafts per slot, one forward, per-position sampling keys.
        Returns the caches and the sampled stream [S, T] the host's
        acceptance walks."""
        k_cache, v_cache, logits = _verify_forward(
            self.model_cfg, params, k_cache, v_cache, tokens, positions,
            kv_bucket=bucket,
        )
        return k_cache, v_cache, _sample_verify(
            seeds, positions, logits, temps, top_ks
        )

    # --------------------------------------------- compiled fns (paged)

    def _paged_prefill_impl(self, bucket, params, kv, block_ids, tokens,
                            length, key, temp, top_k):
        """The paged twin of ``_prefill_impl``: same forward, K/V
        scattered into the slot's blocks instead of its dense extent."""
        logits, ks, vs = forward_full(
            self.model_cfg, params, tokens, impl=self._prefill_attn
        )
        kv = _paged_write_prompt(
            kv, ks[:, 0], vs[:, 0], block_ids,
            block_size=self.cfg.kv_block_size,
        )
        last = jax.lax.dynamic_index_in_dim(
            logits[0], length - 1, keepdims=False
        )
        return kv, _sample_row(key, last, temp, top_k), last

    def _paged_decode_impl(self, bucket, params, kv, tokens, positions,
                           tables, seeds, temps, top_ks):
        del bucket  # static: encoded in tables.shape
        kv, logits = _paged_decode_forward(
            self.model_cfg, params, kv, tokens, positions, tables,
            block_size=self.cfg.kv_block_size,
            attention=self.cfg.attention,
        )
        keys = _request_key_batch(seeds, positions + 1)
        return kv, _sample_batch(keys, logits, temps, top_ks)

    def _paged_verify_impl(self, bucket, params, kv, tokens, positions,
                           tables, seeds, temps, top_ks):
        """The paged twin of ``_verify_impl`` (same sampling contract;
        the verify attention keeps the gather path — its cost amortizes
        over T tokens)."""
        del bucket  # static: encoded in tables.shape
        kv, logits = _paged_verify_forward(
            self.model_cfg, params, kv, tokens, positions, tables,
            block_size=self.cfg.kv_block_size,
        )
        return kv, _sample_verify(seeds, positions, logits, temps, top_ks)

    def _extend_impl(self, tail_bucket, params, kv, ctx_table, tail_ids,
                     tokens, ctx_len, tail_len, key, temp, top_k):
        """Prefix-cache hit path: prefill only the prompt tail over the
        cached context (see ``_extend_forward``); samples the first
        token from the tail's last true row."""
        del tail_bucket  # static: encoded in tokens.shape
        kv, logits = _extend_forward(
            self.model_cfg, params, kv, ctx_table, tail_ids, tokens,
            ctx_len, block_size=self.cfg.kv_block_size,
        )
        last = jax.lax.dynamic_index_in_dim(
            logits[0], tail_len - 1, keepdims=False
        )
        return kv, _sample_row(key, last, temp, top_k), last

    # --------------------------------------------------------- lifecycle

    def warmup(self) -> dict[str, int]:
        """Compile the full bucket ladder ahead of traffic (the AOT
        pass). Returns per-fn compile counts; after this, any further
        compile is a sentinel-warned recompile and
        ``post_warmup_recompiles()`` counts it."""
        s = self.cfg.max_slots
        zero = jnp.zeros((), jnp.int32)
        key = jax.random.PRNGKey(0)
        ftemp = jnp.float32(0.0)
        if self.paged:
            bs = self.cfg.kv_block_size
            for lb in self.prefill_ladder:
                kv, tok, _ = self._prefill_fns[lb](
                    self.params, self.pool.kv_state(),
                    jnp.zeros((lb // bs,), jnp.int32),
                    jnp.zeros((1, lb), jnp.int32), zero + 1, key, ftemp,
                    zero,
                )
                self.pool.set_kv_state(kv)
                tok.block_until_ready()
            for kb in self.kv_ladder:
                kv, toks = self._decode_fns[kb](
                    self.params, self.pool.kv_state(),
                    jnp.zeros((s,), jnp.int32), jnp.zeros((s,), jnp.int32),
                    jnp.zeros((s, kb // bs), jnp.int32),
                    jnp.zeros((s,), jnp.int32),
                    jnp.zeros((s,), jnp.float32),
                    jnp.zeros((s,), jnp.int32),
                )
                self.pool.set_kv_state(kv)
                toks.block_until_ready()
            for tb in self._extend_fns:
                kv, tok, _ = self._extend_fns[tb](
                    self.params, self.pool.kv_state(),
                    jnp.zeros((self.pool.max_blocks_per_slot,), jnp.int32),
                    jnp.zeros((tb // bs,), jnp.int32),
                    jnp.zeros((1, tb), jnp.int32), zero + bs, zero + 1,
                    key, ftemp, zero,
                )
                self.pool.set_kv_state(kv)
                tok.block_until_ready()
            t_n = self.cfg.spec_decode_k + 1
            for kb in self._verify_fns:
                kv, toks = self._verify_fns[kb](
                    self.params, self.pool.kv_state(),
                    jnp.zeros((s, t_n), jnp.int32),
                    jnp.zeros((s,), jnp.int32),
                    jnp.zeros((s, kb // bs), jnp.int32),
                    jnp.zeros((s,), jnp.int32),
                    jnp.zeros((s,), jnp.float32),
                    jnp.zeros((s,), jnp.int32),
                )
                self.pool.set_kv_state(kv)
                toks.block_until_ready()
        else:
            for lb in self.prefill_ladder:
                self.pool.k, self.pool.v, tok, _ = self._prefill_fns[lb](
                    self.params, self.pool.k, self.pool.v, zero,
                    jnp.zeros((1, lb), jnp.int32), zero + 1, key, ftemp,
                    zero,
                )
                tok.block_until_ready()
            for kb in self.kv_ladder:
                self.pool.k, self.pool.v, toks = self._decode_fns[kb](
                    self.params, self.pool.k, self.pool.v,
                    jnp.zeros((s,), jnp.int32), jnp.zeros((s,), jnp.int32),
                    jnp.zeros((s,), jnp.int32), jnp.zeros((s,), jnp.float32),
                    jnp.zeros((s,), jnp.int32),
                )
                toks.block_until_ready()
            t_n = self.cfg.spec_decode_k + 1
            for kb in self._verify_fns:
                self.pool.k, self.pool.v, toks = self._verify_fns[kb](
                    self.params, self.pool.k, self.pool.v,
                    jnp.zeros((s, t_n), jnp.int32),
                    jnp.zeros((s,), jnp.int32),
                    jnp.zeros((s,), jnp.int32),
                    jnp.zeros((s,), jnp.float32),
                    jnp.zeros((s,), jnp.int32),
                )
                toks.block_until_ready()
        self.pool.reset()
        self.warmed = True
        counts = self.sentinel.compile_counts()
        log.info(
            "serving engine warm: %d compiled programs (%s)",
            sum(counts.values()),
            ", ".join(sorted(counts)),
        )
        return counts

    def expected_compiles(self) -> int:
        return (
            len(self.prefill_ladder) + len(self.kv_ladder)
            + len(self._extend_fns) + len(self._verify_fns)
        )

    def post_warmup_recompiles(self) -> int:
        """Total compiles beyond each variant's warmup allowance — the
        number that must be 0 in steady state (CI asserts it)."""
        return self.sentinel.post_warmup_recompiles()

    # ------------------------------------------------ precision accounting

    def precision_stats(self) -> dict | None:
        """The schema-v11 serving keys (``weight_bits`` /
        ``param_bytes`` / ``param_bytes_f32`` / ``quantized_params``)
        when this engine serves quantized weights; None on an
        unquantized tree — a pre-quant serving line carries none of
        them, the same optional-on-write rule as every schema bump."""
        if not self.quantized_weights:
            return None
        return dict(self._precision_stats)

    def byte_breakdown(self, *, per_device: bool = False) -> dict:
        """Serving-side HBM accounting (what ``serve_bench
        --weight-dtype`` banks as ``hbm_bytes_per_replica`` and the
        quantized×sharded test states its ≤0.35× claim in):
        ``params_bytes`` as stored (quantized leaves at 1 byte/elt
        plus their f32 row scales), ``params_bytes_f32`` (the same
        logical tree at 4 bytes/elt), and the KV pool's committed
        bytes. ``per_device=True`` counts each leaf's bytes on ONE
        device — sharded leaves at 1/N (``telemetry/memory.tree_bytes``
        semantics) — and then reports ONLY the per-device-meaningful
        ``params_bytes``/``weight_bits``: the f32 baseline and the
        pool's used-block accounting are global numbers, and mixing
        units in one dict would make the natural ratios silently
        wrong (compare two engines' per-device ``params_bytes``
        instead, which is what the quantized×sharded test does)."""
        from tensorflow_examples_tpu.telemetry.memory import tree_bytes

        out = {
            "params_bytes": tree_bytes(
                self.params, per_device=per_device
            ),
            "weight_bits": self._precision_stats["weight_bits"],
        }
        if not per_device:
            out["params_bytes_f32"] = self._precision_stats[
                "param_bytes_f32"
            ]
            out["kv_cache_bytes"] = int(self.pool.used_bytes())
        return out

    # ------------------------------------------------------ request ops

    def _run_compiled(self, kind: str, fn, *args):
        """Run one donated compiled step. On ANY runtime failure the
        donated KV buffers were consumed, so the pool is reallocated
        and :class:`EngineStepError` surfaces — the one place the
        donation-recovery contract lives (prefill/extend, decode, and
        verify all route through it; the batcher fails the whole
        in-flight set on the error).

        Every dispatch runs inside a host-side span
        (``span/engine_{kind}_dispatch``, ISSUE 18): the compiled call
        returns un-synced device arrays, so the span measures DISPATCH
        wall only — tracing adds no device sync and no new compiled
        programs (the zero-recompile sentinel stays golden-pinned)."""
        try:
            with host_span(f"engine_{kind}_dispatch"):
                return fn(*args)
        except Exception as e:
            self.pool.reallocate()
            raise EngineStepError(
                f"compiled {kind} step failed (KV caches reallocated): "
                f"{type(e).__name__}: {e}"
            ) from e

    def _prefill_fault_tick(self, slot: int) -> None:
        """Serve-side fault hook for PREFILL-role replicas (ISSUE 12):
        a dedicated prefill replica's unit of work is the prefill, not
        a decode step, so its fault schedule counts prefills — which is
        what lets the chaos tier kill one deterministically
        mid-handoff. Mixed/decode replicas keep the decode-step
        counting every existing golden pins."""
        if self.cfg.role != "prefill":
            return
        feng = faults_mod.serve_active()
        if feng is not None:
            feng.decode_step(self.replica_id, [slot])

    def prefill(self, slot: int, prompt: Sequence[int], *, seed: int = 0,
                temperature: float = 0.0, top_k: int = 0):
        """Run a prompt into ``slot``; returns (first generated token,
        last-position logits as numpy — the classify payload).

        Paged mode allocates exactly the blocks the prompt needs
        (``paged_kv.BlockExhausted`` propagates BEFORE any device call
        — no donation happened, so only THIS request fails) and, on a
        prefix-cache hit, maps the shared blocks into the slot's table
        and prefills only the tail (``_extend_impl``)."""
        n = len(prompt)
        if n < 1:
            raise ValueError("empty prompt")
        if n > self.model_cfg.max_len:
            raise ValueError(
                f"prompt length {n} exceeds max_len {self.model_cfg.max_len}"
            )
        self._prefill_fault_tick(slot)
        if self.paged:
            tok, last = self._paged_prefill(
                slot, prompt, seed=seed, temperature=temperature,
                top_k=top_k,
            )
        else:
            bucket = kv_mod.pick_bucket(self.prefill_ladder, n)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :n] = prompt
            (self.pool.k, self.pool.v, tok, last) = self._run_compiled(
                "prefill", self._prefill_fns[bucket],
                self.params, self.pool.k, self.pool.v,
                jnp.int32(slot), jnp.asarray(tokens), jnp.int32(n),
                request_key(seed, n), jnp.float32(temperature),
                jnp.int32(top_k),
            )
        self.pool.lengths[slot] = n
        self.registry.counter("serving/prefill_tokens").inc(n)
        return int(tok), np.asarray(last)

    def _paged_prefill(self, slot, prompt, *, seed, temperature, top_k):
        n = len(prompt)
        bs = self.cfg.kv_block_size
        # A hit is only possible when the extend rungs exist to serve
        # it: the pool's prefix cache and the engine's extend ladder
        # are both keyed off cfg.prefix_cache, so claim_prompt_blocks
        # returns ctx=0 exactly when there is no rung to run a tail on.
        ctx, _ = self.pool.claim_prompt_blocks(slot, prompt)
        total_blocks = -(-n // bs)
        key = request_key(seed, n)
        ftemp, ftk = jnp.float32(temperature), jnp.int32(top_k)
        if ctx == 0:
            bucket = kv_mod.pick_bucket(self.prefill_ladder, n)
            ids = np.zeros((bucket // bs,), np.int32)
            ids[:total_blocks] = self.pool.block_tables[
                slot, :total_blocks
            ]
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :n] = prompt
            kv, tok, last = self._run_compiled(
                "prefill", self._prefill_fns[bucket],
                self.params, self.pool.kv_state(), jnp.asarray(ids),
                jnp.asarray(tokens), jnp.int32(n), key, ftemp, ftk,
            )
        else:
            tail = n - ctx
            tb = kv_mod.pick_bucket(self.prefill_ladder, tail)
            tail_blocks = total_blocks - ctx // bs
            tail_ids = np.zeros((tb // bs,), np.int32)
            tail_ids[:tail_blocks] = self.pool.block_tables[
                slot, ctx // bs:total_blocks
            ]
            tokens = np.zeros((1, tb), np.int32)
            tokens[0, :tail] = prompt[ctx:]
            kv, tok, last = self._run_compiled(
                "prefill", self._extend_fns[tb],
                self.params, self.pool.kv_state(),
                jnp.asarray(self.pool.block_tables[slot]),
                jnp.asarray(tail_ids), jnp.asarray(tokens),
                jnp.int32(ctx), jnp.int32(tail), key, ftemp, ftk,
            )
            self.registry.counter(
                "serving/prefix_reused_tokens"
            ).inc(ctx)
        self.pool.set_kv_state(kv)
        self.pool.insert_prefix(slot, prompt)
        return tok, last

    # --------------------------------- chunked prefill (ISSUE 12 (b))

    def prefill_open(self, slot: int, prompt: Sequence[int], *,
                     seed: int = 0, temperature: float = 0.0,
                     top_k: int = 0):
        """Open a CHUNKED prefill when admission should split this
        prompt (``prefill_chunk_tokens > 0`` and the cold portion
        exceeds it); returns the :class:`ChunkedPrefill` state
        ``prefill_step`` consumes, or None when the prompt needs no
        chunking (the caller uses plain :meth:`prefill`). The slot's
        blocks — reused prefix blocks first — are allocated here
        all-or-nothing, so a ``BlockExhausted`` rejects the request
        before any device work."""
        chunk = self.cfg.prefill_chunk_tokens
        n = len(prompt)
        if chunk <= 0 or not self._extend_fns or n <= chunk:
            return None
        if n > self.model_cfg.max_len:
            raise ValueError(
                f"prompt length {n} exceeds max_len "
                f"{self.model_cfg.max_len}"
            )
        self._prefill_fault_tick(slot)
        from tensorflow_examples_tpu.serving import scheduler

        bs = self.cfg.kv_block_size
        ctx, _ = self.pool.claim_prompt_blocks(slot, prompt)
        if ctx:
            self.registry.counter("serving/prefix_reused_tokens").inc(ctx)
        spans = scheduler.plan_chunks(n, ctx, chunk, bs)
        if len(spans) > 1:
            # Single-span plans (a mostly-cached prompt whose cold tail
            # fits one chunk) are NOT chunked admissions — the batcher
            # runs them inline, exactly like the plain prefix-hit path.
            self.registry.counter("serving/chunked_prefills").inc()
        return ChunkedPrefill(
            slot, [int(t) for t in prompt], spans,
            seed, temperature, top_k,
        )

    def prefill_step(self, state: ChunkedPrefill):
        """Run ONE chunk of an open chunked prefill through the extend
        rung (the chunk attends the already-written context blocks,
        masked to the true covered length, plus itself causally).
        Returns ``(done, first_token, last_logits)`` — the token/logits
        are None until the final chunk, whose sampling key is
        ``request_key(seed, n)``, exactly the unchunked prefill's, so
        the chunked stream is token-identical to the single-shot one
        (test-pinned)."""
        bs = self.cfg.kv_block_size
        slot, prompt = state.slot, state.prompt
        start, end = state.spans[state.idx]
        tail = end - start
        tb = kv_mod.pick_bucket(self.prefill_ladder, tail)
        first_block = start // bs
        last_block = -(-end // bs)
        tail_ids = np.zeros((tb // bs,), np.int32)
        tail_ids[:last_block - first_block] = self.pool.block_tables[
            slot, first_block:last_block
        ]
        tokens = np.zeros((1, tb), np.int32)
        tokens[0, :tail] = prompt[start:end]
        kv, tok, last = self._run_compiled(
            "prefill", self._extend_fns[tb],
            self.params, self.pool.kv_state(),
            jnp.asarray(self.pool.block_tables[slot]),
            jnp.asarray(tail_ids), jnp.asarray(tokens),
            jnp.int32(start), jnp.int32(tail),
            request_key(state.seed, end),
            jnp.float32(state.temperature), jnp.int32(state.top_k),
        )
        self.pool.set_kv_state(kv)
        state.idx += 1
        self.registry.counter("serving/prefill_chunks").inc()
        if state.idx < len(state.spans):
            return False, None, None
        n = len(prompt)
        self.pool.lengths[slot] = n
        self.pool.insert_prefix(slot, prompt)
        self.registry.counter("serving/prefill_tokens").inc(n)
        return True, int(tok), np.asarray(last)

    # ----------------------------------- KV page handoff (ISSUE 12 (c))

    def export_kv_pages(self, slot: int, prompt: Sequence[int], *,
                        skip_tokens: int = 0) -> dict:
        """Serialize the slot's finished prompt KV blocks as the
        prefill->decode handoff payload (``scheduler.encode_pages``
        wire format, quantization scales included). The prefill-role
        half of disaggregated serving: the importer's decode continues
        with numerically identical cache state, so the handed-off
        stream is token-identical to a mixed replica serving the whole
        request.

        ``skip_tokens`` is the streaming DELTA handoff (ISSUE 15
        satellite): the router's digest exchange says the importer
        already caches that many leading prompt tokens, so the leading
        full blocks they cover stay OFF the wire (``start_block``
        meta). Floored to this replica's block multiple and capped so
        at least the final (partial) block always ships."""
        if not self.paged:
            raise ValueError(
                "KV page export requires the paged pool (set "
                "kv_block_size)"
            )
        if skip_tokens < 0:
            raise ValueError(f"skip_tokens={skip_tokens} must be >= 0")
        from tensorflow_examples_tpu.serving import scheduler

        n = len(prompt)
        bs = self.cfg.kv_block_size
        nb = -(-n // bs)
        # Only FULL blocks strictly before the tail are skippable —
        # the same cap prefix_lookup applies to reusable blocks.
        skip = min(int(skip_tokens) // bs, (n - 1) // bs)
        idx = jnp.asarray(
            [int(b) for b in self.pool.block_tables[slot, skip:nb]]
        )
        state = self.pool.kv_state()
        arrays = {
            "k": np.asarray(state[0][:, idx]),
            "v": np.asarray(state[1][:, idx]),
        }
        if self.pool.quantized:
            arrays["k_scale"] = np.asarray(state[2][:, idx])
            arrays["v_scale"] = np.asarray(state[3][:, idx])
        meta = dict(
            block_size=bs,
            num_layers=self.model_cfg.num_layers,
            num_heads=self.model_cfg.num_heads,
            head_dim=self.model_cfg.head_dim,
            length=n,
            kv_bits=self.pool.kv_bits,
            start_block=skip,
        )
        self.registry.counter("serving/kv_pages_exported").inc(nb - skip)
        if skip:
            self.registry.counter(
                "serving/kv_pages_delta_skipped"
            ).inc(skip)
        return scheduler.encode_pages(meta, arrays)

    def import_kv_pages(self, slot: int, payload,
                        prompt: Sequence[int]) -> None:
        """Map a handed-off page payload into ``slot``: validate the
        geometry against this replica's pool (mismatch is a loud
        ValueError -> 400, never a silently wrong cache), claim the
        blocks, scatter the host arrays in, set the slot's length, and
        publish the prompt into the local prefix cache so later
        shared-prefix traffic gains affinity here too. A
        ``BlockExhausted`` propagates before any write (503 upstream)."""
        if not self.paged:
            raise ValueError(
                "KV page import requires the paged pool (set "
                "kv_block_size)"
            )
        from tensorflow_examples_tpu.serving import scheduler

        meta, arrays = scheduler.decode_pages(payload)
        expect = dict(
            block_size=self.cfg.kv_block_size,
            num_layers=self.model_cfg.num_layers,
            num_heads=self.model_cfg.num_heads,
            head_dim=self.model_cfg.head_dim,
            kv_bits=self.pool.kv_bits,
        )
        for key, want in expect.items():
            if meta[key] != want:
                raise ValueError(
                    f"pages geometry mismatch: {key}={meta[key]} but "
                    f"this replica serves {key}={want}"
                )
        n = meta["length"]
        if n != len(prompt):
            raise ValueError(
                f"pages cover {n} tokens but the prompt has "
                f"{len(prompt)}"
            )
        if n > self.model_cfg.max_len:
            raise ValueError(
                f"pages length {n} exceeds max_len "
                f"{self.model_cfg.max_len}"
            )
        bs = self.cfg.kv_block_size
        nb = -(-n // bs)
        # Delta handoff (ISSUE 15 satellite): the payload may start at
        # start_block > 0 — the exporter left off leading blocks the
        # router's digest exchange says this replica already caches
        # (absent on pre-delta payloads: a full export).
        start = meta.get("start_block", 0)
        if start >= nb:
            raise ValueError(
                f"pages start_block={start} but the prompt spans only "
                f"{nb} blocks"
            )
        nb_pages = nb - start
        shapes = {
            "k": (meta["num_layers"], nb_pages, meta["num_heads"], bs,
                  meta["head_dim"]),
            "v": (meta["num_layers"], nb_pages, meta["num_heads"], bs,
                  meta["head_dim"]),
        }
        if self.pool.quantized:
            shapes["k_scale"] = shapes["k"][:-1]
            shapes["v_scale"] = shapes["v"][:-1]
        for name, want_shape in shapes.items():
            arr = arrays.get(name)
            if arr is None:
                raise ValueError(f"pages payload is missing {name!r}")
            if tuple(arr.shape) != want_shape:
                raise ValueError(
                    f"pages array {name!r} has shape "
                    f"{tuple(arr.shape)}, expected {want_shape}"
                )
        state = list(self.pool.kv_state())
        names = ("k", "v", "k_scale", "v_scale")[: len(state)]
        for i, name in enumerate(names):
            # The payload arrays carry the DONOR's cache dtype; a
            # same-width mismatch (f16 pages into a bf16 pool) would
            # value-cast every KV entry — exactly the silently-wrong
            # cache the wire format promises cannot happen. kv_bits
            # catches width; this catches kind.
            want = jnp.dtype(state[i].dtype)
            got = jnp.dtype(arrays[name].dtype)
            if got != want:
                raise ValueError(
                    f"pages dtype mismatch: {name!r} is {got} but "
                    f"this replica's cache stores {want}"
                )
        # Leading blocks this pool ALREADY caches (a previous handoff
        # or local prefill of the same prefix) are mapped, not
        # re-scattered: chained exact-token keys guarantee identical
        # content, so repeated handoffs of a shared system prompt hold
        # one copy and pay the device write only for the cold tail.
        ctx, fresh = self.pool.claim_prompt_blocks(slot, prompt)
        if ctx < start * bs:
            # The delta payload assumes this replica caches the first
            # ``start`` blocks, but the local prefix cache covers only
            # ``ctx`` tokens (evicted since the router's probe, or a
            # stale/bloom-false-positive digest). Loud 400 — the
            # router falls back to the full path, never a torn cache.
            raise ValueError(
                f"delta pages start at block {start} but this "
                f"replica's prefix cache covers only {ctx} of "
                f"{start * bs} skipped tokens — re-send full pages"
            )
        if fresh:
            col = nb - len(fresh) - start  # payload column of fresh[0]
            idx = jnp.asarray(fresh)
            for i, name in enumerate(names):
                state[i] = state[i].at[:, idx].set(
                    jnp.asarray(arrays[name][:, col:])
                )
            self.pool.set_kv_state(tuple(state))
        self.pool.lengths[slot] = n
        self.pool.insert_prefix(slot, prompt)
        self.registry.counter("serving/kv_pages_imported").inc(
            len(fresh)
        )
        if ctx:
            self.registry.counter(
                "serving/prefix_reused_tokens"
            ).inc(ctx)

    # graftlint: hot-path — one bulk np.asarray per step is the budget;
    # any additional host sync lands straight in TPOT (ISSUE 14).
    def decode(self, entries: Sequence[tuple[int, int, int, float, int]]):
        """One continuous-decode step. ``entries`` is the active set:
        (slot, input_token, seed, temperature, top_k) per request —
        every entry's input token sits at cache row
        ``pool.lengths[slot]``. Returns {slot: generated token}."""
        if not entries:
            return {}
        feng = faults_mod.serve_active()
        if feng is not None:
            # Serve-side fault hook (ISSUE 10): may sleep (slowrep),
            # raise a forced BlockExhausted (kvexhaust) or kill this
            # replica's transport and raise InjectedCrash (crash) —
            # all BEFORE any device call, so no donated state is lost
            # to an injected fault.
            feng.decode_step(self.replica_id, [e[0] for e in entries])
        s = self.cfg.max_slots
        tokens = np.zeros((s,), np.int32)
        positions = np.zeros((s,), np.int32)
        temps = np.zeros((s,), np.float32)
        top_ks = np.zeros((s,), np.int32)
        seeds = np.zeros((s,), np.int32)
        slots = []
        for slot, token, seed, temp, tk in entries:
            pos = int(self.pool.lengths[slot])
            tokens[slot] = token
            positions[slot] = pos
            temps[slot] = temp
            top_ks[slot] = tk
            seeds[slot] = seed
            slots.append(slot)
        bucket = kv_mod.pick_bucket(
            self.kv_ladder, int(positions.max(initial=0)) + 1
        )
        if self.paged:
            from tensorflow_examples_tpu.serving import paged_kv

            # Grow block tables BEFORE the device step: an exhaustion
            # here has consumed nothing (no donation yet), so only the
            # requests that could not grow fail — the engine keeps
            # serving the rest (the batcher handles the partition).
            exhausted = []
            for slot in slots:
                try:
                    self.pool.ensure_position(
                        slot, int(positions[slot])
                    )
                except paged_kv.BlockExhausted:
                    exhausted.append(slot)
            if exhausted:
                raise paged_kv.BlockExhausted(
                    "KV block pool exhausted mid-decode for slot(s) "
                    f"{exhausted}; pool is serving at capacity",
                    slots=tuple(exhausted),
                )
            bs = self.cfg.kv_block_size
            tables = np.ascontiguousarray(
                self.pool.block_tables[:, :bucket // bs]
            )
            kv, out = self._run_compiled(
                "decode", self._decode_fns[bucket],
                self.params, self.pool.kv_state(),
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(tables), jnp.asarray(seeds),
                jnp.asarray(temps), jnp.asarray(top_ks),
            )
            self.pool.set_kv_state(kv)
        else:
            self.pool.k, self.pool.v, out = self._run_compiled(
                "decode", self._decode_fns[bucket],
                self.params, self.pool.k, self.pool.v,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(seeds), jnp.asarray(temps),
                jnp.asarray(top_ks),
            )
        out = np.asarray(out)
        for slot in slots:
            self.pool.lengths[slot] += 1
        self.registry.counter("serving/decode_steps").inc()
        self.registry.counter("serving/decode_tokens").inc(len(slots))
        return {slot: int(out[slot]) for slot in slots}

    # graftlint: hot-path — same budget as decode(): the one bulk
    # np.asarray(out) below is the step's accepted device->host sync.
    def verify(self, entries):
        """One SPECULATIVE decode step (ISSUE 11): score each active
        request's launch token plus its draft tokens in one compiled
        ``verify_k`` forward and commit the longest agreeing prefix.

        ``entries``: (slot, input_token, draft_tokens, seed,
        temperature, top_k) per request — the input token sits at cache
        row ``pool.lengths[slot]``, drafts at the rows after it.
        Returns {slot: committed token list} — ALWAYS at least one
        token per entry (the verify-sampled next token; a plain decode
        step would have produced exactly it), plus one more per
        accepted draft (``speculative.accept_drafts``). ``lengths``
        advance by the committed count, so rejected draft rows are
        overwritten by the next step's writes and never attended.
        """
        if not entries:
            return {}
        if not self._verify_fns:
            raise RuntimeError(
                "verify() requires spec_decode_k > 0 (no verify rungs "
                "were compiled)"
            )
        from tensorflow_examples_tpu.serving.speculative import (
            accept_drafts,
        )

        feng = faults_mod.serve_active()
        if feng is not None:
            # Same serve-side fault hook as decode(): a chaos schedule
            # counts speculative steps exactly like plain ones, BEFORE
            # any device call (no donated state lost to a fault).
            feng.decode_step(self.replica_id, [e[0] for e in entries])
        s = self.cfg.max_slots
        t_n = self.cfg.spec_decode_k + 1
        max_len = self.model_cfg.max_len
        tokens = np.zeros((s, t_n), np.int32)
        positions = np.zeros((s,), np.int32)
        temps = np.zeros((s,), np.float32)
        top_ks = np.zeros((s,), np.int32)
        seeds = np.zeros((s,), np.int32)
        slots: list[int] = []
        drafts_by_slot: dict[int, list[int]] = {}
        limits: dict[int, int] = {}
        for slot, token, drafts, seed, temp, tk in entries:
            pos = int(self.pool.lengths[slot])
            drafts = [int(d) for d in drafts][: self.cfg.spec_decode_k]
            tokens[slot, 0] = token
            tokens[slot, 1:1 + len(drafts)] = drafts
            positions[slot] = pos
            temps[slot] = temp
            top_ks[slot] = tk
            seeds[slot] = seed
            slots.append(slot)
            drafts_by_slot[slot] = drafts
            # Committed rows must have landed in the cache: the dense
            # extent caps them at max_len (rows past it were dropped).
            limits[slot] = max_len - pos
        bucket = kv_mod.pick_bucket(
            self.kv_ladder,
            min(int(positions.max(initial=0)) + t_n, max_len),
        )
        if self.paged:
            from tensorflow_examples_tpu.serving import paged_kv

            exhausted = []
            for slot in slots:
                pos = int(positions[slot])
                try:
                    self.pool.ensure_position(
                        slot, min(pos + t_n - 1, max_len - 1)
                    )
                except paged_kv.BlockExhausted:
                    # Shrink the spec window before shedding anything:
                    # the NON-speculative requirement is one row.
                    try:
                        self.pool.ensure_position(slot, pos)
                    except paged_kv.BlockExhausted:
                        exhausted.append(slot)
                        continue
                limits[slot] = min(
                    limits[slot],
                    self.pool.covered_positions(slot) - pos,
                )
            if exhausted:
                raise paged_kv.BlockExhausted(
                    "KV block pool exhausted mid-decode for slot(s) "
                    f"{exhausted}; pool is serving at capacity",
                    slots=tuple(exhausted),
                )
            bs = self.cfg.kv_block_size
            tables = np.ascontiguousarray(
                self.pool.block_tables[:, :bucket // bs]
            )
            kv, out = self._run_compiled(
                "verify", self._verify_fns[bucket],
                self.params, self.pool.kv_state(),
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(tables), jnp.asarray(seeds),
                jnp.asarray(temps), jnp.asarray(top_ks),
            )
            self.pool.set_kv_state(kv)
        else:
            self.pool.k, self.pool.v, out = self._run_compiled(
                "verify", self._verify_fns[bucket],
                self.params, self.pool.k, self.pool.v,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(seeds), jnp.asarray(temps),
                jnp.asarray(top_ks),
            )
        out = np.asarray(out)
        committed: dict[int, list[int]] = {}
        total = drafted = accepted = 0
        for slot in slots:
            toks = accept_drafts(
                drafts_by_slot[slot], out[slot], limit=limits[slot]
            )
            committed[slot] = toks
            self.pool.lengths[slot] += len(toks)
            total += len(toks)
            drafted += len(drafts_by_slot[slot])
            accepted += len(toks) - 1
        reg = self.registry
        reg.counter("serving/decode_steps").inc()
        reg.counter("serving/decode_tokens").inc(total)
        reg.counter("serving/spec_steps").inc()
        reg.counter("serving/spec_request_steps").inc(len(slots))
        reg.counter("serving/spec_drafted_total").inc(drafted)
        reg.counter("serving/spec_accepted_total").inc(accepted)
        return committed

    # ------------------------------------------------------- references

    def _reference_step(self):
        """One jitted (params, tokens[1, max_len], length, key, temp,
        top_k) -> (sampled token, last-row logits) step for the
        reference replay. Always the full ``max_len`` shape — rows past
        ``length`` hold zeros that causal masking makes inert, so ONE
        compile covers every prefix length and the replay is not
        eager-dispatch-bound. Deliberately NOT sentinel-wrapped: the
        reference is test/verify machinery, never the serving path, and
        must not count against the zero-recompile budget."""
        if self._ref_fwd is None:
            def step(params, tokens, length, key, temp, top_k):
                logits, _, _ = forward_full(
                    self.model_cfg, params, tokens, impl="xla"
                )
                last = jax.lax.dynamic_index_in_dim(
                    logits[0], length - 1, keepdims=False
                )
                return _sample_row(key, last, temp, top_k), last

            self._ref_fwd = jax.jit(step)
        return self._ref_fwd

    def _reference_last(self, toks: list[int], *, seed: int,
                        temperature: float, top_k: int):
        padded = np.zeros((1, self.model_cfg.max_len), np.int32)
        padded[0, :len(toks)] = toks
        return self._reference_step()(
            self.params, jnp.asarray(padded), jnp.int32(len(toks)),
            request_key(seed, len(toks)), jnp.float32(temperature),
            jnp.int32(top_k),
        )

    def reference_generate(self, prompt: Sequence[int], *, max_new: int,
                           seed: int = 0, temperature: float = 0.0,
                           top_k: int = 0, eos_id: int | None = None):
        """The unbatched, cacheless replay of one request: a full
        forward of the whole prefix per emitted token, sampling with
        the same (seed, position) keys. O(n^2) on purpose — it shares
        no batching, bucketing, or KV-cache machinery with the serving
        path, which is what makes the continuous-batching golden
        comparison meaningful."""
        toks = [int(t) for t in prompt]
        out: list[int] = []
        for _ in range(max_new):
            tok, _ = self._reference_last(
                toks, seed=seed, temperature=temperature, top_k=top_k
            )
            nxt = int(tok)
            out.append(nxt)
            toks.append(nxt)
            if eos_id is not None and nxt == eos_id:
                break
        return out

    def reference_classify(self, prompt: Sequence[int], *, top_n: int = 5):
        _, last = self._reference_last(
            [int(t) for t in prompt], seed=0, temperature=0.0, top_k=0
        )
        return top_logprobs(np.asarray(last), top_n)


def top_logprobs(logits: np.ndarray, top_n: int) -> list[dict]:
    """Next-token distribution head: top-n (token, logprob) pairs."""
    x = logits.astype(np.float64)
    logz = np.log(np.sum(np.exp(x - x.max()))) + x.max()
    order = np.argsort(x)[::-1][:top_n]
    return [
        {"token": int(t), "logprob": float(x[t] - logz)} for t in order
    ]
