"""The compiled serving step: bucketed prefill + fixed-shape decode.

Shape discipline is the whole design (SURVEY.md's "as fast as the
hardware allows" applied to inference): XLA recompiles on any new
abstract shape, and a serving process that compiles mid-traffic turns
a p50 of milliseconds into a p95 of seconds. So every program the
engine runs comes from a FINITE, warmed-up ladder:

* **Prefill** pads each prompt to the smallest power-of-two length
  bucket (``ServeConfig.prefill_bucket_floor`` up to the model's
  ``max_len``) and runs batch-1: one compiled program per rung.
  Causal masking makes the pad rows inert — the true prompt length
  rides in as a traced scalar that only picks the logits row and the
  cache write extent.
* **Decode** always runs the full ``[max_slots]`` batch — continuous
  batching means the batch composition changes every step, so the
  batch *shape* must not. Per-slot state (token, position, sampling
  key/temperature/top-k) rides in as traced vectors; the KV cache is
  sliced to the smallest power-of-two bucket covering the longest
  active request (``kv_bucket_floor`` ladder), so short-context steps
  read O(bucket) cache bytes — the serving-side mirror of
  ``ops/decode.flash_decode_attention``'s populated-prefix ladder,
  which the prefill path reuses directly under ``attention="flash"``
  (its scalar-length contract matches prefill exactly; the per-slot
  length *vector* of continuous decode is what
  ``kv_cache.varlen_decode_attention`` generalizes).

``warmup()`` compiles the entire ladder ahead of traffic (the
AOT-compiled serving path: every program exists before the first
request) and every compiled variant is wrapped in the PR-3
``CompilationSentinel`` — a post-warmup recompile is a WARNING naming
the exact shape delta, and ``post_warmup_recompiles()`` is the number
CI asserts to be zero (tools/serve_bench.py banks it in the bench
record).

The forward math operates directly on the ``models/transformer.py``
param tree (same names: wte/wpe/h_i/ln_f) rather than through flax
``Transformer.apply``: the flax decode path keys the whole batch off
one scalar cache index, which continuous batching cannot use. Parity
with the flax model is pinned by tests/test_serving.py (engine vs
``transformer.generate`` greedy decode, token-identical).

Sampling reuses ``models.transformer.sample_tokens``'s exact math with
per-request keys (``fold_in(PRNGKey(seed), absolute_position)``), so a
request's tokens are a pure function of (params, prompt, seed) — the
batch it happened to be coalesced into cannot change its output, which
is what makes the continuous-batching golden test meaningful.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_examples_tpu.models.transformer import TransformerConfig
from tensorflow_examples_tpu.ops.attention import NEG_INF, attention_reference
from tensorflow_examples_tpu.serving import kv_cache as kv_mod
from tensorflow_examples_tpu.telemetry import registry as registry_mod
from tensorflow_examples_tpu.telemetry.compilation import CompilationSentinel

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine + batcher knobs (one object configures the whole stack)."""

    max_slots: int = 8           # concurrent requests = decode batch shape
    prefill_bucket_floor: int = 16
    kv_bucket_floor: int = 64
    attention: str = "xla"       # xla | flash (flash: Pallas prefill attend)
    cache_dtype: str = ""        # "" -> follow the params dtype
    compile_warmup: int = 1      # expected compiles per sentinel-wrapped fn
    # ---- continuous batcher (serving/batcher.py) ----
    max_batch: int = 0           # admission cap; 0 -> max_slots
    max_queue: int = 64          # bounded queue: beyond this, load-shed
    max_delay_s: float = 0.002   # idle coalescing window before first prefill
    watchdog_secs: float = 0.0   # 0 disables the serve-loop watchdog
    # ---- frontend ----
    request_timeout_s: float = 120.0


# --------------------------------------------------------------- forward
#
# Pure functions over the Transformer param tree. f32-by-default like the
# flax model (params dtype is the compute dtype); LayerNorm/softmax math
# mirrors flax defaults (eps 1e-5, gelu approximate).


def _layer_norm(x, p, eps=1e-5):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]


def _block_mlp(x, p):
    h = jnp.dot(x, p["mlp_fc"]["kernel"]) + p["mlp_fc"]["bias"]
    h = jax.nn.gelu(h, approximate=True)
    return jnp.dot(h, p["mlp_proj"]["kernel"]) + p["mlp_proj"]["bias"]


def _qkv(x, p):
    """[..., d] -> q, k, v each [..., H, hd]."""
    y = jnp.einsum("...d,dthc->...thc", x, p["qkv"]["kernel"])
    y = y + p["qkv"]["bias"]
    return y[..., 0, :, :], y[..., 1, :, :], y[..., 2, :, :]


def _attn_out(att, p):
    """[..., H, hd] attention output -> [..., d] residual contribution."""
    return jnp.einsum("...hc,hcd->...d", att, p["proj"]["kernel"]) + p[
        "proj"
    ]["bias"]


def _prefill_attend(q, k, v, *, impl: str):
    """Causal self-attention for prefill, [B, L, H, hd] layout.

    ``impl="flash"`` reuses ``ops/decode.flash_decode_attention`` with
    its exact contract: the freshly-computed K/V ARE the populated
    cache and the static bucket length is the scalar ``length`` — a
    prefill is precisely the single-length case of cache attention.
    """
    swap = lambda t: t.transpose(0, 2, 1, 3)  # [B,L,H,D] -> [B,H,L,D]
    if impl == "flash":
        from tensorflow_examples_tpu.ops.decode import flash_decode_attention

        out = flash_decode_attention(swap(q), swap(k), swap(v), q.shape[1])
    else:
        out = attention_reference(swap(q), swap(k), swap(v), causal=True)
    return swap(out)


def forward_full(cfg: TransformerConfig, params, tokens, *, impl="xla"):
    """Full causal forward of ``tokens`` [B, L]: logits [B, L, V] plus
    the per-layer K/V ([2, num_layers, B, H, L, hd]) the prefill path
    writes into the cache. Also the engine's cacheless reference path
    (which recomputes attention over the whole prefix per emitted
    token)."""
    wte = params["wte"]["embedding"]
    positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)
    x = wte[tokens] + params["wpe"]["embedding"][positions][None]
    ks, vs = [], []
    for layer in range(cfg.num_layers):
        p = params[f"h_{layer}"]
        y = _layer_norm(x, p["ln_1"])
        q, k, v = _qkv(y, p["attn"])
        ks.append(k)
        vs.append(v)
        x = x + _attn_out(_prefill_attend(q, k, v, impl=impl), p["attn"])
        x = x + _block_mlp(_layer_norm(x, p["ln_2"]), p)
    x = _layer_norm(x, params["ln_f"])
    return jnp.dot(x, wte.T), jnp.stack(ks), jnp.stack(vs)


def _decode_forward(cfg: TransformerConfig, params, k_cache, v_cache,
                    tokens, positions, *, kv_bucket: int):
    """One continuous-decode step over every slot.

    tokens/positions: [S] — each slot's input token and the cache row
    it occupies (= the slot's pre-step populated length). Returns the
    updated caches and next-token logits [S, V]. Slots not actively
    decoding ride along with position 0: their write lands in a row a
    future prefill fully overwrites, and their output is discarded.
    """
    wte = params["wte"]["embedding"]
    x = wte[tokens] + params["wpe"]["embedding"][positions]
    idx = jnp.arange(tokens.shape[0])
    lengths = positions + 1  # populated length including the new token
    for layer in range(cfg.num_layers):
        p = params[f"h_{layer}"]
        y = _layer_norm(x, p["ln_1"])
        q, k, v = _qkv(y, p["attn"])  # [S, H, hd]
        k_cache = k_cache.at[layer, idx, :, positions, :].set(
            k.astype(k_cache.dtype)
        )
        v_cache = v_cache.at[layer, idx, :, positions, :].set(
            v.astype(v_cache.dtype)
        )
        att = kv_mod.varlen_decode_attention(
            q,
            jax.lax.slice_in_dim(k_cache[layer], 0, kv_bucket, axis=2),
            jax.lax.slice_in_dim(v_cache[layer], 0, kv_bucket, axis=2),
            lengths,
        )
        x = x + _attn_out(att, p["attn"])
        x = x + _block_mlp(_layer_norm(x, p["ln_2"]), p)
    x = _layer_norm(x, params["ln_f"])
    return k_cache, v_cache, jnp.dot(x, wte.T)


# -------------------------------------------------------------- sampling


def _sample_row(key, logits, temp, top_k):
    """Traced-knob clone of ``models.transformer.sample_tokens`` for ONE
    row: temperature/top_k arrive as arrays (a batch mixes settings), so
    the static ``if``s become selects — same math, same keys, identical
    tokens (tests pin it)."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.where(temp > 0, temp, 1.0)
    kth = jax.lax.dynamic_index_in_dim(
        jnp.sort(scaled),
        jnp.maximum(scaled.shape[0] - top_k, 0),
        keepdims=False,
    )
    filtered = jnp.where(
        (top_k > 0) & (scaled < kth), NEG_INF, scaled
    )
    sampled = jax.random.categorical(key, filtered).astype(jnp.int32)
    return jnp.where(temp == 0.0, greedy, sampled)


_sample_batch = jax.vmap(_sample_row)


def request_key(seed: int, position: int) -> jax.Array:
    """The per-token sampling key: a pure function of (request seed,
    absolute position), so batched serving and the unbatched reference
    replay draw identical samples."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), position)


# Vmapped over per-slot (seed, position) vectors INSIDE the jitted
# decode step — eager per-slot fold_in dispatches on the batcher loop
# thread would sit between consecutive compiled decode steps, exactly
# where TPOT is won or lost. Seeds are int32 (the frontend caps them at
# 2**31 - 1) so the traced PRNGKey seeding matches the eager replay's.
_request_key_batch = jax.vmap(request_key)


# ---------------------------------------------------------------- engine


class EngineStepError(RuntimeError):
    """A compiled prefill/decode step failed at runtime. The KV caches
    were donated to the failed call (consumed on donation-honoring
    backends), so the engine has already reallocated them — every
    in-flight request's cache state is gone and the batcher must fail
    the whole active set, not just the request being stepped."""


class InferenceEngine:
    """Loads params once, owns the KV pool, runs the compiled steps.

    Device-facing methods (``prefill`` / ``decode`` / ``warmup``) are
    single-threaded by contract — the continuous batcher's loop thread
    is the only caller. ``submit``-side concurrency lives in
    serving/batcher.py.
    """

    def __init__(
        self,
        model_cfg: TransformerConfig,
        params,
        *,
        cfg: ServeConfig | None = None,
        registry=None,
        sharding=None,
    ):
        if model_cfg.moe_experts:
            raise NotImplementedError(
                "serving engine currently covers dense GPT-2 models only"
            )
        if model_cfg.attention not in ("xla", "flash"):
            # ring/ulysses are training-side context-parallel impls.
            raise ValueError(
                f"model attention={model_cfg.attention!r}; the serving "
                "forward supports 'xla' or 'flash'"
            )
        self.model_cfg = model_cfg
        self.cfg = cfg or ServeConfig()
        if self.cfg.attention not in ("xla", "flash"):
            raise ValueError(
                f"ServeConfig.attention={self.cfg.attention!r} not in "
                "('xla', 'flash')"
            )
        # Sharded serving (ISSUE 7): the SAME ShardingConfig training
        # persisted to workdir/sharding.json places the param tree by
        # its rules (instead of replicating) and the KV pool with heads
        # over `model`; GSPMD inserts the TP collectives into the
        # already-compiled prefill/decode ladder, so the zero-recompile
        # contract is untouched — the ladder is warmed with the
        # sharded placements it will serve with. sharding=None keeps
        # today's single-device placement exactly.
        self.sharding = sharding
        self.mesh = None
        self.param_sharding_digest = None
        if sharding is None:
            params = jax.tree.map(jnp.asarray, params)
        else:
            # No asarray pre-pass: shard_params device_puts the host
            # tree straight into the mesh layout — a model that only
            # fits sharded must never materialize on device 0 first.
            from tensorflow_examples_tpu.core.sharding import shard_params
            from tensorflow_examples_tpu.models.transformer import (
                GPT2_RULES,
            )
            from tensorflow_examples_tpu.sharding import resolve_params

            self.mesh = sharding.build_mesh()
            rules = sharding.sharding_rules(default=GPT2_RULES)
            params = shard_params(params, self.mesh, rules)
            self.param_sharding_digest = resolve_params(
                params, self.mesh, rules
            ).digest()
        self.params = params
        self.registry = (
            registry if registry is not None
            else registry_mod.default_registry()
        )
        self.sentinel = CompilationSentinel(
            warmup=self.cfg.compile_warmup, registry=self.registry
        )
        param_dtype = self.params["wte"]["embedding"].dtype
        cache_dtype = (
            jnp.dtype(self.cfg.cache_dtype)
            if self.cfg.cache_dtype
            else param_dtype
        )
        self.pool = kv_mod.KVCachePool(
            num_layers=model_cfg.num_layers,
            num_slots=self.cfg.max_slots,
            num_heads=model_cfg.num_heads,
            max_len=model_cfg.max_len,
            head_dim=model_cfg.head_dim,
            dtype=cache_dtype,
            registry=self.registry,
            sharding=self._kv_sharding(),
        )
        self.prefill_ladder = kv_mod.bucket_ladder(
            self.cfg.prefill_bucket_floor, model_cfg.max_len
        )
        self.kv_ladder = kv_mod.bucket_ladder(
            self.cfg.kv_bucket_floor, model_cfg.max_len
        )
        # The KV caches are donated (args 1/2 after partial binds the
        # bucket): both steps return the updated caches and the pool
        # unconditionally reassigns from the outputs, so XLA can alias
        # in place instead of copying two [L, slots, H, max_len, D]
        # buffers per generated token. Backends without donation
        # support just ignore the hint.
        self._prefill_fns = {
            lb: self.sentinel.wrap(
                jax.jit(
                    functools.partial(self._prefill_impl, lb),
                    donate_argnums=(1, 2),
                ),
                f"serve_prefill_L{lb}",
            )
            for lb in self.prefill_ladder
        }
        self._decode_fns = {
            kb: self.sentinel.wrap(
                jax.jit(
                    functools.partial(self._decode_impl, kb),
                    donate_argnums=(1, 2),
                ),
                f"serve_decode_K{kb}",
            )
            for kb in self.kv_ladder
        }
        self.warmed = False
        self._ref_fwd = None

    def _kv_sharding(self):
        """KV-pool NamedSharding from the ShardingConfig: heads (dim 2
        of [L, S, H, max_len, D]) shard over ``model`` — the layout
        that keeps per-slot attention local to the head shard the qkv
        projection already produced. A head count the model axis
        doesn't divide replicates instead (placement is an
        optimization, never a shape contract). None without a config
        (single-device placement, the pre-ISSUE-7 behavior)."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tensorflow_examples_tpu.core.mesh import AxisNames

        m = int(self.mesh.shape[AxisNames.MODEL])
        heads = (
            AxisNames.MODEL
            if m > 1 and self.model_cfg.num_heads % m == 0
            else None
        )
        return NamedSharding(self.mesh, P(None, None, heads, None, None))

    # ----------------------------------------------------- compiled fns

    def _prefill_impl(self, bucket, params, k_cache, v_cache, slot,
                      tokens, length, key, temp, top_k):
        """tokens [1, bucket] (right-padded), length = true prompt len.
        Writes the slot's cache rows [0, bucket) (pad rows carry
        garbage K/V that per-slot length masking never reads), samples
        the first generated token from the logits at row length-1."""
        del bucket  # static: encoded in tokens.shape
        logits, ks, vs = forward_full(
            self.model_cfg, params, tokens, impl=self.cfg.attention
        )
        # [L, 1, bucket, H, hd] -> [L, 1, H, bucket, hd] cache layout.
        kstack = ks.transpose(0, 1, 3, 2, 4).astype(k_cache.dtype)
        vstack = vs.transpose(0, 1, 3, 2, 4).astype(v_cache.dtype)
        start = (0, slot.astype(jnp.int32), 0, 0, 0)
        k_cache = jax.lax.dynamic_update_slice(k_cache, kstack, start)
        v_cache = jax.lax.dynamic_update_slice(v_cache, vstack, start)
        last = jax.lax.dynamic_index_in_dim(
            logits[0], length - 1, keepdims=False
        )
        return k_cache, v_cache, _sample_row(key, last, temp, top_k), last

    def _decode_impl(self, bucket, params, k_cache, v_cache, tokens,
                     positions, seeds, temps, top_ks):
        k_cache, v_cache, logits = _decode_forward(
            self.model_cfg, params, k_cache, v_cache, tokens, positions,
            kv_bucket=bucket,
        )
        # The sampled token lands at sequence index position + 1.
        keys = _request_key_batch(seeds, positions + 1)
        return k_cache, v_cache, _sample_batch(keys, logits, temps, top_ks)

    # --------------------------------------------------------- lifecycle

    def warmup(self) -> dict[str, int]:
        """Compile the full bucket ladder ahead of traffic (the AOT
        pass). Returns per-fn compile counts; after this, any further
        compile is a sentinel-warned recompile and
        ``post_warmup_recompiles()`` counts it."""
        s = self.cfg.max_slots
        zero = jnp.zeros((), jnp.int32)
        key = jax.random.PRNGKey(0)
        ftemp = jnp.float32(0.0)
        for lb in self.prefill_ladder:
            self.pool.k, self.pool.v, tok, _ = self._prefill_fns[lb](
                self.params, self.pool.k, self.pool.v, zero,
                jnp.zeros((1, lb), jnp.int32), zero + 1, key, ftemp, zero,
            )
            tok.block_until_ready()
        for kb in self.kv_ladder:
            self.pool.k, self.pool.v, toks = self._decode_fns[kb](
                self.params, self.pool.k, self.pool.v,
                jnp.zeros((s,), jnp.int32), jnp.zeros((s,), jnp.int32),
                jnp.zeros((s,), jnp.int32), jnp.zeros((s,), jnp.float32),
                jnp.zeros((s,), jnp.int32),
            )
            toks.block_until_ready()
        self.pool.reset()
        self.warmed = True
        counts = self.sentinel.compile_counts()
        log.info(
            "serving engine warm: %d compiled programs (%s)",
            sum(counts.values()),
            ", ".join(sorted(counts)),
        )
        return counts

    def expected_compiles(self) -> int:
        return len(self.prefill_ladder) + len(self.kv_ladder)

    def post_warmup_recompiles(self) -> int:
        """Total compiles beyond each variant's warmup allowance — the
        number that must be 0 in steady state (CI asserts it)."""
        return self.sentinel.post_warmup_recompiles()

    # ------------------------------------------------------ request ops

    def prefill(self, slot: int, prompt: Sequence[int], *, seed: int = 0,
                temperature: float = 0.0, top_k: int = 0):
        """Run a prompt into ``slot``; returns (first generated token,
        last-position logits as numpy — the classify payload)."""
        n = len(prompt)
        if n < 1:
            raise ValueError("empty prompt")
        if n > self.model_cfg.max_len:
            raise ValueError(
                f"prompt length {n} exceeds max_len {self.model_cfg.max_len}"
            )
        bucket = kv_mod.pick_bucket(self.prefill_ladder, n)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = prompt
        try:
            self.pool.k, self.pool.v, tok, last = self._prefill_fns[bucket](
                self.params, self.pool.k, self.pool.v,
                jnp.int32(slot), jnp.asarray(tokens), jnp.int32(n),
                request_key(seed, n), jnp.float32(temperature),
                jnp.int32(top_k),
            )
        except Exception as e:
            self.pool.reallocate()
            raise EngineStepError(
                f"compiled prefill step failed (KV caches reallocated): "
                f"{type(e).__name__}: {e}"
            ) from e
        self.pool.lengths[slot] = n
        self.registry.counter("serving/prefill_tokens").inc(n)
        return int(tok), np.asarray(last)

    def decode(self, entries: Sequence[tuple[int, int, int, float, int]]):
        """One continuous-decode step. ``entries`` is the active set:
        (slot, input_token, seed, temperature, top_k) per request —
        every entry's input token sits at cache row
        ``pool.lengths[slot]``. Returns {slot: generated token}."""
        if not entries:
            return {}
        s = self.cfg.max_slots
        tokens = np.zeros((s,), np.int32)
        positions = np.zeros((s,), np.int32)
        temps = np.zeros((s,), np.float32)
        top_ks = np.zeros((s,), np.int32)
        seeds = np.zeros((s,), np.int32)
        slots = []
        for slot, token, seed, temp, tk in entries:
            pos = int(self.pool.lengths[slot])
            tokens[slot] = token
            positions[slot] = pos
            temps[slot] = temp
            top_ks[slot] = tk
            seeds[slot] = seed
            slots.append(slot)
        bucket = kv_mod.pick_bucket(
            self.kv_ladder, int(positions.max(initial=0)) + 1
        )
        try:
            self.pool.k, self.pool.v, out = self._decode_fns[bucket](
                self.params, self.pool.k, self.pool.v,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(seeds), jnp.asarray(temps),
                jnp.asarray(top_ks),
            )
        except Exception as e:
            self.pool.reallocate()
            raise EngineStepError(
                f"compiled decode step failed (KV caches reallocated): "
                f"{type(e).__name__}: {e}"
            ) from e
        out = np.asarray(out)
        for slot in slots:
            self.pool.lengths[slot] += 1
        self.registry.counter("serving/decode_steps").inc()
        self.registry.counter("serving/decode_tokens").inc(len(slots))
        return {slot: int(out[slot]) for slot in slots}

    # ------------------------------------------------------- references

    def _reference_step(self):
        """One jitted (params, tokens[1, max_len], length, key, temp,
        top_k) -> (sampled token, last-row logits) step for the
        reference replay. Always the full ``max_len`` shape — rows past
        ``length`` hold zeros that causal masking makes inert, so ONE
        compile covers every prefix length and the replay is not
        eager-dispatch-bound. Deliberately NOT sentinel-wrapped: the
        reference is test/verify machinery, never the serving path, and
        must not count against the zero-recompile budget."""
        if self._ref_fwd is None:
            def step(params, tokens, length, key, temp, top_k):
                logits, _, _ = forward_full(
                    self.model_cfg, params, tokens, impl="xla"
                )
                last = jax.lax.dynamic_index_in_dim(
                    logits[0], length - 1, keepdims=False
                )
                return _sample_row(key, last, temp, top_k), last

            self._ref_fwd = jax.jit(step)
        return self._ref_fwd

    def _reference_last(self, toks: list[int], *, seed: int,
                        temperature: float, top_k: int):
        padded = np.zeros((1, self.model_cfg.max_len), np.int32)
        padded[0, :len(toks)] = toks
        return self._reference_step()(
            self.params, jnp.asarray(padded), jnp.int32(len(toks)),
            request_key(seed, len(toks)), jnp.float32(temperature),
            jnp.int32(top_k),
        )

    def reference_generate(self, prompt: Sequence[int], *, max_new: int,
                           seed: int = 0, temperature: float = 0.0,
                           top_k: int = 0, eos_id: int | None = None):
        """The unbatched, cacheless replay of one request: a full
        forward of the whole prefix per emitted token, sampling with
        the same (seed, position) keys. O(n^2) on purpose — it shares
        no batching, bucketing, or KV-cache machinery with the serving
        path, which is what makes the continuous-batching golden
        comparison meaningful."""
        toks = [int(t) for t in prompt]
        out: list[int] = []
        for _ in range(max_new):
            tok, _ = self._reference_last(
                toks, seed=seed, temperature=temperature, top_k=top_k
            )
            nxt = int(tok)
            out.append(nxt)
            toks.append(nxt)
            if eos_id is not None and nxt == eos_id:
                break
        return out

    def reference_classify(self, prompt: Sequence[int], *, top_n: int = 5):
        _, last = self._reference_last(
            [int(t) for t in prompt], seed=0, temperature=0.0, top_k=0
        )
        return top_logprobs(np.asarray(last), top_n)


def top_logprobs(logits: np.ndarray, top_n: int) -> list[dict]:
    """Next-token distribution head: top-n (token, logprob) pairs."""
    x = logits.astype(np.float64)
    logz = np.log(np.sum(np.exp(x - x.max()))) + x.max()
    order = np.argsort(x)[::-1][:top_n]
    return [
        {"token": int(t), "logprob": float(x[t] - logz)} for t in order
    ]
