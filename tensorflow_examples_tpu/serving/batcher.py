"""Continuous-batching request queue over the inference engine.

The throughput story of serving (the "serves heavy traffic" half of the
ROADMAP north star) is batching; the latency story is NOT waiting for a
full batch. Continuous batching does both: the decode step always runs
at the engine's fixed ``[max_slots]`` shape, and requests join (prefill
into a free slot) and leave (retire at EOS/limit) BETWEEN steps — a new
request never waits for the current batch to finish, a finished request
never makes the batch wait.

Flow control, outermost first:

* **Backpressure**: the submit queue is bounded (``max_queue``). A full
  queue sheds the request immediately (:class:`QueueFull`, counted in
  ``serving/shed_total``) — the caller gets a 503 now instead of a
  timeout later, and the queue can never grow without bound.
* **Admission control**: a request whose prompt+generation budget
  cannot fit the model's ``max_len`` is rejected up front
  (``serving/rejected_total``); one whose deadline already passed while
  queued is expired without touching the device
  (``serving/expired_total``).
* **Coalescing**: from idle, the first arrival opens a ``max_delay_s``
  window so a burst prefills together before the first decode step;
  under load, admission happens opportunistically between decode steps
  with no added delay. ``max_batch`` caps concurrency below the slot
  count when wanted.
* **Deadlines**: a request past its deadline mid-generation retires
  early with what it has (``truncated="deadline"``).

Latency accounting (the histograms the frontend's ``/metrics`` renders,
all ``registry.TimeHistogram``): ``serving/queue_wait`` (submit ->
admitted), ``serving/prefill`` (prefill wall), ``serving/ttft``
(submit -> first token), ``serving/tpot`` (per generated token decode
wall), ``serving/e2e`` (submit -> done).

The loop runs on one daemon thread; a ``utils.diagnostics.Watchdog``
(``watchdog_secs > 0``) gets phase markers (``serve_idle`` /
``serve_admit`` / ``serve_prefill`` / ``serve_decode``) so a wedged
device step is attributed exactly like a training-loop hang.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import logging
import queue
import threading
import time

from tensorflow_examples_tpu.serving.engine import EngineStepError
from tensorflow_examples_tpu.serving.paged_kv import BlockExhausted
from tensorflow_examples_tpu.telemetry import registry as registry_mod
from tensorflow_examples_tpu.telemetry import schema
from tensorflow_examples_tpu.telemetry.spans import span

log = logging.getLogger(__name__)


class QueueFull(RuntimeError):
    """Bounded submit queue is full: request load-shed (HTTP 503)."""


class Draining(RuntimeError):
    """Batcher is draining for shutdown: new requests rejected (503)."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before any token was produced."""


@dataclasses.dataclass
class Request:
    """One generate/classify request (token ids in, token ids out)."""

    prompt: list[int]
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0
    eos_id: int | None = None
    deadline_s: float | None = None  # relative to submit time
    kind: str = "generate"           # generate | classify
    classify_top_n: int = 5


@dataclasses.dataclass
class Result:
    """Resolved request payload (the frontend serializes this)."""

    tokens: list[int]               # generated tokens (generate)
    prompt_len: int
    top: list[dict] | None = None   # classify payload
    truncated: str | None = None  # None | "deadline" | "max_len" | "shutdown"
    queue_wait_s: float = 0.0
    ttft_s: float | None = None
    total_s: float = 0.0


class _InFlight:
    __slots__ = (
        "req", "future", "slot", "t_submit", "t_admit", "t_first",
        "deadline", "tokens", "last_token",
    )

    def __init__(self, req: Request, future, t_submit: float):
        self.req = req
        self.future = future
        self.slot: int | None = None
        self.t_submit = t_submit
        self.t_admit: float | None = None
        self.t_first: float | None = None
        self.deadline = (
            t_submit + req.deadline_s
            if req.deadline_s is not None else None
        )
        self.tokens: list[int] = []
        self.last_token: int | None = None


class ContinuousBatcher:
    def __init__(self, engine, *, registry=None, watchdog=None):
        self.engine = engine
        cfg = engine.cfg
        self.max_batch = min(
            cfg.max_batch or cfg.max_slots, cfg.max_slots
        )
        self.max_delay_s = cfg.max_delay_s
        self.registry = (
            registry if registry is not None else engine.registry
        )
        self._q: queue.Queue[_InFlight] = queue.Queue(
            maxsize=cfg.max_queue
        )
        self._active: dict[int, _InFlight] = {}
        # Requests the loop has dequeued but not yet admitted into
        # _active (mid-prefill). close(drain=True)'s poll must count
        # them or a drain landing in that window truncates an accepted
        # request. Single-writer (the loop thread); int reads are
        # atomic under the GIL.
        self._staged = 0
        self._draining = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._start_unix = time.time()
        self._watchdog = watchdog
        if watchdog is None and cfg.watchdog_secs > 0:
            from tensorflow_examples_tpu.utils.diagnostics import Watchdog

            self._watchdog = Watchdog(
                cfg.watchdog_secs,
                fatal_timeout_s=4 * cfg.watchdog_secs,
            )

    # ------------------------------------------------------------ intake

    def submit(self, req: Request) -> concurrent.futures.Future:
        """Enqueue; resolves to :class:`Result`. Raises
        :class:`Draining`/:class:`QueueFull` instead of queueing when
        the request can never be served promptly, and fails the future
        fast on admission-impossible requests."""
        reg = self.registry
        reg.counter("serving/requests_total").inc()
        if self._draining or self._stop.is_set():
            reg.counter("serving/rejected_total").inc()
            raise Draining("serving is draining; retry against a live host")
        fut: concurrent.futures.Future = concurrent.futures.Future()
        item = _InFlight(req, fut, time.monotonic())
        budget = len(req.prompt) + (
            req.max_new_tokens if req.kind == "generate" else 0
        )
        if req.kind not in ("generate", "classify"):
            fut.set_exception(ValueError(f"unknown kind {req.kind!r}"))
            reg.counter("serving/rejected_total").inc()
            return fut
        if not req.prompt or budget > self.engine.model_cfg.max_len:
            fut.set_exception(
                ValueError(
                    f"prompt ({len(req.prompt)}) + max_new_tokens must fit "
                    f"1..max_len={self.engine.model_cfg.max_len}"
                )
            )
            reg.counter("serving/rejected_total").inc()
            return fut
        vocab = self.engine.model_cfg.vocab_size
        if any(t < 0 or t >= vocab for t in req.prompt):
            # jit-side gathers clamp out-of-range ids, which would
            # silently generate from a DIFFERENT prompt — reject here.
            fut.set_exception(
                ValueError(f"prompt token ids must be in [0, {vocab})")
            )
            reg.counter("serving/rejected_total").inc()
            return fut
        try:
            self._q.put_nowait(item)
        except queue.Full:
            reg.counter("serving/shed_total").inc()
            raise QueueFull(
                f"request queue at capacity ({self._q.maxsize}); load shed"
            ) from None
        if self._draining or self._stop.is_set():
            # Raced close(): its queue sweep may already have passed,
            # leaving this item unresolved in a dead batcher (the caller
            # would block its full request timeout instead of getting an
            # instant 503). Pull it back out if the loop hasn't taken
            # it; whoever dequeued it first resolves the future.
            with self._q.mutex:
                try:
                    self._q.queue.remove(item)
                    removed = True
                except ValueError:
                    removed = False
            if removed:
                reg.counter("serving/rejected_total").inc()
                raise Draining(
                    "serving is draining; retry against a live host"
                )
        reg.gauge("serving/queue_depth").set(self._q.qsize())
        return fut

    # --------------------------------------------------------- lifecycle

    def start(self) -> "ContinuousBatcher":
        if self._watchdog is not None:
            self._watchdog.start()
        self._thread = threading.Thread(
            target=self._loop, name="serving-batcher", daemon=True
        )
        self._thread.start()
        return self

    def close(self, *, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop accepting, optionally finish everything already
        accepted (queued + in flight), then stop the loop thread."""
        self._draining = True
        if drain:
            deadline = time.monotonic() + timeout

            def busy():
                return bool(
                    self._active or self._staged or not self._q.empty()
                )

            while (
                time.monotonic() < deadline
                and self._thread is not None
                and self._thread.is_alive()
            ):
                if not busy():
                    # A request dequeued this instant may not have
                    # bumped _staged yet; confirm emptiness after a
                    # tick before declaring the drain complete.
                    time.sleep(0.01)
                    if not busy():
                        break
                time.sleep(0.005)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._watchdog is not None:
            self._watchdog.stop()
        # Anything still unresolved (drain=False, or the drain timed
        # out) is failed/retired now — callers must never block forever.
        self._fail_pending(Draining("serving shut down before drain"))

    @property
    def draining(self) -> bool:
        return self._draining

    def _fail_pending(self, exc: Exception) -> None:
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            item.future.set_exception(exc)
        for item in list(self._active.values()):
            self._retire(item, truncated="shutdown")

    # -------------------------------------------------------------- loop

    def _wd(self, phase: str) -> None:
        if self._watchdog is not None:
            self._watchdog.enter(phase)

    def _loop(self) -> None:
        reg = self.registry
        decode_steps = 0
        while not self._stop.is_set():
            staged = self._gather()
            if staged:
                self._wd("serve_prefill")
                for item in staged:
                    try:
                        self._admit(item)
                    except Exception as e:  # noqa: BLE001 — one bad
                        # request must not take the serve loop down
                        log.exception("prefill failed; failing request")
                        if item.slot is not None:
                            self.engine.pool.free(item.slot)
                            item.slot = None
                        if not item.future.done():
                            item.future.set_exception(e)
                        reg.counter("serving/errors_total").inc()
                        if isinstance(e, EngineStepError):
                            # The failed step consumed the donated KV
                            # caches — every in-flight request's state
                            # is gone with them.
                            self._fail_active(e)
                    finally:
                        self._staged -= 1
            if not self._active:
                continue
            self._wd("serve_decode")
            t0 = time.perf_counter()
            try:
                with span("serve_decode_step", active=len(self._active)):
                    entries = [
                        (
                            it.slot, it.last_token, it.req.seed,
                            it.req.temperature, it.req.top_k,
                        )
                        for it in self._active.values()
                    ]
                    out = self.engine.decode(entries)
            except BlockExhausted as e:
                # Host-side exhaustion BEFORE the device step: no
                # donated state was lost, so only the named slots (the
                # requests that needed a new block) fail — loudly —
                # and the engine keeps serving the rest. Freeing them
                # returns their blocks, so the survivors' next growth
                # usually succeeds.
                log.warning(
                    "KV block exhaustion: failing %d of %d active "
                    "request(s): %s", len(e.slots), len(self._active), e,
                )
                reg.counter("serving/errors_total").inc()
                for slot in e.slots:
                    item = self._active.pop(slot, None)
                    if item is None:
                        continue
                    self.engine.pool.free(slot)
                    if not item.future.done():
                        item.future.set_exception(e)
                continue
            except Exception as e:  # noqa: BLE001 — fail the batch,
                # keep serving: the next admissions start clean
                log.exception("decode step failed; failing active batch")
                reg.counter("serving/errors_total").inc()
                self._fail_active(e)
                continue
            dt = time.perf_counter() - t0
            decode_steps += 1
            if self._watchdog is not None:
                self._watchdog.ping(decode_steps)
            tpot = reg.histogram("serving/tpot")
            reg.histogram("serving/decode_step").record(dt)
            for slot, token in out.items():
                item = self._active[slot]
                item.tokens.append(token)
                item.last_token = token
                tpot.record(dt)
                self._maybe_finish(item)
            reg.gauge("serving/active_requests").set(len(self._active))

    def _gather(self) -> list[_InFlight]:
        """Pull admissible requests without over-committing slots. Idle:
        block briefly for the first arrival, then hold a
        ``max_delay_s`` window so a burst prefills together. Busy:
        drain whatever is queued into the free slots, no waiting."""
        free = min(
            self.max_batch - len(self._active),
            self.engine.pool.num_slots - self.engine.pool.active_slots,
        )
        staged: list[_InFlight] = []
        if not self._active:
            self._wd("serve_idle")
            try:
                self._take(staged, timeout=0.05)
            except queue.Empty:
                return staged
            window_end = time.monotonic() + self.max_delay_s
            while len(staged) < free:
                remaining = window_end - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    self._take(staged, timeout=remaining)
                except queue.Empty:
                    break
        else:
            self._wd("serve_admit")
            while len(staged) < free:
                try:
                    self._take(staged)
                except queue.Empty:
                    break
        self.registry.gauge("serving/queue_depth").set(self._q.qsize())
        return staged

    def _fail_active(self, exc: Exception) -> None:
        """Fail and free every in-flight request (a step error lost or
        poisoned the shared device state; next admissions start clean)."""
        for it in list(self._active.values()):
            del self._active[it.slot]
            self.engine.pool.free(it.slot)
            if not it.future.done():
                it.future.set_exception(exc)

    def _take(self, staged: list, timeout: float | None = None) -> None:
        """Dequeue one request into ``staged``, counted in ``_staged``
        the moment it leaves the queue so the drain poll never sees it
        in neither place."""
        item = (
            self._q.get(timeout=timeout)
            if timeout is not None else self._q.get_nowait()
        )
        self._staged += 1
        staged.append(item)

    def _admit(self, item: _InFlight) -> None:
        reg = self.registry
        now = time.monotonic()
        if item.deadline is not None and now > item.deadline:
            reg.counter("serving/expired_total").inc()
            item.future.set_exception(
                DeadlineExceeded(
                    f"deadline ({item.req.deadline_s:.3f}s) passed after "
                    f"{now - item.t_submit:.3f}s in queue"
                )
            )
            return
        slot = self.engine.pool.alloc()
        if slot is None:  # _gather bounds by free slots; belt-and-braces
            reg.counter("serving/shed_total").inc()
            item.future.set_exception(QueueFull("no free KV slot"))
            return
        item.slot = slot
        item.t_admit = now
        reg.histogram("serving/queue_wait").record(now - item.t_submit)
        req = item.req
        t0 = time.perf_counter()
        with span("serve_prefill", tokens=len(req.prompt)):
            first, last_logits = self.engine.prefill(
                slot, req.prompt, seed=req.seed,
                temperature=req.temperature, top_k=req.top_k,
            )
        reg.histogram("serving/prefill").record(time.perf_counter() - t0)
        item.t_first = time.monotonic()
        reg.histogram("serving/ttft").record(item.t_first - item.t_submit)
        if req.kind == "classify":
            from tensorflow_examples_tpu.serving.engine import top_logprobs

            self.engine.pool.free(slot)
            item.slot = None
            self._resolve(
                item,
                Result(
                    tokens=[], prompt_len=len(req.prompt),
                    top=top_logprobs(last_logits, req.classify_top_n),
                ),
            )
            return
        item.tokens.append(first)
        item.last_token = first
        self._active[slot] = item
        self._maybe_finish(item)

    # ----------------------------------------------------------- retire

    def _maybe_finish(self, item: _InFlight) -> None:
        req, truncated = item.req, None
        done = (
            len(item.tokens) >= req.max_new_tokens
            or (req.eos_id is not None and item.last_token == req.eos_id)
        )
        if not done and item.deadline is not None \
                and time.monotonic() > item.deadline:
            done, truncated = True, "deadline"
        if not done and item.slot is not None and (
            len(req.prompt) + len(item.tokens)
            >= self.engine.model_cfg.max_len
        ):
            done, truncated = True, "max_len"  # admission makes this rare
        if done:
            self._retire(item, truncated=truncated)

    def _retire(self, item: _InFlight, *, truncated: str | None) -> None:
        if item.slot is not None and item.slot in self._active:
            del self._active[item.slot]
        if item.slot is not None:
            self.engine.pool.free(item.slot)
        self._resolve(
            item,
            Result(
                tokens=item.tokens,
                prompt_len=len(item.req.prompt),
                truncated=truncated,
            ),
        )

    def _resolve(self, item: _InFlight, result: Result) -> None:
        now = time.monotonic()
        result.queue_wait_s = (
            (item.t_admit or now) - item.t_submit
        )
        result.ttft_s = (
            item.t_first - item.t_submit if item.t_first else None
        )
        result.total_s = now - item.t_submit
        reg = self.registry
        reg.histogram("serving/e2e").record(result.total_s)
        reg.counter("serving/completed_total").inc()
        reg.counter("serving/generated_tokens_total").inc(
            len(result.tokens)
        )
        if not item.future.set_running_or_notify_cancel():
            return  # caller gave up; nothing to deliver
        item.future.set_result(result)

    # ------------------------------------------------------------- stats

    def stats_line(self) -> dict:
        """A schema-v6 ``kind="serving"`` JSONL line: the serving
        counterpart of the training window line (validated in tier-1;
        the frontend serves the latest one at ``/window`` and
        examples/gpt2/serve.py appends them to ``serving.jsonl``).
        Paged pools (serving/paged_kv.py) add their block/prefix-cache
        fields to the ``serving`` object — the v6 additions."""
        reg = self.registry
        counters = {
            k: v for k, v in reg.counter_values().items()
            if k.startswith(("serving/", "compile/"))
        }
        gauges = {
            k: v for k, v in reg.gauge_values().items()
            if k.startswith("serving/")
        }
        hists = reg.histogram_summaries()
        derived = {}
        for name in ("queue_wait", "prefill", "ttft", "tpot", "e2e"):
            h = hists.get(f"serving/{name}")
            if h and h["count"]:
                derived[f"{name}_p50"] = h["p50"]
                derived[f"{name}_p95"] = h["p95"]
        serving = {
            "active_requests": len(self._active),
            "queue_depth": self._q.qsize(),
            "slots": self.engine.pool.num_slots,
            "kv_occupancy": self.engine.pool.occupancy,
            "post_warmup_recompiles": (
                self.engine.post_warmup_recompiles()
            ),
            "draining": 1 if self._draining else 0,
        }
        paged = getattr(self.engine.pool, "paged_stats", None)
        if callable(paged):
            serving.update(paged())
        return {
            "schema_version": schema.SERVING_SCHEMA_VERSION,
            "kind": "serving",
            "step": int(
                counters.get("serving/decode_steps", 0)
            ),
            "time_unix": time.time(),
            "session_start_unix": self._start_unix,
            "host": 0,
            "metrics": {},
            "counters": counters,
            "gauges": gauges,
            "derived": derived,
            "serving": serving,
        }


def default_registry():  # convenience re-export for the frontend/tools
    return registry_mod.default_registry()
