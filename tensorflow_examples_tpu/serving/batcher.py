"""Continuous-batching request queue over the inference engine.

The throughput story of serving (the "serves heavy traffic" half of the
ROADMAP north star) is batching; the latency story is NOT waiting for a
full batch. Continuous batching does both: the decode step always runs
at the engine's fixed ``[max_slots]`` shape, and requests join (prefill
into a free slot) and leave (retire at EOS/limit) BETWEEN steps — a new
request never waits for the current batch to finish, a finished request
never makes the batch wait.

Flow control, outermost first:

* **Backpressure**: the submit queue is bounded (``max_queue``). A full
  queue sheds the request immediately (:class:`QueueFull`, counted in
  ``serving/shed_total``) — the caller gets a 503 now instead of a
  timeout later, and the queue can never grow without bound.
* **Admission control**: a request whose prompt+generation budget
  cannot fit the model's ``max_len`` is rejected up front
  (``serving/rejected_total``); one whose deadline already passed while
  queued is expired without touching the device
  (``serving/expired_total``).
* **Coalescing**: from idle, the first arrival opens a ``max_delay_s``
  window so a burst prefills together before the first decode step;
  under load, admission happens opportunistically between decode steps
  with no added delay. ``max_batch`` caps concurrency below the slot
  count when wanted.
* **Deadlines**: a request past its deadline mid-generation retires
  early with what it has (``truncated="deadline"``).

Latency accounting (the histograms the frontend's ``/metrics`` renders,
all ``registry.TimeHistogram``): ``serving/queue_wait`` (submit ->
admitted), ``serving/prefill`` (prefill wall), ``serving/ttft``
(submit -> first token), ``serving/tpot`` (per generated token decode
wall), ``serving/e2e`` (submit -> done).

The loop runs on one daemon thread; a ``utils.diagnostics.Watchdog``
(``watchdog_secs > 0``) gets phase markers (``serve_idle`` /
``serve_admit`` / ``serve_prefill`` / ``serve_decode``) so a wedged
device step is attributed exactly like a training-loop hang.

Speculative decoding (ISSUE 11, ``ServeConfig.spec_decode_k > 0``):
each decode step first asks the per-request draft source
(serving/speculative.py) for up to k candidate tokens, runs the
engine's compiled ``verify_k`` rung over launch token + drafts, and
commits the longest agreeing prefix — multiple tokens per step, every
one of them a verify-SAMPLED token at its own position key, so streams
stay token-identical to the non-speculative path. TPOT records
wall/committed per token; ``serving/accepted_per_step`` and the
``serving/spec_*`` counters carry the acceptance story onto the
schema-v8 stats line.

Threading contract (checked by graftlint, ISSUE 14 — see
docs/static_analysis.md): the batcher deliberately owns NO lock, so it
carries no ``# guard:`` annotations. Every structure crossed by the
frontend submit threads and the loop thread synchronizes itself — the
per-class ``queue.Queue``s and the ``_arrival`` Event internally, the
per-request shed/spec tallies through the LOCKED metrics registry
(``telemetry/registry.py``, annotated there; this is why the
lock pass surfaces no tally aggregation race here), and the brownout
controller via its annotated ``_ttft`` sample lock plus documented
atomic ``level`` reads (``serving/overload.py``). Everything else
(``_active``/``_prefilling``/``_staged``/``_draining``) is
single-writer on the loop thread with GIL-atomic len()/int/bool
snapshot reads from close()/stats_line(), as noted field-by-field
below. The runtime lock-order detector and the thread-leak guard
(tests/conftest.py) cover the dynamic side in the overload tier.

SLO classes (ISSUE 13): every request carries an ``slo`` class —
``interactive`` (default) or ``batch`` — and the batcher keeps one
bounded queue per class. Interactive is served first at every decision
point: admission drains the interactive queue before the batch queue,
chunked-prefill turns prefer interactive, and when the slots are full
an interactive arrival PREEMPTS the most recently admitted batch
request (its slot is freed and the request re-queued; replay from the
prompt is token-identical by the per-request seeding, so preemption is
a latency event, never a content one). Latency histograms and shed
counters exist per class (``serving/ttft_interactive`` /
``serving/shed_batch_total`` / ...) next to the class-blind ones, and
the schema-v10 stats line carries the split. Under pressure the
brownout ladder (``serving/overload.py``, ``ServeConfig.brownout``)
sheds batch FIRST, then caps generation budgets, then drops
speculation's extra verify work, and sheds interactive only as the
last rung before falling over.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import logging
import queue
import threading
import time

from tensorflow_examples_tpu.serving.engine import EngineStepError
from tensorflow_examples_tpu.serving.overload import OverloadController
from tensorflow_examples_tpu.serving.paged_kv import BlockExhausted
from tensorflow_examples_tpu.telemetry import registry as registry_mod
from tensorflow_examples_tpu.telemetry import schema
from tensorflow_examples_tpu.telemetry.spans import span
from tensorflow_examples_tpu.telemetry.tracing import (
    ExemplarStore,
    close_span,
)

log = logging.getLogger(__name__)

# SLO classes, in service-priority order: admission, chunk turns and
# preemption all prefer earlier classes (ISSUE 13).
SLO_CLASSES = ("interactive", "batch")


class QueueFull(RuntimeError):
    """Bounded submit queue is full: request load-shed (HTTP 503)."""


class Draining(RuntimeError):
    """Batcher is draining for shutdown: new requests rejected (503)."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before any token was produced."""


@dataclasses.dataclass
class Request:
    """One generate/classify request (token ids in, token ids out).

    The disaggregated roles (ISSUE 12) add two more kinds: ``prefill``
    runs the prompt to completion-of-prefill and resolves with the
    first generated token plus the slot's serialized KV pages
    (``Result.pages``); ``resume`` imports ``pages``/``first_token``
    from a prefill replica and continues the decode stream. Both
    require the paged pool."""

    prompt: list[int]
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0
    eos_id: int | None = None
    deadline_s: float | None = None  # relative to submit time
    kind: str = "generate"       # generate | classify | prefill | resume
    classify_top_n: int = 5
    pages: dict | None = None        # resume: the handed-off KV pages
    first_token: int | None = None   # resume: the prefill's sampled token
    skip_tokens: int = 0             # prefill: leading prompt tokens the
    #                                  importer already caches (router
    #                                  digest exchange, ISSUE 15) — the
    #                                  export ships only the rest
    slo: str = "interactive"     # interactive | batch (ISSUE 13):
    #                              interactive is served first
    #                              everywhere; batch absorbs shedding
    #                              and preemption first
    trace: dict | None = None    # ISSUE 18: the router's trace context
    #                              ({"trace_id", "parent_span_id",
    #                              "sampled"}). When set, the batcher
    #                              collects this request's spans
    #                              (queue_wait, prefill chunks, decode
    #                              segments, preemptions) and returns
    #                              them on Result.spans; None costs the
    #                              hot path nothing.


@dataclasses.dataclass
class Result:
    """Resolved request payload (the frontend serializes this)."""

    tokens: list[int]               # generated tokens (generate)
    prompt_len: int
    top: list[dict] | None = None   # classify payload
    truncated: str | None = None  # None | "deadline" | "max_len"
    #                               | "shutdown" | "brownout" (the
    #                               level-2 generation cap bit: tokens
    #                               are a PREFIX of the uncapped stream)
    queue_wait_s: float = 0.0
    ttft_s: float | None = None
    total_s: float = 0.0
    # Per-request speculation accounting (ISSUE 11; zeros with
    # speculation off): drafts offered to verify steps and drafts
    # accepted. len(tokens) - 1 - spec_accepted = plain decode commits,
    # which is how the accounting test ties streams to counters.
    spec_drafted: int = 0
    spec_accepted: int = 0
    # Disaggregated prefill (ISSUE 12): the serialized KV pages a
    # kind="prefill" request resolves with (None otherwise).
    pages: dict | None = None
    # ISSUE 18: this request's replica-side span dicts (None when the
    # request carried no trace context). The frontend returns them as
    # the reply's "trace_spans"; top-level spans carry parent_id=None
    # and the router reparents them under its dispatch span.
    spans: list | None = None


class _InFlight:
    __slots__ = (
        "req", "future", "slot", "t_submit", "t_admit", "t_first",
        "deadline", "tokens", "last_token", "spec_drafted",
        "spec_accepted", "max_new_eff", "spans", "t_decode0",
        "decode_seg", "decode_tok0",
    )

    def __init__(self, req: Request, future, t_submit: float):
        self.req = req
        self.future = future
        self.slot: int | None = None
        self.t_submit = t_submit
        self.t_admit: float | None = None
        self.t_first: float | None = None
        self.deadline = (
            t_submit + req.deadline_s
            if req.deadline_s is not None else None
        )
        self.tokens: list[int] = []
        self.last_token: int | None = None
        # ISSUE 18 trace collection (None = untraced, zero overhead).
        # The span list SURVIVES preemption resets below — a preempted
        # request's trace shows every decode segment it lived through.
        self.spans: list | None = [] if req.trace is not None else None
        self.t_decode0: float | None = None  # current decode segment t0
        self.decode_seg = 0
        self.decode_tok0 = 0  # committed tokens at segment start
        # Per-request speculation accounting (ISSUE 11): drafts offered
        # to verify steps and drafts accepted. Committed tokens ==
        # len(tokens) always — acceptance is a speed story, never a
        # content one (test-pinned).
        self.spec_drafted = 0
        self.spec_accepted = 0
        # Effective generation budget (ISSUE 13): the brownout level-2
        # cap at ADMISSION time, <= req.max_new_tokens. A capped stream
        # retires with truncated="brownout" — still a prefix of the
        # uncapped stream.
        self.max_new_eff = req.max_new_tokens


class ContinuousBatcher:
    def __init__(self, engine, *, registry=None, watchdog=None,
                 draft=None):
        self.engine = engine
        cfg = engine.cfg
        self.max_batch = min(
            cfg.max_batch or cfg.max_slots, cfg.max_slots
        )
        self.max_delay_s = cfg.max_delay_s
        # Speculative decoding (ISSUE 11): with spec_decode_k > 0 the
        # decode step becomes draft-propose / verify-commit — the
        # drafter proposes up to k tokens per request, one compiled
        # verify_k forward scores them, and the longest agreeing prefix
        # commits. ``draft=`` injects a custom DraftSource (a small
        # draft model, a test stub); default is the self-speculative
        # n-gram source.
        self.spec_k = int(getattr(cfg, "spec_decode_k", 0) or 0)
        self._draft = None
        if self.spec_k > 0 and hasattr(engine, "verify"):
            if draft is None:
                from tensorflow_examples_tpu.serving.speculative import (
                    make_draft,
                )

                draft = make_draft(cfg)
            self._draft = draft
        self.registry = (
            registry if registry is not None else engine.registry
        )
        # One bounded queue per SLO class (ISSUE 13): admission drains
        # interactive first; a class sheds only against its OWN bound,
        # and the brownout ladder sheds batch fleet-wide before
        # interactive ever queues deep.
        self._queues: dict[str, queue.Queue] = {
            cls: queue.Queue(maxsize=cfg.max_queue)
            for cls in SLO_CLASSES
        }
        # Signaled on every submit so the idle loop can block on
        # "anything arrived in ANY class queue".
        self._arrival = threading.Event()
        # Brownout overload controller (serving/overload.py): ticked
        # once per loop iteration with queue depth + KV occupancy (+
        # its own recent-TTFT window); submit() reads its level.
        self._overload = OverloadController(
            registry=self.registry,
            enabled=bool(getattr(cfg, "brownout", False)),
            queue_hi=(
                int(getattr(cfg, "brownout_queue_hi", 0) or 0)
                or 2 * cfg.max_slots
            ),
            kv_hi=float(getattr(cfg, "brownout_kv_hi", 0.92)),
            ttft_hi_s=float(getattr(cfg, "brownout_ttft_hi_s", 0.0)),
            clear_frac=float(getattr(cfg, "brownout_clear_frac", 0.5)),
            hold_s=float(getattr(cfg, "brownout_hold_s", 0.5)),
            max_new_tokens_cap=int(
                getattr(cfg, "brownout_max_new_tokens", 8)
            ),
        )
        # ISSUE 18: worst-recent TTFT/e2e observations with their
        # trace_id, exposed as /metrics exemplars. Per-INSTANCE (not
        # module-global): in-proc fleets share one process, and a
        # shared store would cross-pollute replicas' exemplars.
        self.exemplars = ExemplarStore()
        self._active: dict[int, _InFlight] = {}
        # Chunked prefills in flight (ISSUE 12): slot -> (item, engine
        # ChunkedPrefill state). One chunk runs per decode-loop
        # iteration (oldest first), so a long cold prompt's prefill
        # interleaves with decode steps instead of monopolizing them.
        # Single-writer: the loop thread.
        self._prefilling: dict[int, tuple] = {}
        # Requests the loop has dequeued but not yet admitted into
        # _active (mid-prefill). close(drain=True)'s poll must count
        # them or a drain landing in that window truncates an accepted
        # request. Single-writer (the loop thread); int reads are
        # atomic under the GIL.
        self._staged = 0
        self._draining = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._start_unix = time.time()
        self._watchdog = watchdog
        if watchdog is None and cfg.watchdog_secs > 0:
            from tensorflow_examples_tpu.utils.diagnostics import Watchdog

            self._watchdog = Watchdog(
                cfg.watchdog_secs,
                fatal_timeout_s=4 * cfg.watchdog_secs,
            )

    # ------------------------------------------------------------ intake

    def submit(self, req: Request) -> concurrent.futures.Future:
        """Enqueue; resolves to :class:`Result`. Raises
        :class:`Draining`/:class:`QueueFull` instead of queueing when
        the request can never be served promptly, and fails the future
        fast on admission-impossible requests."""
        reg = self.registry
        reg.counter("serving/requests_total").inc()
        if self._draining or self._stop.is_set():
            reg.counter("serving/rejected_total").inc()
            raise Draining("serving is draining; retry against a live host")
        if req.slo not in SLO_CLASSES:
            fut = concurrent.futures.Future()
            fut.set_exception(ValueError(
                f"unknown slo class {req.slo!r}; one of {SLO_CLASSES}"
            ))
            reg.counter("serving/rejected_total").inc()
            return fut
        if self._overload.sheds(req.slo):
            # Brownout shed (ISSUE 13): the ladder sheds batch at level
            # 1 and interactive only at level 4 — a 503 NOW, before the
            # queue, so degradation lands on the class that can absorb
            # it.
            reg.counter("serving/shed_total").inc()
            reg.counter(f"serving/shed_{req.slo}_total").inc()
            reg.counter("serving/brownout_shed_total").inc()
            raise QueueFull(
                f"brownout level {self._overload.level}: shedding "
                f"{req.slo} traffic; retry later"
            )
        fut: concurrent.futures.Future = concurrent.futures.Future()
        item = _InFlight(req, fut, time.monotonic())
        budget = len(req.prompt) + (
            req.max_new_tokens
            if req.kind in ("generate", "resume") else 0
        )
        if req.kind not in ("generate", "classify", "prefill", "resume"):
            fut.set_exception(ValueError(f"unknown kind {req.kind!r}"))
            reg.counter("serving/rejected_total").inc()
            return fut
        if req.kind in ("prefill", "resume") and not getattr(
            self.engine, "paged", False
        ):
            # The handoff verbs move KV as serialized pages — only the
            # block-paged pool has a page to move.
            fut.set_exception(ValueError(
                "disaggregated prefill/decode requires the paged KV "
                "pool (set kv_block_size)"
            ))
            reg.counter("serving/rejected_total").inc()
            return fut
        if not req.prompt or budget > self.engine.model_cfg.max_len:
            fut.set_exception(
                ValueError(
                    f"prompt ({len(req.prompt)}) + max_new_tokens must fit "
                    f"1..max_len={self.engine.model_cfg.max_len}"
                )
            )
            reg.counter("serving/rejected_total").inc()
            return fut
        vocab = self.engine.model_cfg.vocab_size
        if any(t < 0 or t >= vocab for t in req.prompt):
            # jit-side gathers clamp out-of-range ids, which would
            # silently generate from a DIFFERENT prompt — reject here.
            fut.set_exception(
                ValueError(f"prompt token ids must be in [0, {vocab})")
            )
            reg.counter("serving/rejected_total").inc()
            return fut
        if req.kind == "resume":
            if not isinstance(req.pages, dict):
                fut.set_exception(ValueError(
                    "resume requires the prefill replica's 'pages' "
                    "payload"
                ))
                reg.counter("serving/rejected_total").inc()
                return fut
            ft = req.first_token
            if not isinstance(ft, int) or isinstance(ft, bool) \
                    or not 0 <= ft < vocab:
                fut.set_exception(ValueError(
                    f"resume 'first_token' must be a token id in "
                    f"[0, {vocab})"
                ))
                reg.counter("serving/rejected_total").inc()
                return fut
        q = self._queues[req.slo]
        try:
            q.put_nowait(item)
        except queue.Full:
            reg.counter("serving/shed_total").inc()
            reg.counter(f"serving/shed_{req.slo}_total").inc()
            raise QueueFull(
                f"{req.slo} request queue at capacity ({q.maxsize}); "
                "load shed"
            ) from None
        self._arrival.set()
        if self._draining or self._stop.is_set():
            # Raced close(): its queue sweep may already have passed,
            # leaving this item unresolved in a dead batcher (the caller
            # would block its full request timeout instead of getting an
            # instant 503). Pull it back out if the loop hasn't taken
            # it; whoever dequeued it first resolves the future.
            with q.mutex:
                try:
                    q.queue.remove(item)
                    removed = True
                except ValueError:
                    removed = False
            if removed:
                reg.counter("serving/rejected_total").inc()
                raise Draining(
                    "serving is draining; retry against a live host"
                )
        reg.gauge("serving/queue_depth").set(self.queue_depth())
        return fut

    def queue_depth(self) -> int:
        """Total queued requests across SLO classes (the load signal
        the frontend's /health and the brownout controller read)."""
        return sum(q.qsize() for q in self._queues.values())

    @property
    def brownout_level(self) -> int:
        return self._overload.level

    # --------------------------------------------------------- lifecycle

    def start(self) -> "ContinuousBatcher":
        if self._watchdog is not None:
            self._watchdog.start()
        self._thread = threading.Thread(
            target=self._loop, name="serving-batcher", daemon=True
        )
        self._thread.start()
        return self

    def close(self, *, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop accepting, optionally finish everything already
        accepted (queued + in flight), then stop the loop thread."""
        self._draining = True
        if drain:
            deadline = time.monotonic() + timeout

            def busy():
                return bool(
                    self._active or self._staged or self._prefilling
                    or self.queue_depth()
                )

            while (
                time.monotonic() < deadline
                and self._thread is not None
                and self._thread.is_alive()
            ):
                if not busy():
                    # A request dequeued this instant may not have
                    # bumped _staged yet; confirm emptiness after a
                    # tick before declaring the drain complete.
                    time.sleep(0.01)
                    if not busy():
                        break
                time.sleep(0.005)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._watchdog is not None:
            self._watchdog.stop()
        # Anything still unresolved (drain=False, or the drain timed
        # out) is failed/retired now — callers must never block forever.
        self._fail_pending(Draining("serving shut down before drain"))

    @property
    def draining(self) -> bool:
        return self._draining

    def _fail_pending(self, exc: Exception) -> None:
        for q in self._queues.values():
            while True:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    break
                item.future.set_exception(exc)
        for item, _ in list(self._prefilling.values()):
            self._prefilling.pop(item.slot, None)
            self._retire(item, truncated="shutdown")
        for item in list(self._active.values()):
            self._retire(item, truncated="shutdown")

    # -------------------------------------------------------------- loop

    def _wd(self, phase: str) -> None:
        if self._watchdog is not None:
            self._watchdog.enter(phase)

    def _loop(self) -> None:
        reg = self.registry
        decode_steps = 0
        while not self._stop.is_set():
            # Brownout tick (ISSUE 13): one controller evaluation per
            # loop iteration — queue depth + KV occupancy here, the
            # controller's own recent-TTFT window inside. Cheap host
            # math; the ladder's hysteresis does the rate limiting.
            self._overload.update(
                queue_depth=self.queue_depth(),
                kv_occupancy=float(self.engine.pool.occupancy),
            )
            # Interactive preempts batch for decode slots (ISSUE 13):
            # free slots for waiting interactive requests BEFORE this
            # iteration's admission, so the preempted batch slots are
            # immediately reusable.
            self._preempt_for_interactive()
            staged = self._gather()
            if staged:
                self._wd("serve_prefill")
                for item in staged:
                    try:
                        self._admit(item)
                    except Exception as e:  # noqa: BLE001 — one bad
                        # request must not take the serve loop down
                        log.exception("prefill failed; failing request")
                        if item.slot is not None:
                            self.engine.pool.free(item.slot)
                            self._drop_draft(item.slot)
                            item.slot = None
                        if not item.future.done():
                            item.future.set_exception(e)
                        reg.counter("serving/errors_total").inc()
                        if isinstance(e, EngineStepError):
                            # The failed step consumed the donated KV
                            # caches — every in-flight request's state
                            # is gone with them.
                            self._fail_active(e)
                    finally:
                        self._staged -= 1
            if self._prefilling:
                # ONE chunk per loop iteration (oldest admission
                # first): the decode step below runs between chunks,
                # which is the whole TTFT-vs-TPOT admission bargain.
                self._wd("serve_prefill")
                self._chunk_step()
            if not self._active:
                continue
            self._wd("serve_decode")
            t0 = time.perf_counter()
            drafts_by_slot: dict[int, int] = {}
            try:
                with span("serve_decode_step", active=len(self._active)):
                    out = self._decode_step(drafts_by_slot)
            except BlockExhausted as e:
                # Host-side exhaustion BEFORE the device step: no
                # donated state was lost, so only the named slots (the
                # requests that needed a new block) fail — loudly —
                # and the engine keeps serving the rest. Freeing them
                # returns their blocks, so the survivors' next growth
                # usually succeeds.
                log.warning(
                    "KV block exhaustion: failing %d of %d active "
                    "request(s): %s", len(e.slots), len(self._active), e,
                )
                reg.counter("serving/errors_total").inc()
                for slot in e.slots:
                    item = self._active.pop(slot, None)
                    if item is None:
                        continue
                    self.engine.pool.free(slot)
                    self._drop_draft(slot)
                    if not item.future.done():
                        item.future.set_exception(e)
                continue
            except Exception as e:  # noqa: BLE001 — fail the batch,
                # keep serving: the next admissions start clean
                log.exception("decode step failed; failing active batch")
                reg.counter("serving/errors_total").inc()
                self._fail_active(e)
                continue
            dt = time.perf_counter() - t0
            decode_steps += 1
            if self._watchdog is not None:
                self._watchdog.ping(decode_steps)
            tpot = reg.histogram("serving/tpot")
            reg.histogram("serving/decode_step").record(dt)
            for slot, toks in out.items():
                item = self._active[slot]
                cls_tpot = reg.histogram(
                    f"serving/tpot_{item.req.slo}"
                )
                item.spec_drafted += drafts_by_slot.get(slot, 0)
                item.spec_accepted += len(toks) - 1
                per_tok = dt / len(toks)
                committed: list[int] = []
                for token in toks:
                    item.tokens.append(token)
                    item.last_token = token
                    committed.append(token)
                    tpot.record(per_tok)
                    cls_tpot.record(per_tok)
                    if item.req.eos_id is not None \
                            and token == item.req.eos_id:
                        # Tokens past eos in the same verify window are
                        # discarded — identical to the non-speculative
                        # stream, which stops here.
                        break
                if self._draft is not None:
                    if drafts_by_slot:  # a verify step, not a fallback
                        # The ENGINE-committed count (pre-eos-discard),
                        # so the histogram and the spec_* counters
                        # measure the same thing.
                        reg.histogram(
                            "serving/accepted_per_step"
                        ).record(float(len(toks)))
                    self._draft.extend(slot, committed)
                self._maybe_finish(item)
            reg.gauge("serving/active_requests").set(len(self._active))

    def _decode_step(self, drafts_by_slot: dict[int, int]):
        """One device step over the active set; returns {slot:
        committed token list}. Speculation on: propose per-request
        drafts (capped at the request's remaining budget minus the one
        token the verify itself samples) and run the verify_k rung; a
        step where NO request has a draft falls back to the plain
        one-token decode rung — same tokens, (k+1)x less compute.
        Brownout level 3+ (ISSUE 13) forces that fallback every step:
        speculation's extra verify compute is the cheapest thing to
        drop under pressure, and dropping it never changes tokens."""
        if self._draft is None or self._overload.spec_disabled():
            out = self.engine.decode([
                (
                    it.slot, it.last_token, it.req.seed,
                    it.req.temperature, it.req.top_k,
                )
                for it in self._active.values()
            ])
            return {slot: [tok] for slot, tok in out.items()}
        entries = []
        proposed: dict[int, int] = {}
        for it in self._active.values():
            remaining = it.req.max_new_tokens - len(it.tokens)
            k_eff = min(self.spec_k, remaining - 1)
            k_eff = min(k_eff, it.max_new_eff - len(it.tokens) - 1)
            drafts = (
                self._draft.propose(it.slot, k_eff) if k_eff > 0 else []
            )
            proposed[it.slot] = len(drafts)
            entries.append((
                it.slot, it.last_token, drafts, it.req.seed,
                it.req.temperature, it.req.top_k,
            ))
        if not any(e[2] for e in entries):
            # drafts_by_slot stays empty: this is a plain decode step,
            # and the accepted_per_step histogram (like the spec_*
            # counters) measures VERIFY steps only.
            out = self.engine.decode([
                (slot, tok, seed, temp, tk)
                for slot, tok, _, seed, temp, tk in entries
            ])
            return {slot: [tok] for slot, tok in out.items()}
        drafts_by_slot.update(proposed)
        return self.engine.verify(entries)

    def _gather(self) -> list[_InFlight]:
        """Pull admissible requests without over-committing slots. Idle:
        block briefly for the first arrival, then hold a
        ``max_delay_s`` window so a burst prefills together. Busy:
        drain whatever is queued into the free slots, no waiting."""
        free = min(
            self.max_batch - len(self._active) - len(self._prefilling),
            self.engine.pool.num_slots - self.engine.pool.active_slots,
        )
        staged: list[_InFlight] = []
        if not self._active and not self._prefilling:
            self._wd("serve_idle")
            try:
                self._take(staged, timeout=0.05)
            except queue.Empty:
                return staged
            window_end = time.monotonic() + self.max_delay_s
            while len(staged) < free:
                remaining = window_end - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    self._take(staged, timeout=remaining)
                except queue.Empty:
                    break
        else:
            self._wd("serve_admit")
            while len(staged) < free:
                try:
                    self._take(staged)
                except queue.Empty:
                    break
        self.registry.gauge("serving/queue_depth").set(
            self.queue_depth()
        )
        return staged

    def _fail_active(self, exc: Exception) -> None:
        """Fail and free every in-flight request — decoding AND
        mid-chunked-prefill, whose written blocks died with the same
        donated device state (a step error lost or poisoned it; next
        admissions start clean)."""
        for it, _ in list(self._prefilling.values()):
            del self._prefilling[it.slot]
            self.engine.pool.free(it.slot)
            it.slot = None
            if not it.future.done():
                it.future.set_exception(exc)
        for it in list(self._active.values()):
            del self._active[it.slot]
            self.engine.pool.free(it.slot)
            self._drop_draft(it.slot)
            if not it.future.done():
                it.future.set_exception(exc)

    def _drop_draft(self, slot: int | None) -> None:
        if self._draft is not None and slot is not None:
            self._draft.end(slot)

    def _take(self, staged: list, timeout: float | None = None) -> None:
        """Dequeue one request into ``staged`` — INTERACTIVE FIRST
        (ISSUE 13: the class order is the admission order), counted in
        ``_staged`` the moment it leaves a queue so the drain poll
        never sees it in neither place. With a timeout, blocks on the
        arrival event until any class queue has an item."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while True:
            for cls in SLO_CLASSES:
                try:
                    item = self._queues[cls].get_nowait()
                except queue.Empty:
                    continue
                self._staged += 1
                staged.append(item)
                return
            if deadline is None:
                raise queue.Empty
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise queue.Empty
            # Clear-then-recheck closes the missed-wakeup race with
            # submit()'s put-then-set.
            self._arrival.clear()
            if any(not q.empty() for q in self._queues.values()):
                continue
            if not self._arrival.wait(timeout=remaining):
                raise queue.Empty

    # ------------------------------------------------------- preemption

    def _preempt_for_interactive(self) -> None:
        """Interactive preempts batch for decode slots (ISSUE 13):
        when interactive requests are queued and the slots are
        exhausted, evict batch requests — most recently admitted first
        (least sunk work), mid-chunked-prefill before decoding — and
        re-queue them at the back of the batch queue. Replay from the
        prompt is token-identical by the per-request seeding, so a
        preemption costs the batch request latency, never content.
        Loop-thread only."""
        waiting = self._queues["interactive"].qsize()
        if not waiting:
            return
        free = min(
            self.max_batch - len(self._active) - len(self._prefilling),
            self.engine.pool.num_slots - self.engine.pool.active_slots,
        )
        need = waiting - max(free, 0)
        if need <= 0:
            return
        victims: list[_InFlight] = [
            it for it, _ in self._prefilling.values()
            if it.req.slo == "batch"
        ]
        victims += sorted(
            (it for it in self._active.values()
             if it.req.slo == "batch"),
            key=lambda it: it.t_admit or 0.0, reverse=True,
        )
        for item in victims[:need]:
            self._preempt(item)

    def _preempt(self, item: _InFlight) -> None:
        reg = self.registry
        slot = item.slot
        self._prefilling.pop(slot, None)
        self._active.pop(slot, None)
        self.engine.pool.free(slot)
        self._drop_draft(slot)
        if item.spans is not None:
            if item.t_decode0 is not None:
                self._close_decode_segment(item, preempted=True)
            else:
                # Evicted mid-prefill: a point marker keeps the
                # preemption visible (and forced-kept) in the trace.
                item.spans.append(close_span(
                    "preempted", time.monotonic(),
                    tags={"preempted": True, "phase": "prefill"},
                ))
        # Full reset: re-admission replays prefill + decode from the
        # prompt (same tokens by seeding); the original t_submit keeps
        # queue-wait/deadline accounting honest about the total wait.
        item.slot = None
        item.t_admit = None
        item.t_first = None
        item.tokens = []
        item.last_token = None
        item.spec_drafted = 0
        item.spec_accepted = 0
        item.max_new_eff = item.req.max_new_tokens
        reg.counter("serving/preempted_total").inc()
        try:
            self._queues["batch"].put_nowait(item)
        except queue.Full:
            # The batch queue itself is saturated: the preemption
            # becomes a shed — batch absorbs it, by design.
            reg.counter("serving/shed_total").inc()
            reg.counter("serving/shed_batch_total").inc()
            if not item.future.done():
                item.future.set_exception(QueueFull(
                    "preempted for interactive traffic and the batch "
                    "queue is full; load shed"
                ))

    def _admit(self, item: _InFlight) -> None:
        reg = self.registry
        now = time.monotonic()
        if item.deadline is not None and now > item.deadline:
            reg.counter("serving/expired_total").inc()
            item.future.set_exception(
                DeadlineExceeded(
                    f"deadline ({item.req.deadline_s:.3f}s) passed after "
                    f"{now - item.t_submit:.3f}s in queue"
                )
            )
            return
        slot = self.engine.pool.alloc()
        if slot is None:  # _gather bounds by free slots; belt-and-braces
            reg.counter("serving/shed_total").inc()
            item.future.set_exception(QueueFull("no free KV slot"))
            return
        item.slot = slot
        item.t_admit = now
        reg.histogram("serving/queue_wait").record(now - item.t_submit)
        req = item.req
        reg.histogram(
            f"serving/queue_wait_{req.slo}"
        ).record(now - item.t_submit)
        if item.spans is not None:
            # ISSUE 18: the queue-wait span carries the brownout rung
            # in force AT ADMISSION — a brownout_level tag > 0 is a
            # forced-keep signal for the tail sampler.
            item.spans.append(close_span(
                "queue_wait", item.t_submit,
                tags={"slo": req.slo,
                      "brownout_level": self._overload.level},
            ))
        cap = self._overload.max_new_cap()
        if cap is not None and req.kind in ("generate", "resume"):
            # Brownout level 2 (ISSUE 13): cap the generation budget at
            # admission — the stream retires early with
            # truncated="brownout", still a prefix of the full stream.
            item.max_new_eff = min(req.max_new_tokens, cap)
        if req.kind == "resume":
            # Disaggregated decode (ISSUE 12): no prefill — map the
            # handed-off KV pages in and continue the stream from the
            # prefill replica's first token.
            t_import = time.monotonic()
            with span("serve_resume", tokens=len(req.prompt)):
                self.engine.import_kv_pages(slot, req.pages, req.prompt)
            item.t_first = time.monotonic()
            if item.spans is not None:
                item.spans.append(close_span(
                    "resume_import", t_import,
                    tags={"tokens": len(req.prompt)},
                ))
            ttft = item.t_first - item.t_submit
            reg.histogram("serving/ttft").record(ttft)
            reg.histogram(f"serving/ttft_{req.slo}").record(ttft)
            self._overload.note_ttft(ttft)
            if item.spans is not None:
                self.exemplars.record(
                    "serving/ttft", ttft, req.trace["trace_id"]
                )
                self._start_decode_segment(item)
            item.tokens.append(req.first_token)
            item.last_token = req.first_token
            if self._draft is not None:
                self._draft.begin(
                    slot, list(req.prompt) + [req.first_token]
                )
            self._active[slot] = item
            self._maybe_finish(item)
            return
        open_chunked = getattr(self.engine, "prefill_open", None)
        if callable(open_chunked):
            state = open_chunked(
                slot, req.prompt, seed=req.seed,
                temperature=req.temperature, top_k=req.top_k,
            )
            if state is not None and len(state.spans) == 1:
                # The COLD TAIL fits one chunk (a mostly-cached long
                # prompt): run it inline — the documented chunking
                # semantics key on the cold tail, and queueing this
                # effectively-warm request behind an older 16k chunked
                # prefill would stall its TTFT for nothing.
                t0 = time.perf_counter()
                with span("serve_prefill", tokens=len(req.prompt)):
                    _, first, last_logits = self.engine.prefill_step(
                        state
                    )
                reg.histogram("serving/prefill").record(
                    time.perf_counter() - t0
                )
                self._finish_prefill(item, first, last_logits)
                return
            if state is not None:
                # Chunked admission: the slot's blocks are claimed; the
                # loop runs one chunk per iteration from here on and
                # _finish_prefill fires on the final one.
                self._prefilling[slot] = (item, state)
                return
        t0 = time.perf_counter()
        with span("serve_prefill", tokens=len(req.prompt)):
            first, last_logits = self.engine.prefill(
                slot, req.prompt, seed=req.seed,
                temperature=req.temperature, top_k=req.top_k,
            )
        reg.histogram("serving/prefill").record(time.perf_counter() - t0)
        self._finish_prefill(item, first, last_logits)

    def _start_decode_segment(self, item: _InFlight) -> None:
        """Open a decode segment span (traced requests only): one
        continuous slot residency. Preemption closes it; re-admission
        opens the next — a preempted request's trace shows each
        segment it decoded through."""
        item.t_decode0 = time.monotonic()
        item.decode_seg += 1
        item.decode_tok0 = len(item.tokens)

    def _close_decode_segment(self, item: _InFlight, *,
                              preempted: bool = False) -> None:
        if item.spans is None or item.t_decode0 is None:
            return
        tags = {
            "segment": item.decode_seg,
            "tokens": len(item.tokens) - item.decode_tok0,
        }
        if preempted:
            tags["preempted"] = True
        item.spans.append(
            close_span("decode_segment", item.t_decode0, tags=tags)
        )
        item.t_decode0 = None

    def _finish_prefill(self, item: _InFlight, first: int,
                        last_logits) -> None:
        """Shared tail of single-shot and chunked prefill: record TTFT
        and route the request by kind (classify resolves the logits
        head, prefill exports the KV pages, generate enters the decode
        set)."""
        reg = self.registry
        req, slot = item.req, item.slot
        item.t_first = time.monotonic()
        ttft = item.t_first - item.t_submit
        reg.histogram("serving/ttft").record(ttft)
        reg.histogram(f"serving/ttft_{req.slo}").record(ttft)
        self._overload.note_ttft(ttft)
        if item.spans is not None:
            # Admission-to-first-token: single-shot this is the one
            # prefill dispatch; chunked, it brackets the per-chunk
            # spans (decode steps interleave inside — that is the
            # chunking's point and the span shows it).
            item.spans.append(close_span(
                "prefill", item.t_admit,
                tags={"prompt_tokens": len(req.prompt)},
            ))
            self.exemplars.record(
                "serving/ttft", ttft, req.trace["trace_id"]
            )
        if req.kind == "classify":
            from tensorflow_examples_tpu.serving.engine import top_logprobs

            self.engine.pool.free(slot)
            item.slot = None
            self._resolve(
                item,
                Result(
                    tokens=[], prompt_len=len(req.prompt),
                    top=top_logprobs(last_logits, req.classify_top_n),
                ),
            )
            return
        if req.kind == "prefill":
            # Disaggregated prefill (ISSUE 12): the work product is the
            # slot's KV pages, not a decode stream — export, free, and
            # hand the payload (plus the first sampled token) back for
            # the router to ship to a decode replica.
            pages = self.engine.export_kv_pages(
                slot, req.prompt, skip_tokens=req.skip_tokens
            )
            self.engine.pool.free(slot)
            item.slot = None
            self._resolve(
                item,
                Result(
                    tokens=[first], prompt_len=len(req.prompt),
                    pages=pages,
                ),
            )
            return
        item.tokens.append(first)
        item.last_token = first
        if item.spans is not None:
            self._start_decode_segment(item)
        if self._draft is not None:
            # The drafter's context: prompt + everything committed.
            self._draft.begin(slot, list(req.prompt) + [first])
        self._active[slot] = item
        self._maybe_finish(item)

    def _chunk_step(self) -> None:
        """Run ONE chunk of the oldest in-flight chunked prefill; on
        the final chunk the request joins the decode set exactly as a
        single-shot admission would (token-identical: the final chunk's
        sampling key is the unchunked prefill's). Interactive chunked
        prefills take the turn before batch ones (ISSUE 13) — the
        chunk turn is a decode-slot-adjacent resource, and the class
        order is the service order."""
        reg = self.registry
        slot = next(
            (s for s, (it, _) in self._prefilling.items()
             if it.req.slo == "interactive"),
            next(iter(self._prefilling)),
        )
        item, state = self._prefilling[slot]
        if item.deadline is not None and time.monotonic() > item.deadline:
            # A dead-on-arrival stream must not keep stalling everyone
            # else's decode steps for its remaining chunks — abandon it
            # now, exactly like the queued-deadline expiry (504).
            del self._prefilling[slot]
            self.engine.pool.free(slot)
            item.slot = None
            reg.counter("serving/expired_total").inc()
            if not item.future.done():
                item.future.set_exception(DeadlineExceeded(
                    f"deadline ({item.req.deadline_s:.3f}s) passed "
                    "mid-chunked-prefill"
                ))
            return
        t_chunk = time.monotonic()
        try:
            with span("serve_prefill_chunk"):
                done, first, last_logits = self.engine.prefill_step(state)
        except Exception as e:  # noqa: BLE001 — one bad chunk must not
            # take the serve loop down
            log.exception("prefill chunk failed; failing request")
            self._prefilling.pop(slot, None)
            self.engine.pool.free(slot)
            item.slot = None
            if not item.future.done():
                item.future.set_exception(e)
            reg.counter("serving/errors_total").inc()
            if isinstance(e, EngineStepError):
                self._fail_active(e)
            return
        if item.spans is not None:
            item.spans.append(close_span(
                "prefill_chunk", t_chunk, tags={"chunk": state.idx}
            ))
        if not done:
            return
        del self._prefilling[slot]
        # Chunked prefill wall = admission to final chunk (decode steps
        # interleave inside it — that is the point, and what an
        # operator reading serving/prefill for a chunked request should
        # see).
        reg.histogram("serving/prefill").record(
            time.monotonic() - item.t_admit
        )
        self._finish_prefill(item, first, last_logits)

    # ----------------------------------------------------------- retire

    def _maybe_finish(self, item: _InFlight) -> None:
        req, truncated = item.req, None
        done = (
            len(item.tokens) >= req.max_new_tokens
            or (req.eos_id is not None and item.last_token == req.eos_id)
        )
        if not done and len(item.tokens) >= item.max_new_eff:
            # Brownout level-2 cap (ISSUE 13): retire early with what
            # we have — a prefix of the full stream, labeled so the
            # client knows the fleet cheapened it, not the model.
            done, truncated = True, "brownout"
            self.registry.counter(
                "serving/brownout_truncated_total"
            ).inc()
        if not done and item.deadline is not None \
                and time.monotonic() > item.deadline:
            done, truncated = True, "deadline"
        if not done and item.slot is not None and (
            len(req.prompt) + len(item.tokens)
            >= self.engine.model_cfg.max_len
        ):
            done, truncated = True, "max_len"  # admission makes this rare
        if done:
            self._retire(item, truncated=truncated)

    def _retire(self, item: _InFlight, *, truncated: str | None) -> None:
        if item.slot is not None and item.slot in self._active:
            del self._active[item.slot]
        if item.slot is not None:
            self.engine.pool.free(item.slot)
            self._drop_draft(item.slot)
        self._close_decode_segment(item)
        self._resolve(
            item,
            Result(
                tokens=item.tokens,
                prompt_len=len(item.req.prompt),
                truncated=truncated,
                spec_drafted=item.spec_drafted,
                spec_accepted=item.spec_accepted,
            ),
        )

    def _resolve(self, item: _InFlight, result: Result) -> None:
        now = time.monotonic()
        result.queue_wait_s = (
            (item.t_admit or now) - item.t_submit
        )
        result.ttft_s = (
            item.t_first - item.t_submit if item.t_first else None
        )
        result.total_s = now - item.t_submit
        reg = self.registry
        reg.histogram("serving/e2e").record(result.total_s)
        reg.histogram(
            f"serving/e2e_{item.req.slo}"
        ).record(result.total_s)
        reg.counter("serving/completed_total").inc()
        # Handoff accounting: the DELIVERING replica owns the whole
        # stream (resume counts the first token too), the prefill leg
        # counts zero — so fleet-summed generated_tokens stays exact
        # whether a handoff completes or falls back to the full path
        # after a successful prefill leg.
        generated = 0 if item.req.kind == "prefill" else len(
            result.tokens
        )
        reg.counter("serving/generated_tokens_total").inc(generated)
        if item.spans is not None:
            result.spans = item.spans
            self.exemplars.record(
                "serving/e2e", result.total_s,
                item.req.trace["trace_id"],
            )
        if not item.future.set_running_or_notify_cancel():
            return  # caller gave up; nothing to deliver
        item.future.set_result(result)

    # ------------------------------------------------------------- stats

    def stats_line(self) -> dict:
        """A schema-v6 ``kind="serving"`` JSONL line: the serving
        counterpart of the training window line (validated in tier-1;
        the frontend serves the latest one at ``/window`` and
        examples/gpt2/serve.py appends them to ``serving.jsonl``).
        Paged pools (serving/paged_kv.py) add their block/prefix-cache
        fields to the ``serving`` object — the v6 additions."""
        reg = self.registry
        counters = {
            k: v for k, v in reg.counter_values().items()
            if k.startswith(("serving/", "compile/"))
        }
        gauges = {
            k: v for k, v in reg.gauge_values().items()
            if k.startswith("serving/")
        }
        hists = reg.histogram_summaries()
        derived = {}
        for name in ("queue_wait", "prefill", "ttft", "tpot", "e2e"):
            h = hists.get(f"serving/{name}")
            if h and h["count"]:
                derived[f"{name}_p50"] = h["p50"]
                derived[f"{name}_p95"] = h["p95"]
        serving = {
            # Chunk-prefilling requests count as active: they hold a
            # slot and stall one chunk per loop iteration.
            "active_requests": len(self._active) + len(self._prefilling),
            "queue_depth": self.queue_depth(),
            "slots": self.engine.pool.num_slots,
            "kv_occupancy": self.engine.pool.occupancy,
            "post_warmup_recompiles": (
                self.engine.post_warmup_recompiles()
            ),
            "draining": 1 if self._draining else 0,
        }
        if self.spec_k > 0:
            # Schema-v8 speculation keys (ISSUE 11): how many tokens a
            # verify step commits and how often drafts land — the
            # measured numbers behind any TPOT-speedup claim.
            steps = counters.get("serving/spec_request_steps", 0)
            drafted = counters.get("serving/spec_drafted_total", 0)
            accepted = counters.get("serving/spec_accepted_total", 0)
            serving["spec_k"] = self.spec_k
            serving["draft_hit_rate"] = (
                accepted / drafted if drafted else 0.0
            )
            serving["accepted_per_step"] = (
                (steps + accepted) / steps if steps else 0.0
            )
        # Schema-v10 overload keys (ISSUE 13): the SLO-class split and
        # the brownout ladder's state — the per-class latency story an
        # operator reads to see WHO is paying for an overload.
        for cls in SLO_CLASSES:
            for name in ("queue_wait", "ttft", "tpot"):
                h = hists.get(f"serving/{name}_{cls}")
                if h and h["count"]:
                    serving[f"{name}_p95_{cls}"] = h["p95"]
        serving["shed_interactive"] = int(
            counters.get("serving/shed_interactive_total", 0)
        )
        serving["shed_batch"] = int(
            counters.get("serving/shed_batch_total", 0)
        )
        serving["preempted_batch"] = int(
            counters.get("serving/preempted_total", 0)
        )
        serving["brownout_level"] = int(self._overload.level)
        serving["brownout_transitions"] = int(
            self._overload.transitions()
        )
        paged = getattr(self.engine.pool, "paged_stats", None)
        if callable(paged):
            serving.update(paged())
        # Schema-v11 precision keys (ISSUE 15): what precision this
        # replica is actually serving at and what it costs vs f32 —
        # stamped only when the engine holds quantized weights (an
        # unquantized line carries none, like every earlier bump).
        pstats = getattr(self.engine, "precision_stats", None)
        pstats = pstats() if callable(pstats) else None
        if pstats:
            serving["weight_bits"] = pstats["weight_bits"]
            serving["param_bytes"] = pstats["param_bytes"]
            serving["param_bytes_f32"] = pstats["param_bytes_f32"]
            serving["quantized_params"] = pstats["quantized_params"]
        return {
            "schema_version": schema.SERVING_SCHEMA_VERSION,
            "kind": "serving",
            "step": int(
                counters.get("serving/decode_steps", 0)
            ),
            "time_unix": time.time(),
            "session_start_unix": self._start_unix,
            "host": 0,
            "metrics": {},
            "counters": counters,
            "gauges": gauges,
            "derived": derived,
            "serving": serving,
        }


def default_registry():  # convenience re-export for the frontend/tools
    return registry_mod.default_registry()
