"""ctypes bindings for the C++ host-runtime libraries (native/).

The reference leaned on TensorFlow's C++ runtime for its input pipeline
and on CUDA ``tf.custom_op`` kernels (SURVEY.md §2c). The TPU-native
split implemented here:

- device kernels → Pallas (ops/), because that is the supported kernel
  path on TPU;
- host runtime → C++ in ``native/``: threaded augmentation/normalization
  (libfastdata) feeding the device-prefetch queue, and an XLA FFI
  custom-call library (libffi_ops) as the C++ compiled-op scaffold on
  the CPU backend.

Everything degrades gracefully: if the toolchain or headers are missing
the numpy/Pallas fallbacks are used and ``available()`` returns False.
Build happens lazily (``make -C native``) on first use.
"""

from __future__ import annotations

import ctypes
import functools
import logging
import os
import subprocess

import numpy as np

log = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)


@functools.lru_cache(maxsize=None)
def _load(name: str):
    path = os.path.join(_NATIVE_DIR, "build", f"lib{name}.so")
    if not os.path.exists(path):
        if not os.path.isdir(_NATIVE_DIR):
            return None
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, f"build/lib{name}.so"],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except Exception as e:  # toolchain missing → fallbacks
            log.warning("native build of %s failed: %s", name, e)
            return None
    try:
        return ctypes.CDLL(path)
    except OSError as e:
        log.warning("failed to load %s: %s", path, e)
        return None


def available(name: str = "fastdata") -> bool:
    return _load(name) is not None


# ------------------------------------------------------------- fastdata


def crop_flip_normalize(
    images_u8: np.ndarray,
    ys: np.ndarray,
    xs: np.ndarray,
    flips: np.ndarray,
    mean: np.ndarray,
    std: np.ndarray,
    *,
    pad: int = 4,
    out_size: tuple[int, int] | None = None,
    threads: int | None = None,
) -> np.ndarray | None:
    """Threaded reflect-pad crop + flip + normalize; None if unavailable.

    images_u8: [B, H, W, C] uint8. ys/xs: [B] int32 offsets in padded
    coords (0..2*pad). flips: [B] bool/uint8. Returns [B, h, w, C] f32.
    """
    lib = _load("fastdata")
    if lib is None:
        return None
    b, h, w, c = images_u8.shape
    oh, ow = out_size or (h, w)
    out = np.empty((b, oh, ow, c), np.float32)
    images_u8 = np.ascontiguousarray(images_u8)
    inv_std = np.ascontiguousarray(1.0 / std.astype(np.float32))
    mean = np.ascontiguousarray(mean.astype(np.float32))
    ys = np.ascontiguousarray(ys.astype(np.int32))
    xs = np.ascontiguousarray(xs.astype(np.int32))
    flips = np.ascontiguousarray(flips.astype(np.uint8))
    nthreads = threads or min(16, os.cpu_count() or 1)
    u8 = ctypes.POINTER(ctypes.c_uint8)
    f32 = ctypes.POINTER(ctypes.c_float)
    i32 = ctypes.POINTER(ctypes.c_int32)
    lib.crop_flip_normalize_u8(
        images_u8.ctypes.data_as(u8),
        out.ctypes.data_as(f32),
        ys.ctypes.data_as(i32),
        xs.ctypes.data_as(i32),
        flips.ctypes.data_as(u8),
        mean.ctypes.data_as(f32),
        inv_std.ctypes.data_as(f32),
        *map(ctypes.c_int64, (b, h, w, oh, ow, c, pad, nthreads)),
    )
    return out


def normalize(
    images_u8: np.ndarray,
    mean: np.ndarray,
    std: np.ndarray,
    *,
    threads: int | None = None,
) -> np.ndarray | None:
    """Threaded (x/255 - mean)/std on a uint8 NHWC batch; None if unavailable."""
    lib = _load("fastdata")
    if lib is None:
        return None
    b, h, w, c = images_u8.shape
    out = np.empty((b, h, w, c), np.float32)
    images_u8 = np.ascontiguousarray(images_u8)
    inv_std = np.ascontiguousarray(1.0 / std.astype(np.float32))
    mean = np.ascontiguousarray(mean.astype(np.float32))
    nthreads = threads or min(16, os.cpu_count() or 1)
    u8 = ctypes.POINTER(ctypes.c_uint8)
    f32 = ctypes.POINTER(ctypes.c_float)
    lib.normalize_u8(
        images_u8.ctypes.data_as(u8),
        out.ctypes.data_as(f32),
        mean.ctypes.data_as(f32),
        inv_std.ctypes.data_as(f32),
        *map(ctypes.c_int64, (b, h * w, c, nthreads)),
    )
    return out


# ------------------------------------------------------------- fastjpeg


def decode_augment_batch(
    jpegs: "list[bytes]",
    *,
    train: bool,
    out_size: int,
    seeds: np.ndarray | None,
    mean: np.ndarray,
    std: np.ndarray,
    threads: int | None = None,
) -> "tuple[np.ndarray, np.ndarray] | None":
    """One threaded C++ stage: JPEG decode (DCT-scaled) + ResNet
    random-resized-crop (train) / central 87.5% crop (eval) + bilinear
    resize + flip + normalize. ``seeds``: [n] uint64, one splitmix64
    stream per image (ignored for eval). Returns ``(images f32
    [n, S, S, 3], ok uint8 [n])`` or None when libfastjpeg (libjpeg) is
    unavailable. Failed decodes are zero-filled with ok == 0."""
    lib = _load("fastjpeg")
    if lib is None:
        return None
    n = len(jpegs)
    data = np.frombuffer(b"".join(jpegs), np.uint8)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum([len(j) for j in jpegs], out=offsets[1:])
    if seeds is None:
        seeds = np.zeros(n, np.uint64)
    seeds = np.ascontiguousarray(seeds.astype(np.uint64))
    out = np.empty((n, out_size, out_size, 3), np.float32)
    ok = np.empty(n, np.uint8)
    inv_std = np.ascontiguousarray(1.0 / std.astype(np.float32))
    mean = np.ascontiguousarray(mean.astype(np.float32))
    nthreads = threads or min(16, os.cpu_count() or 1)
    u8 = ctypes.POINTER(ctypes.c_uint8)
    f32 = ctypes.POINTER(ctypes.c_float)
    i64 = ctypes.POINTER(ctypes.c_int64)
    u64 = ctypes.POINTER(ctypes.c_uint64)
    lib.fj_decode_augment_batch.restype = ctypes.c_int64
    lib.fj_decode_augment_batch(
        data.ctypes.data_as(u8),
        offsets.ctypes.data_as(i64),
        ctypes.c_int64(n),
        ctypes.c_int32(1 if train else 0),
        ctypes.c_int32(out_size),
        seeds.ctypes.data_as(u64),
        mean.ctypes.data_as(f32),
        inv_std.ctypes.data_as(f32),
        out.ctypes.data_as(f32),
        ctypes.c_int64(nthreads),
        ok.ctypes.data_as(u8),
    )
    return out, ok


def jpeg_dims(data: bytes) -> "tuple[int, int] | None":
    """Header-only (height, width); None on error or missing lib."""
    lib = _load("fastjpeg")
    if lib is None:
        return None
    arr = np.frombuffer(data, np.uint8)
    h = ctypes.c_int32()
    w = ctypes.c_int32()
    rc = lib.fj_jpeg_dims(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int64(len(data)),
        ctypes.byref(h),
        ctypes.byref(w),
    )
    return None if rc else (h.value, w.value)


# ------------------------------------------------------------- ffi_ops


@functools.lru_cache(maxsize=None)
def register_ffi_targets() -> bool:
    """Register the C++ XLA custom-calls with jax (CPU backend).

    Returns True when ``fused_cross_entropy_fwd`` is callable via
    ``jax.ffi.ffi_call`` (see ``ffi_cross_entropy``)."""
    lib = _load("ffi_ops")
    if lib is None:
        return False
    try:
        import jax.ffi

        lib.fused_cross_entropy_fwd_handler.restype = ctypes.c_void_p
        handler = lib.fused_cross_entropy_fwd_handler()
        ctypes.pythonapi.PyCapsule_New.restype = ctypes.py_object
        ctypes.pythonapi.PyCapsule_New.argtypes = (
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
        )
        capsule = ctypes.pythonapi.PyCapsule_New(
            ctypes.c_void_p(handler), None, None
        )
        jax.ffi.register_ffi_target(
            "tfe_fused_cross_entropy_fwd", capsule, platform="cpu"
        )
        return True
    except Exception as e:
        log.warning("FFI registration failed: %s", e)
        return False


def ffi_cross_entropy(logits, labels):
    """Per-example (nll, lse) via the C++ XLA custom call (CPU backend)."""
    import jax
    import jax.numpy as jnp

    if not register_ffi_targets():
        raise RuntimeError("native ffi_ops library unavailable")
    n = logits.shape[0]
    out_types = (
        jax.ShapeDtypeStruct((n,), jnp.float32),
        jax.ShapeDtypeStruct((n,), jnp.float32),
    )
    return jax.ffi.ffi_call("tfe_fused_cross_entropy_fwd", out_types)(
        logits.astype(jnp.float32), labels.astype(jnp.int32)
    )
