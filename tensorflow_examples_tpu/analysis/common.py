"""Shared graftlint plumbing: findings, comments, suppression baseline.

A :class:`Finding` carries both a display location (``path:line``) and
a **stable key** deliberately free of line numbers —
``pass:path:scope:detail`` — so the committed suppression baseline
(``tools/graftlint_baseline.json``) survives unrelated edits above a
finding. The baseline maps keys to *accepted counts*: a key is
suppressed while its current occurrence count stays at or below the
accepted one, and the excess occurrences surface as findings — adding
a second unguarded read of an attribute in the same function is a new
finding even though the first was accepted.

Inline escape hatch: any source line whose comment contains
``graftlint: ignore`` is skipped by every pass (use sparingly, with
the justification in the surrounding comment; the baseline is the
audited mechanism).
"""

from __future__ import annotations

import ast
import collections
import dataclasses
import io
import json
import os
import tokenize


@dataclasses.dataclass(frozen=True)
class Finding:
    pass_name: str      # "locks" | "jax" | "schema"
    path: str           # repo-relative, forward slashes
    line: int           # 1-indexed display line
    scope: str          # Class.method / function / "-" (module level)
    detail: str         # stable discriminator within the scope
    message: str        # human-facing explanation

    @property
    def key(self) -> str:
        """Line-number-free identity the baseline is keyed by."""
        return f"{self.pass_name}:{self.path}:{self.scope}:{self.detail}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: [{self.pass_name}] "
            f"{self.scope}: {self.message}"
        )


# ------------------------------------------------------------ source IO


def rel_path(path: str, repo_root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), os.path.abspath(repo_root))
    return rel.replace(os.sep, "/")


def iter_python_files(paths) -> list[str]:
    """Expand files/directories into a sorted list of ``.py`` files."""
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                out.extend(
                    os.path.join(root, f)
                    for f in sorted(files) if f.endswith(".py")
                )
        elif p.endswith(".py"):
            out.append(p)
    return sorted(dict.fromkeys(out))


class SourceFile:
    """One parsed file: AST + per-line comments + scope resolution."""

    def __init__(self, path: str, repo_root: str, text: str | None = None):
        self.path = path
        self.rel = rel_path(path, repo_root)
        if text is None:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                io.StringIO(text).readline
            ):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:  # torn source: AST parsed, comments best-effort
            pass
        # Parent links + enclosing-scope names for stable keys.
        self._parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(node)

    def scope_of(self, node: ast.AST) -> str:
        """Dotted Class.method / function name enclosing ``node``
        ("-" at module level)."""
        parts: list[str] = []
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                parts.append(cur.name)
            cur = self._parents.get(cur)
        return ".".join(reversed(parts)) or "-"

    def ignored(self, lineno: int) -> bool:
        """True when the line (or the def/class line of a decorated
        statement) carries a ``graftlint: ignore`` comment."""
        c = self.comments.get(lineno, "")
        return "graftlint: ignore" in c

    def comment(self, lineno: int) -> str:
        return self.comments.get(lineno, "")


def load_source(path: str, repo_root: str) -> SourceFile | None:
    try:
        return SourceFile(path, repo_root)
    except (OSError, SyntaxError):
        return None


def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — display-only fallback
        return f"<{type(node).__name__}>"


# ------------------------------------------------------------- baseline


class Baseline:
    """Committed suppression baseline: finding key -> accepted count."""

    VERSION = 1

    def __init__(self, counts: dict[str, int] | None = None):
        self.counts: dict[str, int] = dict(counts or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.isfile(path):
            return cls()
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or doc.get("version") != cls.VERSION:
            raise ValueError(
                f"{path}: not a graftlint baseline (expected "
                f'{{"version": {cls.VERSION}, "findings": {{...}}}})'
            )
        findings = doc.get("findings")
        if not isinstance(findings, dict) or not all(
            isinstance(k, str) and isinstance(v, int) and v > 0
            for k, v in findings.items()
        ):
            raise ValueError(
                f"{path}: baseline findings must map keys to positive "
                "counts"
            )
        return cls(findings)

    def save(self, path: str) -> None:
        doc = {"version": self.VERSION, "findings": dict(sorted(
            self.counts.items()
        ))}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")

    def total(self) -> int:
        return sum(self.counts.values())

    @classmethod
    def from_findings(cls, findings) -> "Baseline":
        counts: dict[str, int] = collections.Counter(
            f.key for f in findings
        )
        return cls(dict(counts))


def apply_baseline(findings, baseline: Baseline):
    """Split findings into (reported, suppressed, stale_keys).

    Per key, the first ``accepted`` occurrences are suppressed and the
    rest reported. ``stale_keys`` are baseline entries whose finding no
    longer occurs (or occurs fewer times) — candidates for removal, so
    the baseline only ever shrinks toward the truth.
    """
    by_key: dict[str, list] = collections.defaultdict(list)
    for f in findings:
        by_key[f.key].append(f)
    reported, suppressed = [], []
    for key, group in by_key.items():
        accepted = baseline.counts.get(key, 0)
        group = sorted(group, key=lambda f: f.line)
        suppressed.extend(group[:accepted])
        reported.extend(group[accepted:])
    stale = sorted(
        key for key, accepted in baseline.counts.items()
        if len(by_key.get(key, ())) < accepted
    )
    reported.sort(key=lambda f: (f.path, f.line, f.detail))
    return reported, suppressed, stale
