"""JAX hazard pass (graftlint pass 2, ISSUE 14 tentpole).

Three hazard families, all tuned to this repo's serving/training
idioms (the engine's AOT-warmed ladder of ``jax.jit(...,
donate_argnums=...)`` programs, the ``_run_compiled`` donation-recovery
funnel, the one-bulk-sync-per-step decode hot path):

**(a) Traced-value branching** — inside functions reachable from a
``jax.jit`` entry point (decorated, passed directly, or bound through
``functools.partial``), a Python ``if``/``while``/ternary on a traced
parameter recompiles per value or fails at trace time. The pass
resolves partial-bound leading arguments as static (the engine's
``partial(self._impl, bucket)`` ladder idiom), honors
``static_argnums``/``static_argnames``, treats ``del X  # static`` as
a static declaration, and skips the obviously-host-side shapes
(``is None`` checks, comparisons against string constants,
``isinstance``) plus config-ish parameter names. Reachability is a
same-module call-graph closure (depth-capped), matched by bare name —
heuristic on purpose; the fixtures pin exactly what it must catch.

**(b) Implicit host syncs** — ``.item()``, ``np.asarray``/``np.array``,
``jax.device_get`` and ``float()/int()/bool()`` on traced values force
a device->host transfer (or a trace-time concretization error). Inside
jit-reachable code they are always flagged; on the host side they are
flagged inside functions carrying the ``# graftlint: hot-path`` marker
comment on their ``def`` line — the decode/verify host entries, where
every sync beyond the accepted one-bulk-``np.asarray``-per-step shows
up directly in TPOT. The accepted syncs live in the committed
baseline: explicit and counted.

**(c) Use-after-donate** — an argument passed at a donated position of
a ``donate_argnums`` program is consumed; reading it afterwards is the
"Array has been deleted" heisenbug. The pass registers donating
callables (``F = jax.jit(fn, donate_argnums=(1,))``, including the
engine's ``self._fns = {b: sentinel.wrap(jax.jit(...), ...)}`` ladder
dicts) and — repo-natively — sees through
``self._run_compiled(kind, fn, *args)``, the engine's one donation
funnel, mapping ``donate_argnums`` onto ``args``. After a donating
call, any read of the same expression (a name or dotted attribute
chain) before it is reassigned flags. The engine's own pattern —
donated ``self.pool.k/v`` reassigned as targets of the very call
statement — passes by construction.
"""

from __future__ import annotations

import ast

from tensorflow_examples_tpu.analysis import common

# Parameter names that are host-side configuration by strong repo
# convention: branching on them is static dispatch, not traced control
# flow.
_STATIC_NAMEISH = frozenset({
    "self", "cls", "cfg", "config", "model_cfg", "impl", "mesh",
    "dtype", "axis", "axis_name", "name", "kind", "bucket", "mode",
})

_HOT_PATH_MARK = "graftlint: hot-path"
_SYNC_MODULES = {"np", "numpy"}


# --------------------------------------------------------------- roots


class _JitRoot:
    def __init__(self, func_name: str, bound: int, static: set[str],
                 donate: tuple[int, ...],
                 static_nums: tuple[int, ...] = (),
                 donate_names: tuple[str, ...] = ()):
        self.func_name = func_name  # bare function/method name
        self.bound = bound          # leading positional args bound by partial
        self.static = static        # statically-known parameter names
        self.donate = donate        # donate_argnums of the WRAPPED callable
        self.donate_names = donate_names  # donate_argnames: resolved to
        #                                   indices against the def in
        #                                   _collect_roots_and_donors
        self.static_nums = static_nums  # static_argnums: indices into
        #                                 the wrapped callable's args,
        #                                 resolved against the def in
        #                                 _reachable (self excluded,
        #                                 partial binds offset)


def _const_int_tuple(node: ast.AST | None) -> tuple[int, ...]:
    if node is None:
        return ()
    try:
        v = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return ()
    if isinstance(v, int):
        return (v,)
    if isinstance(v, (tuple, list)) and all(
        isinstance(i, int) for i in v
    ):
        return tuple(v)
    return ()


def _const_str_tuple(node: ast.AST | None) -> tuple[str, ...]:
    if node is None:
        return ()
    try:
        v = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return ()
    if isinstance(v, str):
        return (v,)
    if isinstance(v, (tuple, list)) and all(
        isinstance(i, str) for i in v
    ):
        return tuple(v)
    return ()


def _is_jit_callable(node: ast.AST) -> bool:
    """``jax.jit`` / bare ``jit`` as a call target."""
    if isinstance(node, ast.Name):
        return node.id == "jit"
    return (
        isinstance(node, ast.Attribute) and node.attr == "jit"
        and isinstance(node.value, ast.Name) and node.value.id == "jax"
    )


def _is_partial(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "partial"
    return isinstance(node, ast.Attribute) and node.attr == "partial"


def _target_name(node: ast.AST) -> tuple[str, int] | None:
    """Resolve a jit() first argument to (bare name, n bound leading
    args): ``f`` -> (f, 0); ``self._impl`` -> (_impl, 0);
    ``partial(self._impl, b)`` / ``functools.partial(f, a, b)`` ->
    (name, len(bound))."""
    if isinstance(node, ast.Name):
        return node.id, 0
    if isinstance(node, ast.Attribute):
        return node.attr, 0
    if isinstance(node, ast.Call) and _is_partial(node.func) and node.args:
        inner = _target_name(node.args[0])
        if inner is not None:
            return inner[0], inner[1] + len(node.args) - 1
    return None


def _find_jit_call(node: ast.AST) -> ast.Call | None:
    """The jax.jit(...) call inside ``node`` (sees through wrapper
    calls like ``sentinel.wrap(jax.jit(...), label)`` and dict/list
    comprehensions)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _is_jit_callable(sub.func):
            return sub
    return None


def _jit_root_from_call(call: ast.Call) -> _JitRoot | None:
    if not call.args:
        return None
    resolved = _target_name(call.args[0])
    if resolved is None:
        return None
    name, bound = resolved
    static: set[str] = set()
    static_nums: tuple[int, ...] = ()
    donate: tuple[int, ...] = ()
    donate_names: tuple[str, ...] = ()
    for kw in call.keywords:
        if kw.arg in ("static_argnames",):
            static.update(_const_str_tuple(kw.value))
        elif kw.arg == "static_argnums":
            static_nums = _const_int_tuple(kw.value)
        elif kw.arg == "donate_argnums":
            donate = _const_int_tuple(kw.value)
        elif kw.arg == "donate_argnames":
            donate_names = _const_str_tuple(kw.value)
    if not name:
        return None
    return _JitRoot(name, bound, static, donate, static_nums,
                    donate_names)


def _collect_roots_and_donors(src: common.SourceFile):
    """(roots by function name, donating callables).

    Donating callables maps a call-site spelling — the bare final name
    of the assigned target (``_decode_fns``, ``step_fn``) — to the
    wrapped program's donate_argnums."""
    roots: dict[str, _JitRoot] = {}
    donors: dict[str, tuple[int, ...]] = {}
    params_by_name: dict[str, list[str]] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params_by_name.setdefault(
                node.name, [a.arg for a in node.args.args]
            )
    for node in ast.walk(src.tree):
        # @jax.jit / @partial(jax.jit, ...) decorated defs
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                call = None
                if isinstance(dec, ast.Call) and _is_jit_callable(dec.func):
                    call = dec
                elif isinstance(dec, ast.Call) and _is_partial(dec.func) \
                        and dec.args and _is_jit_callable(dec.args[0]):
                    call = dec
                elif _is_jit_callable(dec):
                    roots.setdefault(
                        node.name, _JitRoot(node.name, 0, set(), ())
                    )
                    continue
                if call is None:
                    continue
                static: set[str] = set()
                donate: tuple[int, ...] = ()
                params = [a.arg for a in node.args.args]
                for kw in call.keywords:
                    if kw.arg == "static_argnames":
                        static.update(_const_str_tuple(kw.value))
                    elif kw.arg == "static_argnums":
                        for i in _const_int_tuple(kw.value):
                            if 0 <= i < len(params):
                                static.add(params[i])
                    elif kw.arg == "donate_argnums":
                        donate = _const_int_tuple(kw.value)
                    elif kw.arg == "donate_argnames":
                        donate = donate + tuple(
                            params.index(n)
                            for n in _const_str_tuple(kw.value)
                            if n in params
                        )
                roots[node.name] = _JitRoot(node.name, 0, static, donate)
                if donate:
                    # A decorated donating def is called by its own
                    # name — it is a donor exactly like an assigned
                    # jitted callable (the docs advertise decorators
                    # as pass-(c) roots).
                    donors[node.name] = donate
        elif isinstance(node, ast.Assign):
            call = _find_jit_call(node.value)
            if call is None:
                continue
            root = _jit_root_from_call(call)
            if root is None:
                continue
            if root.donate_names:
                # donate_argnames name the WRAPPED callable's params;
                # a call site donates at position (param index, minus
                # self, minus any partial-bound leading args).
                params = params_by_name.get(root.func_name, [])
                base = 1 if params[:1] == ["self"] else 0
                root.donate = root.donate + tuple(
                    j for j in (
                        params.index(n) - base - root.bound
                        for n in root.donate_names if n in params
                    ) if j >= 0
                )
            # static_argnums indexes the wrapped callable's params —
            # resolved later against the def; record the root.
            existing = roots.get(root.func_name)
            if existing is None or root.donate:
                roots[root.func_name] = root
            if root.donate:
                for t in node.targets:
                    tail = None
                    if isinstance(t, ast.Name):
                        tail = t.id
                    elif isinstance(t, ast.Attribute):
                        tail = t.attr
                    if tail:
                        donors[tail] = root.donate
    return roots, donors


# --------------------------------------------------------- reachability


def _index_functions(src: common.SourceFile):
    fns: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns.setdefault(node.name, []).append(node)
    return fns


def _called_names(fn: ast.FunctionDef) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name):
                out.add(node.func.id)
            elif isinstance(node.func, ast.Attribute) and isinstance(
                node.func.value, ast.Name
            ) and node.func.value.id == "self":
                out.add(node.func.attr)
    return out


def _reachable(roots: dict[str, "_JitRoot"], fns, max_depth: int = 3):
    """{function name: static param names} closure from the jit roots.
    Non-root reachable functions get an empty static set (everything
    they receive may be traced)."""
    seen: dict[str, set[str]] = {}
    root_static: dict[str, set[str]] = {}
    frontier: list[tuple[str, int, set[str]]] = []
    for name, root in roots.items():
        defs = fns.get(name, [])
        static = set(root.static)
        for d in defs:
            params = [a.arg for a in d.args.args]
            base = 1 if params[:1] == ["self"] else 0
            static.update(params[base:base + root.bound])
            # static_argnums index the WRAPPED callable's positional
            # args — i.e. past `self` and past any partial-bound
            # leading args.
            for i in root.static_nums:
                j = base + root.bound + i
                if 0 <= j < len(params):
                    static.add(params[j])
        root_static[name] = static
        frontier.append((name, 0, static))
    while frontier:
        name, depth, static = frontier.pop()
        if name in seen:
            seen[name] &= static  # keep only commonly-static names
            continue
        seen[name] = set(static)
        if depth >= max_depth:
            continue
        for d in fns.get(name, []):
            for callee in _called_names(d):
                if callee in fns and callee not in seen:
                    frontier.append((callee, depth + 1, set()))
    # A root's OWN static declaration is authoritative for its body:
    # when the BFS reached it first as some other root's callee (empty
    # static set), the intersection above clobbered the declared
    # statics and manufactured traced-branch findings on host-dispatch
    # branches the jit boundary makes concrete.
    for name, static in root_static.items():
        if name in seen:
            seen[name] |= static
    return seen


# ------------------------------------------------------------- checks


def _traced_params(fn: ast.FunctionDef, static: set[str],
                   src: common.SourceFile) -> set[str]:
    args = fn.args
    names = [a.arg for a in (
        args.posonlyargs + args.args + args.kwonlyargs
    )]
    traced = {
        n for n in names
        if n not in static and n not in _STATIC_NAMEISH
    }
    # `del bucket  # static: ...` — the repo's static-marker idiom.
    for node in ast.walk(fn):
        if isinstance(node, ast.Delete) and "static" in src.comment(
            node.lineno
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    traced.discard(t.id)
    return traced


def _names_in(node: ast.AST) -> set[str]:
    return {
        n.id for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def _branch_names(test: ast.AST) -> set[str]:
    """Names a branch condition actually *traces* on: every Name load
    except those only ever passed to ``len()`` — ``len`` of a pytree
    tuple (``if len(kv) == 4:``) or of a traced array is host-side
    structure/shape, the repo's quantized-vs-f32 dispatch idiom."""
    all_names: dict[str, int] = {}
    len_names: dict[str, int] = {}
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            all_names[n.id] = all_names.get(n.id, 0) + 1
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "len":
            for arg in n.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and isinstance(
                        sub.ctx, ast.Load
                    ):
                        len_names[sub.id] = len_names.get(sub.id, 0) + 1
    return {
        name for name, count in all_names.items()
        if count > len_names.get(name, 0) and name != "len"
    }


def _static_shaped_test(test: ast.AST) -> bool:
    """Conditions that are host-side dispatch even when they mention a
    parameter: None checks, string-constant comparisons, isinstance."""
    if isinstance(test, ast.Compare):
        if any(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return True
        operands = [test.left] + list(test.comparators)
        if any(
            isinstance(o, ast.Constant) and isinstance(o.value, str)
            for o in operands
        ):
            return True
    if isinstance(test, ast.Call) and isinstance(test.func, ast.Name) \
            and test.func.id in ("isinstance", "callable", "hasattr"):
        return True
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _static_shaped_test(test.operand)
    if isinstance(test, ast.BoolOp):
        return all(_static_shaped_test(v) for v in test.values)
    return False


def _sync_call_kind(node: ast.Call, traced: set[str] | None) -> str | None:
    """Classify a call as a host sync. ``traced=None`` means "flag
    regardless of the argument" (hot-path mode for the unambiguous
    syncs); otherwise float/int/bool only flag on traced names."""
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "item" and not node.args:
            return ".item()"
        if f.attr in ("asarray", "array") and isinstance(
            f.value, ast.Name
        ) and f.value.id in _SYNC_MODULES:
            if traced is None or (
                node.args and _names_in(node.args[0]) & traced
            ):
                return f"np.{f.attr}"
        if f.attr == "device_get":
            return "jax.device_get"
    if isinstance(f, ast.Name) and f.id in ("float", "int", "bool"):
        # Only a sync when applied to a TRACED value — in hot-path
        # mode (traced=None) the argument's host/device nature is
        # unknowable statically, and int() over host lists/ints is the
        # bread and butter of the decode loop, so only the unambiguous
        # syncs flag there.
        if traced is not None and node.args and isinstance(
            node.args[0], ast.Name
        ) and node.args[0].id in traced:
            return f"{f.id}()"
    return None


def _walk_shallow(fn):
    """Walk ``fn``'s body WITHOUT descending into nested def/lambda
    subtrees — ``ast.walk`` does not prune, and a nested function's
    parameters shadow the outer traced set (its body is its own,
    separately-reached scope)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _check_jitted_fn(src, fn, static, findings) -> None:
    traced = _traced_params(fn, static, src)
    scope = src.scope_of(fn) or "-"
    scope = f"{scope}.{fn.name}" if scope != "-" else fn.name
    for node in _walk_shallow(fn):
        test = None
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            test = node.test
        if test is not None and not _static_shaped_test(test):
            hits = sorted(_branch_names(test) & traced)
            if hits and not src.ignored(node.lineno):
                findings.append(common.Finding(
                    pass_name="jax", path=src.rel, line=node.lineno,
                    scope=scope,
                    detail=f"traced-branch:{','.join(hits)}",
                    message=(
                        "python branch on traced value(s) "
                        f"{', '.join(hits)} inside a jit-reachable "
                        "function (use lax.cond/select, or mark the "
                        "argument static)"
                    ),
                ))
        if isinstance(node, ast.Call):
            kind = _sync_call_kind(node, traced)
            if kind and not src.ignored(node.lineno):
                findings.append(common.Finding(
                    pass_name="jax", path=src.rel, line=node.lineno,
                    scope=scope, detail=f"traced-sync:{kind}",
                    message=(
                        f"host sync {kind} inside a jit-reachable "
                        "function (concretizes a traced value)"
                    ),
                ))


def _check_hot_path_fn(src, fn, findings) -> None:
    scope = src.scope_of(fn) or "-"
    scope = f"{scope}.{fn.name}" if scope != "-" else fn.name
    for node in _walk_shallow(fn):
        if isinstance(node, ast.Call):
            kind = _sync_call_kind(node, traced=None)
            if kind and not src.ignored(node.lineno):
                findings.append(common.Finding(
                    pass_name="jax", path=src.rel, line=node.lineno,
                    scope=scope, detail=f"host-sync:{kind}",
                    message=(
                        f"host sync {kind} on the marked hot path "
                        "(each one stalls the decode/verify loop; "
                        "batch syncs, or baseline the accepted one)"
                    ),
                ))


# ----------------------------------------------------- use-after-donate


def _expr_text(node: ast.AST) -> str | None:
    """A trackable donated-argument spelling: a bare name or a dotted
    attribute chain (``kv``, ``self.pool.k``). Calls/subscripts are
    untrackable -> None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _expr_text(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _donating_call(node: ast.Call, donors: dict[str, tuple[int, ...]]
                   ) -> list[ast.AST]:
    """Donated argument expressions of this call (empty when it is not
    a donating call). Sees through the engine's ``_run_compiled(kind,
    fn, *args)`` funnel: donate_argnums of ``fn`` index into ``args``."""
    f = node.func
    tail = None
    if isinstance(f, ast.Name):
        tail = f.id
    elif isinstance(f, ast.Attribute):
        tail = f.attr
    elif isinstance(f, ast.Subscript):  # self._fns[bucket](...)
        inner = f.value
        if isinstance(inner, ast.Attribute):
            tail = inner.attr
        elif isinstance(inner, ast.Name):
            tail = inner.id
    if tail == "_run_compiled" and len(node.args) >= 2:
        fn_expr = node.args[1]
        inner_tail = None
        if isinstance(fn_expr, ast.Subscript):
            fn_expr = fn_expr.value
        if isinstance(fn_expr, ast.Attribute):
            inner_tail = fn_expr.attr
        elif isinstance(fn_expr, ast.Name):
            inner_tail = fn_expr.id
        donate = donors.get(inner_tail or "", ())
        rest = node.args[2:]
        return [rest[i] for i in donate if i < len(rest)]
    donate = donors.get(tail or "", ())
    return [node.args[i] for i in donate if i < len(node.args)]


def _assign_targets_text(node: ast.AST) -> set[str]:
    out: set[str] = set()
    targets: list[ast.AST] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    elif isinstance(node, ast.For):
        targets = [node.target]
    for t in targets:
        for sub in ast.walk(t):
            text = _expr_text(sub)
            if text:
                out.add(text)
    return out


def _check_use_after_donate(src, fn, donors, findings) -> None:
    scope = src.scope_of(fn) or "-"
    scope = f"{scope}.{fn.name}" if scope != "-" else fn.name
    events: list[tuple[tuple[int, int], str, object]] = []
    # _walk_shallow, like the branch/sync checks: a nested def's
    # parameters are fresh bindings, not reads of the outer (possibly
    # donated) names.
    for node in _walk_shallow(fn):
        pos = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))
        if isinstance(node, ast.Call):
            donated = [
                t for t in map(_expr_text, _donating_call(node, donors))
                if t
            ]
            if donated:
                # The donation takes effect at the call's END: the
                # call's own argument reads (including the donated
                # expression itself) evaluate first and are the
                # donation, not a use-after — while a SECOND donating
                # call re-passing the same buffer sorts after the
                # first call's end and flags (the classic
                # double-donate "Array has been deleted").
                end = (
                    getattr(node, "end_lineno", pos[0]) or pos[0],
                    getattr(node, "end_col_offset", pos[1]) or pos[1],
                )
                events.append((end, "donate", (node, donated)))
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                             ast.For)):
            texts = _assign_targets_text(node)
            if texts:
                # Assignments clear at the END of the statement — the
                # RHS evaluates first, so `kv = kv + 1` after a
                # donation is a real read of the deleted array and
                # must flag (clearing at statement START masked it).
                # The engine's donate-and-reassign-in-one-statement
                # idiom stays clean: its donating call also ends
                # before the statement does, and the donate event's
                # enclosing-statement target check exempts it anyway.
                # A `for` clears at its TARGET (the header binds the
                # name before each body iteration), not at the end of
                # the whole loop body.
                anchor = node.target if isinstance(node, ast.For) else node
                end = (
                    getattr(anchor, "end_lineno", pos[0]) or pos[0],
                    getattr(anchor, "end_col_offset", pos[1]) or pos[1],
                )
                events.append((end, "assign", texts))
        if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
            getattr(node, "ctx", None), ast.Load
        ):
            text = _expr_text(node)
            if text:
                events.append((pos, "read", (node, text)))
    events.sort(key=lambda e: e[0])
    dead: dict[str, int] = {}  # expr text -> donate line
    for pos, kind, payload in events:
        if kind == "assign":
            for text in payload:
                dead.pop(text, None)
        elif kind == "read":
            node, text = payload
            line = dead.get(text)
            if line is not None and not src.ignored(node.lineno):
                findings.append(common.Finding(
                    pass_name="jax", path=src.rel, line=node.lineno,
                    scope=scope, detail=f"use-after-donate:{text}",
                    message=(
                        f"read of {text!r} after it was passed at a "
                        f"donated position (line {line}) — the buffer "
                        "was consumed; reassign from the program's "
                        "outputs first"
                    ),
                ))
        elif kind == "donate":
            node, texts = payload
            # Same-statement reassignment (targets of the enclosing
            # Assign) already cleared via the assign event at the same
            # position sorting earlier is NOT guaranteed; resolve by
            # checking the enclosing statement's targets explicitly.
            parent = src.parent(node)
            while parent is not None and not isinstance(
                parent,
                (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr,
                 ast.Return),
            ):
                parent = src.parent(parent)
            cleared = _assign_targets_text(parent) if parent else set()
            for text in texts:
                if text not in cleared:
                    dead[text] = node.lineno


def _hot_path_marked(src, fn) -> bool:
    """Marker comment on the ``def`` line or anywhere in the
    contiguous comment block right above the function — where "the
    function" starts at its FIRST decorator (``fn.lineno`` is the
    ``def`` line, so a scan from there would stop at the decorator
    and silently exempt decorated hot paths)."""
    if _HOT_PATH_MARK in src.comment(fn.lineno):
        return True
    start = min(
        [fn.lineno] + [d.lineno for d in fn.decorator_list]
    )
    line = start - 1
    while line > 0 and src.comment(line):
        if _HOT_PATH_MARK in src.comment(line):
            return True
        line -= 1
    return False


# ---------------------------------------------------------------- main


def check_file(src: common.SourceFile) -> list[common.Finding]:
    findings: list[common.Finding] = []
    roots, donors = _collect_roots_and_donors(src)
    fns = _index_functions(src)
    reach = _reachable(roots, fns)
    for name, static in sorted(reach.items()):
        for fn in fns.get(name, []):
            _check_jitted_fn(src, fn, static, findings)
    for defs in fns.values():
        for fn in defs:
            if _hot_path_marked(src, fn):
                _check_hot_path_fn(src, fn, findings)
            if donors and fn.name not in reach:
                _check_use_after_donate(src, fn, donors, findings)
    return findings


def run(paths, repo_root) -> list[common.Finding]:
    findings: list[common.Finding] = []
    for path in common.iter_python_files(paths):
        src = common.load_source(path, repo_root)
        if src is not None:
            findings.extend(check_file(src))
    return findings
