"""Runtime lock-order cycle detector (graftlint pass 4, ISSUE 14).

The static lock pass checks that annotated state is touched under its
lock; it cannot see the *order* two threads take two locks in — the
classic deadlock shape (thread A holds L1 wanting L2, thread B holds
L2 wanting L1) only exists dynamically. This module is the runtime
complement: while armed, every ``threading.Lock()`` / ``RLock()``
created by repo code is wrapped so each acquisition records
*held-before* edges (every lock currently held by the acquiring thread
-> the lock being acquired) into a global graph, and a new edge that
closes a cycle is recorded as a violation **at the moment the ordering
is established** — no actual deadlock (and no lucky interleaving) is
needed, because the edges accumulate across threads and across time.

Scope and noise control:

* Only locks allocated from files under ``tensorflow_examples_tpu``
  are wrapped (the creating frame is inspected once, at allocation);
  stdlib internals — ``queue.Queue``'s mutex, ``threading.Event``'s
  condition — keep raw locks, so the graph stays the repo's own.
* Edges are recorded at acquisition *attempt* (before blocking): the
  detector reports the ordering hazard even when the test run happens
  not to interleave into the deadlock.
* RLock re-entry by the owning thread records no self-edge.

Arming is test-scoped: the chaos/router/overload tier-1 tests arm it
via the autouse conftest fixture (see ``tests/conftest.py``), which
asserts ``violations == []`` at teardown. ``armed()`` is the
context-manager form for direct use::

    with lockorder.armed() as mon:
        ... exercise the threaded code ...
    assert not mon.violations

Locks created while armed keep working after disarm (recording becomes
a no-op), so objects that outlive the window are safe.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading

_PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_real_lock = threading.Lock
_real_rlock = threading.RLock

# The single active monitor (None = disarmed). Wrapped locks hold a
# reference to the monitor that existed at their creation; they check
# its `enabled` flag per acquisition, so disarm is O(1) and permanent.
_active: "LockOrderMonitor | None" = None
_arm_lock = _real_lock()


class LockOrderMonitor:
    """Held-before graph + cycle detection over tracked locks."""

    def __init__(self):
        self.enabled = True
        self.violations: list[str] = []
        self._graph: dict[int, set[int]] = {}   # lock id -> successors
        self._sites: dict[int, str] = {}        # lock id -> creation site
        self._edges: set[tuple[int, int]] = set()
        # The graph is keyed by id(); a freed lock's id is recycled by
        # CPython, which would alias a NEW lock onto a dead lock's
        # recorded edges and manufacture (or mask) cycles between locks
        # that never coexisted. Pin every registered wrapper for the
        # armed window so ids stay unique. Bounded by locks created
        # while armed — test scope.
        self._refs: dict[int, object] = {}
        self._mu = _real_lock()
        # Per-thread held stacks keyed by thread ident (NOT
        # threading.local): a plain threading.Lock may legally be
        # released by a different thread than its acquirer (hand-off /
        # semaphore style), and that release must be able to pop the
        # ACQUIRER's stack entry — a thread-local stranded it forever,
        # turning every later acquire by the acquirer into a phantom
        # held-before edge. All stack/owner access is under _mu.
        self._stacks: dict[int, list[int]] = {}
        self._owners: dict[int, int] = {}  # lock id -> acquiring thread

    # ------------------------------------------------------- thread state

    def _stack_locked(self, ident: int) -> list[int]:
        return self._stacks.setdefault(ident, [])

    # ---------------------------------------------------------- recording

    def register(self, lock_id: int, site: str, lock: object) -> None:
        with self._mu:
            self._sites[lock_id] = site
            self._refs[lock_id] = lock

    def note_acquire(self, lock_id: int, *, reentrant: bool) -> None:
        if not self.enabled:
            return
        me = threading.get_ident()
        with self._mu:
            stack = self._stack_locked(me)
            if reentrant and lock_id in stack:
                return  # RLock re-entry: no ordering established
            for h in stack:
                if h == lock_id:
                    continue
                edge = (h, lock_id)
                if edge in self._edges:
                    continue
                self._edges.add(edge)
                self._graph.setdefault(h, set()).add(lock_id)
                cycle = self._find_path(lock_id, h)
                if cycle is not None:
                    self._record_violation([h] + cycle)
            stack.append(lock_id)

    def note_acquired(self, lock_id: int) -> None:
        """Inner acquire SUCCEEDED: the calling thread owns the lock.
        Ownership must not be recorded at attempt time — a blocked
        waiter would clobber the real holder's entry, and a legal
        cross-thread release would then pop the waiter's stack,
        stranding the holder's entry into phantom edges."""
        if not self.enabled:
            return
        with self._mu:
            self._owners[lock_id] = threading.get_ident()

    def note_acquired_failed(self, lock_id: int) -> None:
        """A non-blocking acquire that returned False: undo the held
        push (the edge stays — the ordering intent was real)."""
        with self._mu:
            stack = self._stack_locked(threading.get_ident())
            if stack and stack[-1] == lock_id:
                stack.pop()
            elif lock_id in stack:
                stack.remove(lock_id)

    def note_release(self, lock_id: int) -> None:
        if not self.enabled:
            return
        me = threading.get_ident()
        with self._mu:
            stack = self._stack_locked(me)
            if lock_id not in stack:
                # Cross-thread release: pop the ACQUIRER's entry.
                owner = self._owners.get(lock_id)
                stack = self._stacks.get(owner, []) if owner is not None \
                    else []
            if lock_id in stack:
                # remove the most recent occurrence (RLock depth
                # handled by the wrapper, which only notes the
                # outermost pair)
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i] == lock_id:
                        del stack[i]
                        break
            self._owners.pop(lock_id, None)

    # ------------------------------------------------------ cycle search

    def _find_path(self, start: int, goal: int) -> list[int] | None:
        """DFS path start -> goal in the held-before graph (caller
        holds self._mu). A path means the fresh edge goal->start closed
        a cycle."""
        seen = {start}
        stack: list[tuple[int, list[int]]] = [(start, [start])]
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            for nxt in self._graph.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _record_violation(self, cycle_ids: list[int]) -> None:
        names = " -> ".join(
            self._sites.get(i, f"lock@{i:#x}") for i in cycle_ids
        )
        msg = (
            f"lock-order cycle: {names} (thread "
            f"{threading.current_thread().name!r} closed the cycle)"
        )
        self.violations.append(msg)

    def edge_count(self) -> int:
        with self._mu:
            return len(self._edges)


class _TrackedLock:
    """A threading.Lock/RLock stand-in that reports to the monitor."""

    def __init__(self, monitor: LockOrderMonitor, site: str,
                 reentrant: bool):
        self._inner = _real_rlock() if reentrant else _real_lock()
        self._monitor = monitor
        self._reentrant = reentrant
        # RLock re-entry depth. Moved only while the lock is HELD by
        # the moving thread (increment after a successful acquire,
        # decrement before the inner release), so a plain int is
        # race-free; it keeps an inner release from erasing the
        # held-stack entry while the lock is still held — which would
        # hide every ordering edge recorded after a re-entry.
        self._depth = 0
        monitor.register(id(self), site, self)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        mon = self._monitor
        mon.note_acquire(id(self), reentrant=self._reentrant)
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            mon.note_acquired_failed(id(self))
        else:
            mon.note_acquired(id(self))
            if self._reentrant:
                self._depth += 1
        return ok

    def release(self):
        # ALL monitor bookkeeping happens BEFORE freeing the inner
        # lock, while ownership is still exclusive: after the release
        # the next owner's note_acquired races anything we do here
        # (note_release's owners.pop would erase the NEW holder's
        # ownership record, stranding its stack entry into phantom
        # edges). The cost: an erroneous release of an un-owned lock
        # pops bookkeeping before the inner lock raises — acceptable,
        # because that RuntimeError already fails the armed test
        # loudly, while the race above corrupts CORRECT programs.
        if self._reentrant:
            depth = self._depth = self._depth - 1
            if depth > 0:
                try:
                    self._inner.release()
                except RuntimeError:  # not owned: undo the bookkeeping
                    self._depth = depth + 1
                    raise
                return  # still held by this thread: keep the stack entry
        self._monitor.note_release(id(self))
        self._inner.release()

    def __getattr__(self, name):
        # Delegate everything else (locked(), _at_fork_reinit, ...) to
        # the inner lock so hasattr/getattr probing observes exactly
        # the real type's surface — Py<3.14's C RLock has no locked(),
        # and a test must not pass or fail differently only because
        # the detector is armed.
        if name == "_inner":  # guard pre-__init__ lookups
            raise AttributeError(name)
        return getattr(self._inner, name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        kind = "RLock" if self._reentrant else "Lock"
        return f"<Tracked{kind} {self._monitor._sites.get(id(self))}>"


def _creation_site(depth: int = 2) -> str | None:
    """``relpath:lineno`` of the allocating frame when it lives in the
    package; None for stdlib/third-party allocations (left raw)."""
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return None
    filename = frame.f_code.co_filename
    if not filename.startswith(_PACKAGE_DIR):
        return None
    rel = os.path.relpath(filename, os.path.dirname(_PACKAGE_DIR))
    return f"{rel}:{frame.f_lineno}"


def _patched_lock():
    mon = _active
    if mon is None or not mon.enabled:
        return _real_lock()
    site = _creation_site()
    if site is None:
        return _real_lock()
    return _TrackedLock(mon, site, reentrant=False)


def _patched_rlock():
    mon = _active
    if mon is None or not mon.enabled:
        return _real_rlock()
    site = _creation_site()
    if site is None:
        return _real_rlock()
    return _TrackedLock(mon, site, reentrant=True)


def arm() -> LockOrderMonitor:
    """Start tracking: patch ``threading.Lock``/``RLock`` so
    package-allocated locks are wrapped. Returns the monitor. Nested
    arming is an error (one global graph at a time keeps the report
    attributable to one test)."""
    global _active
    with _arm_lock:
        if _active is not None and _active.enabled:
            raise RuntimeError("lock-order detector is already armed")
        mon = LockOrderMonitor()
        _active = mon
        threading.Lock = _patched_lock
        threading.RLock = _patched_rlock
        return mon


def disarm() -> None:
    """Stop tracking and restore ``threading``. Locks created while
    armed keep working; their recording turns into a no-op."""
    global _active
    with _arm_lock:
        if _active is not None:
            _active.enabled = False
        _active = None
        threading.Lock = _real_lock
        threading.RLock = _real_rlock


@contextlib.contextmanager
def armed():
    mon = arm()
    try:
        yield mon
    finally:
        disarm()
