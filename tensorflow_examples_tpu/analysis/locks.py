"""Lock-discipline pass (graftlint pass 1, ISSUE 14 tentpole).

Convention: shared mutable state in a threaded class is annotated at
its defining assignment with a trailing guard comment::

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self._free = []          # guard: self._lock
            self.hits = 0            # guard: self._lock

    _DEPTH = 0                       # guard: _SWITCH_LOCK  (module global)

The pass then walks the whole FILE and reports every read or write of
an annotated attribute (matched by attribute name) — or annotated
module global (matched by name) — that is not lexically inside a
``with`` statement whose context expression matches the guard. Guard
matching is by the guard expression's final component (``self._lock``
matches ``with self._lock:`` in the defining class and ``with
self._lock:`` in a *different* class that owns the instances — the
router's ``ReplicaState`` fields are guarded by the Router's lock, so
the annotation there reads ``# guard: Router._lock``).

Exemptions, in the order they are checked:

* the defining class's ``__init__`` (construction precedes sharing);
* functions whose name ends in ``_locked`` (the repo's caller-holds-
  the-lock suffix convention, e.g. ``paged_kv._alloc_block_locked``);
* lines carrying a ``graftlint: ignore`` comment (intentional
  lock-free reads with the rationale in the comment, e.g. an atomic
  int load published as "last-write-wins");
* everything else lands in the committed suppression baseline or is a
  finding.

This is a lexical dominance check, not a dataflow analysis: a method
that is only ever *called* with the lock held still flags (baseline it
or rename it ``*_locked``). That is deliberate — the annotation makes
the locking contract explicit at the definition, and the baseline
makes every accepted exception explicit and counted.
"""

from __future__ import annotations

import ast
import re

from tensorflow_examples_tpu.analysis import common

_GUARD_RE = re.compile(r"#\s*guard:\s*([A-Za-z_][\w.]*)")


def _guard_in_comment(comment: str) -> str | None:
    m = _GUARD_RE.search(comment)
    return m.group(1) if m else None


def _last_component(expr_text: str) -> str:
    return expr_text.rsplit(".", 1)[-1]


def _with_item_text(item: ast.withitem) -> str:
    return common.unparse(item.context_expr)


class _Annotations:
    """Guarded names collected from one file. Two classes in one file
    may annotate the SAME attribute name under different guards (the
    router's ``ReplicaState.completed`` vs ``_SetStats.completed``), so
    each name keeps every annotation: an access is clean when ANY of
    the name's guards encloses it, and exempt inside any annotating
    class's ``__init__``."""

    def __init__(self):
        # attr name -> [(guard text, defining class name, def lineno)]
        self.attrs: dict[str, list[tuple[str, str, int]]] = {}
        # module-global name -> (guard text, defining lineno)
        self.globals: dict[str, tuple[str, int]] = {}


def _collect_annotations(src: common.SourceFile) -> _Annotations:
    ann = _Annotations()
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            continue
        guard = _guard_in_comment(src.comment(node.lineno))
        if guard is None:
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign)
            else [node.target]
        )
        for t in targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                scope = src.scope_of(node)
                cls = scope.split(".")[0] if scope != "-" else ""
                ann.attrs.setdefault(t.attr, []).append(
                    (guard, cls, node.lineno)
                )
            elif isinstance(t, ast.Name) and src.scope_of(node) == "-":
                ann.globals[t.id] = (guard, node.lineno)
    return ann


def _enclosing_withs(src: common.SourceFile, node: ast.AST) -> list[str]:
    """Context-expression texts of every ``with`` lexically enclosing
    ``node`` within its own function (the whole statement stack,
    innermost last). The walk STOPS at a ``def`` boundary: a ``with``
    outside a nested function does not hold when that function later
    runs — a deferred callback defined under the lock still touches
    the state unguarded. Lambdas do NOT stop the walk: the repo's
    lambdas are in-place sort/max keys that execute synchronously
    under the enclosing block (``sorted(..., key=lambda kv:
    self._chain_depth[...])`` in ``paged_kv.prefix_digest``)."""
    out: list[str] = []
    cur = src.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            out.extend(_with_item_text(i) for i in cur.items)
        cur = src.parent(cur)
    return out


def _guard_matches(guard: str, with_texts: list[str]) -> bool:
    tail = _last_component(guard)
    for text in with_texts:
        # `with self._lock:` / `with pool._lock:` / `with q.mutex:` —
        # exact text or same final component. `with cond:` where the
        # guard is `self._cond` also matches on the component name.
        base = text.split(" as ")[0].strip()
        # strip a trailing call: `with self._lock():` styles
        if base.endswith("()"):
            base = base[:-2]
        if base == guard or _last_component(base) == tail:
            return True
    return False


def _enclosing_function(src: common.SourceFile, node: ast.AST):
    cur = src.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = src.parent(cur)
    return None


# Mutating container methods: calling one on an annotated name is a
# write to the shared state, not a read — the read/write split is part
# of the stable baseline key, and a maintainer triages the two kinds
# differently (a lock-free snapshot *read* may be acceptable; a
# lock-free *mutation* almost never is).
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "popitem", "clear", "add", "discard", "update",
    "setdefault", "sort", "reverse", "rotate", "move_to_end",
})


def _access_kind(src: common.SourceFile, node: ast.AST) -> str:
    if isinstance(node, (ast.Attribute, ast.Name, ast.Subscript)):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return "write"
        parent = src.parent(node)
        if isinstance(parent, ast.AugAssign) and parent.target is node:
            return "write"
        # `self._results[seq] = v` / `del self._free[0]` /
        # `self.d[k] += 1` / `self.d[k][0] = v`: the annotated node is
        # the Load-context *value* of a Subscript chain whose outermost
        # link carries the Store/Del — the container is being mutated.
        cur, p = node, parent
        while isinstance(p, ast.Subscript) and p.value is cur:
            if isinstance(p.ctx, (ast.Store, ast.Del)):
                return "write"
            gp = src.parent(p)
            if isinstance(gp, ast.AugAssign) and gp.target is p:
                return "write"
            cur, p = p, gp
        # `self._free.append(x)`: a known mutator method called on the
        # annotated container.
        if (
            isinstance(parent, ast.Attribute)
            and parent.value is node
            and parent.attr in _MUTATORS
        ):
            gp = src.parent(parent)
            if isinstance(gp, ast.Call) and gp.func is parent:
                return "write"
    return "read"


def check_file(src: common.SourceFile) -> list[common.Finding]:
    ann = _collect_annotations(src)
    if not ann.attrs and not ann.globals:
        return []
    findings: list[common.Finding] = []

    def flag(node, name: str, guards: list[str],
             owners: list[str]) -> None:
        if src.ignored(node.lineno):
            return
        fn = _enclosing_function(src, node)
        if fn is not None and fn.name.endswith("_locked"):
            return  # caller-holds-the-lock suffix convention
        scope = src.scope_of(node)
        for owner in owners:
            if owner and (
                scope == f"{owner}.__init__"
                or scope.startswith(f"{owner}.__init__.")
            ):
                return  # construction precedes sharing
        withs = _enclosing_withs(src, node)
        if any(_guard_matches(g, withs) for g in guards):
            return
        kind = _access_kind(src, node)
        shown = "/".join(dict.fromkeys(guards))
        findings.append(common.Finding(
            pass_name="locks",
            path=src.rel,
            line=node.lineno,
            scope=scope,
            detail=f"{name}:{kind}",
            message=(
                f"{kind} of {name!r} (guarded by {shown}) outside a "
                f"`with {shown}:` block"
            ),
        ))

    for node in ast.walk(src.tree):
        if isinstance(node, ast.Attribute) and node.attr in ann.attrs:
            entries = ann.attrs[node.attr]
            if any(node.lineno == d for _, _, d in entries):
                continue  # the annotated definition itself
            flag(
                node, node.attr,
                [g for g, _, _ in entries],
                [c for _, c, _ in entries],
            )
        elif isinstance(node, ast.Name) and node.id in ann.globals:
            guard, def_line = ann.globals[node.id]
            if node.lineno == def_line or src.scope_of(node) == "-":
                continue  # definition / other module-level constants
            # `global X` declarations are not accesses.
            flag(node, node.id, [guard], [""])
    return findings


def run(paths, repo_root) -> list[common.Finding]:
    findings: list[common.Finding] = []
    for path in common.iter_python_files(paths):
        src = common.load_source(path, repo_root)
        if src is not None:
            findings.extend(check_file(src))
    return findings
