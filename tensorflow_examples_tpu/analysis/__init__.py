"""graftlint: repo-native static analysis (ISSUE 14).

The serving stack is a fleet — 40+ ``threading`` sites across the
batcher/router/supervisor/autoscaler tier, a dozen jitted/AOT-warmed
programs with donated buffers, and a schema-versioned telemetry
contract — and until this package its invariants were guarded only by
convention and by goldens that catch breakage *after* it ships. The
original TensorFlow design argument (arxiv 1605.08695) is that a
statically analyzable program representation makes whole-program
checking tractable; these passes apply that discipline to the repo's
own contracts:

* :mod:`analysis.locks` — lock-discipline pass over the
  ``# guard: <lock>`` attribute annotations (reads/writes of annotated
  shared state must sit under a matching ``with`` block).
* :mod:`analysis.jaxhaz` — JAX hazard pass: traced-value branching and
  implicit host syncs inside jit-reachable functions, host syncs on
  marked hot paths, and use-after-donate of buffers passed to
  ``donate_argnums`` programs.
* :mod:`analysis.drift` — schema/counter drift pass: the
  ``SERVING_KEYS_V4..V10`` contract in ``telemetry/schema.py`` vs what
  the batcher/router/paged pool actually stamp vs what the docs
  document, plus registered counter/gauge names vs the docs.
* :mod:`analysis.lockorder` — the runtime complement: an opt-in
  lock-order cycle detector the chaos/router/overload tier-1 tests arm
  (dynamic acquisition ordering is where static analysis can't reach).

``tools/graftlint.py`` is the CLI; ``tests/test_lint.py`` pins every
pass with known-bad/known-good fixtures and runs ``--all`` over the
package with the committed suppression baseline
(``tools/graftlint_baseline.json``) in tier-1. See
``docs/static_analysis.md``.
"""

from tensorflow_examples_tpu.analysis.common import (  # noqa: F401
    Baseline,
    Finding,
    apply_baseline,
    iter_python_files,
)

PASSES = ("locks", "jax", "schema")


def run_pass(name: str, paths, repo_root):
    """Run one named pass over ``paths`` (list of file paths); returns
    a list of :class:`Finding`."""
    if name == "locks":
        from tensorflow_examples_tpu.analysis import locks

        return locks.run(paths, repo_root)
    if name == "jax":
        from tensorflow_examples_tpu.analysis import jaxhaz

        return jaxhaz.run(paths, repo_root)
    if name == "schema":
        from tensorflow_examples_tpu.analysis import drift

        return drift.run(paths, repo_root)
    raise ValueError(f"unknown pass {name!r}; one of {PASSES}")
