"""Schema/counter drift pass (graftlint pass 3, ISSUE 14 tentpole).

The serving stats contract has three copies that historically drifted
only in review: the ``SERVING_KEYS_V4..V10`` tuples in
``telemetry/schema.py`` (the validator), the keys
``serving/batcher.py`` / ``serving/router.py`` / ``serving/paged_kv.py``
actually stamp into the ``serving`` object, and what
``docs/serving.md`` / ``docs/observability.md`` document. This pass
cross-checks all three on every run:

* **unknown-serving-key** — a stamper writes a key no schema version
  declares (a new field shipped without a schema bump: the exact
  mistake the mislabeling rule in ``validate_line`` exists to catch
  downstream, caught at authoring time instead);
* **unstamped-schema-key** — a declared schema key no stamper writes
  (dead contract: consumers guard for a field nothing produces);
* **undocumented-schema-key** — a declared schema key the serving/
  observability docs never mention;
* **undocumented-counter** — a ``serving/`` / ``router/`` /
  ``autoscaler/`` counter or gauge registered in the serving tier that
  no doc mentions (the ops runbooks are the operator's only index).

Dynamic stamps are expanded where the pieces are statically knowable:
an f-string key whose formatted values are names bound by an enclosing
``for`` over a constant tuple (or a module-level constant tuple like
``SLO_CLASSES``) expands to its cartesian product — which is how the
batcher's per-class ``f"{name}_p95_{cls}"`` stamps are credited
against ``SERVING_KEYS_V10``. F-strings with unresolvable parts (e.g.
``f"serving/shed_{req.slo}_total"``) are skipped, not guessed.
"""

from __future__ import annotations

import ast
import itertools
import os
import re

from tensorflow_examples_tpu.analysis import common

# The three contract surfaces, repo-relative.
SCHEMA_FILE = "tensorflow_examples_tpu/telemetry/schema.py"
STAMP_FILES = (
    "tensorflow_examples_tpu/serving/batcher.py",
    "tensorflow_examples_tpu/serving/router.py",
    "tensorflow_examples_tpu/serving/paged_kv.py",
)
DOC_FILES = ("docs/serving.md", "docs/observability.md")

COUNTER_SCAN_DIR = "tensorflow_examples_tpu/serving"

# Counter/gauge namespace fallback when the schema module predates
# INSTRUMENT_PREFIXES (the pass normally LEARNS the list from there —
# ISSUE 15 satellite: a new namespace is a schema-module edit, never a
# pass-side edit).
_FALLBACK_PREFIXES = ("serving/", "router/", "autoscaler/")

# The serving-key tuple naming convention the pass discovers in the
# schema module: SERVING_KEYS (the v4 required set) plus every
# SERVING_KEYS_V<N> bump. A new schema version's tuple is learned
# automatically — no hand-maintained pass-side list to drift.
_TUPLE_NAME = re.compile(r"^SERVING_KEYS(_V\d+)?$")


def _load(repo_root: str, rel: str) -> common.SourceFile | None:
    return common.load_source(os.path.join(repo_root, rel), repo_root)


# ------------------------------------------------------- schema tuples


def schema_keys(src: common.SourceFile) -> dict[str, set[str]]:
    """{tuple name: keys} from the schema module's module-level
    constant tuples, discovered by the SERVING_KEYS* naming
    convention."""
    out: dict[str, set[str]] = {}
    for node in src.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and _TUPLE_NAME.match(t.id):
                try:
                    vals = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    continue
                if isinstance(vals, (tuple, list)):
                    out[t.id] = {v for v in vals if isinstance(v, str)}
    return out


def _tuple_order(name: str) -> tuple[int, str]:
    m = _TUPLE_NAME.match(name)
    version = int(m.group(1)[2:]) if m and m.group(1) else 4
    return (version, name)


def instrument_prefixes(src: common.SourceFile) -> tuple[str, ...]:
    """The scanned counter/gauge namespaces, learned from the schema
    module's INSTRUMENT_PREFIXES constant (fallback: the pre-ISSUE-15
    trio)."""
    for node in src.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == "INSTRUMENT_PREFIXES":
                try:
                    vals = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    continue
                if isinstance(vals, (tuple, list)) and all(
                    isinstance(v, str) for v in vals
                ):
                    return tuple(vals)
    return _FALLBACK_PREFIXES


# ----------------------------------------------------- f-string expand


def _module_const_tuples(src: common.SourceFile) -> dict[str, tuple]:
    out: dict[str, tuple] = {}
    for node in src.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    try:
                        v = ast.literal_eval(node.value)
                    except (ValueError, SyntaxError):
                        continue
                    if isinstance(v, (tuple, list)) and all(
                        isinstance(i, str) for i in v
                    ):
                        out[t.id] = tuple(v)
    return out


def _resolve_domain(it: ast.AST,
                    consts: dict[str, tuple]) -> tuple | None:
    """A for/comprehension iterable as a tuple of strings: a named
    module constant or an all-string literal; None when dynamic."""
    if isinstance(it, ast.Name) and it.id in consts:
        return consts[it.id]
    try:
        lit = ast.literal_eval(it)
    except (ValueError, SyntaxError):
        return None
    if isinstance(lit, (tuple, list)) and all(
        isinstance(i, str) for i in lit
    ):
        return tuple(lit)
    return None


def _loop_domains(src: common.SourceFile, node: ast.AST,
                  consts: dict[str, tuple]) -> dict[str, tuple]:
    """{name: candidate string values} from enclosing ``for`` targets
    whose iterables are constant tuples or named module constants."""
    domains: dict[str, tuple] = {}
    cur = src.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.comprehension)):
            values = _resolve_domain(cur.iter, consts)
            if values is not None and isinstance(cur.target, ast.Name):
                domains.setdefault(cur.target.id, values)
        # comprehensions: generators live on the parent expression
        for gen in getattr(cur, "generators", []) or []:
            sub = _loop_domains_from_comp(gen, consts)
            for k, v in sub.items():
                domains.setdefault(k, v)
        cur = src.parent(cur)
    return domains


def _loop_domains_from_comp(gen: ast.comprehension,
                            consts: dict[str, tuple]) -> dict[str, tuple]:
    values = _resolve_domain(gen.iter, consts)
    if values is not None and isinstance(gen.target, ast.Name):
        return {gen.target.id: values}
    return {}


def expand_key(src: common.SourceFile, node: ast.AST,
               consts: dict[str, tuple]) -> list[str] | None:
    """Constant -> [key]; expandable f-string -> cartesian expansion;
    anything else -> None (dynamic, skipped)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if not isinstance(node, ast.JoinedStr):
        return None
    domains = _loop_domains(src, node, consts)
    parts: list[tuple[str, ...]] = []
    for piece in node.values:
        if isinstance(piece, ast.Constant):
            parts.append((str(piece.value),))
        elif isinstance(piece, ast.FormattedValue) and isinstance(
            piece.value, ast.Name
        ) and piece.value.id in domains:
            parts.append(tuple(domains[piece.value.id]))
        else:
            return None
    return ["".join(combo) for combo in itertools.product(*parts)]


# ---------------------------------------------------------- stamp scan


def stamped_keys(src: common.SourceFile) -> dict[str, int]:
    """{serving-object key: first lineno} stamped in this file:
    ``serving["k"] = ...`` subscript stores on a name ``serving``, the
    dict literal assigned to ``serving``, and the dict literal a
    ``paged_stats`` function returns."""
    consts = _module_const_tuples(src)
    out: dict[str, int] = {}

    def note(keys: list[str] | None, lineno: int) -> None:
        for k in keys or ():
            out.setdefault(k, lineno)

    for node in ast.walk(src.tree):
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Store
        ) and isinstance(node.value, ast.Name) \
                and node.value.id == "serving":
            note(expand_key(src, node.slice, consts), node.lineno)
        elif isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Dict
        ):
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if "serving" in targets:
                for k in node.value.keys:
                    note(expand_key(src, k, consts) if k else None,
                         node.lineno)
        elif isinstance(node, ast.FunctionDef) and node.name in (
            "paged_stats",
        ):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return) and isinstance(
                    sub.value, ast.Dict
                ):
                    for k in sub.value.keys:
                        note(
                            expand_key(src, k, consts) if k else None,
                            sub.lineno,
                        )
    return out


# -------------------------------------------------------- counter scan


def registered_instruments(
    src: common.SourceFile,
    prefixes: tuple[str, ...] = _FALLBACK_PREFIXES,
) -> dict[str, int]:
    """{instrument name: first lineno} for counter()/gauge()/histogram()
    registrations with resolvable names in the scanned prefixes."""
    consts = _module_const_tuples(src)
    out: dict[str, int] = {}
    for node in ast.walk(src.tree):
        if not (isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr in ("counter", "gauge", "histogram")
                and node.args):
            continue
        for name in expand_key(src, node.args[0], consts) or ():
            if name.startswith(tuple(prefixes)):
                out.setdefault(name, node.lineno)
    return out


# ---------------------------------------------------------------- main


def run(paths, repo_root) -> list[common.Finding]:
    """The drift pass is whole-repo by construction: ``paths`` gates
    which findings are *reported* (a file outside the requested set
    stays quiet) but the contract is always read from the canonical
    schema/stamper/doc locations."""
    requested = {
        common.rel_path(p, repo_root)
        for p in common.iter_python_files(paths)
    }
    findings: list[common.Finding] = []
    schema_src = _load(repo_root, SCHEMA_FILE)
    if schema_src is None:
        return findings
    tuples = schema_keys(schema_src)
    declared: dict[str, str] = {}
    for tup in sorted(tuples, key=_tuple_order):
        for key in tuples[tup]:
            declared.setdefault(key, tup)
    prefixes = instrument_prefixes(schema_src)

    docs_text = ""
    for rel in DOC_FILES:
        p = os.path.join(repo_root, rel)
        if os.path.isfile(p):
            with open(p, encoding="utf-8") as f:
                docs_text += f.read()

    stamps: dict[str, tuple[str, int]] = {}  # key -> (file, line)
    for rel in STAMP_FILES:
        src = _load(repo_root, rel)
        if src is None:
            continue
        for key, line in stamped_keys(src).items():
            stamps.setdefault(key, (rel, line))
            if key not in declared and not src.ignored(line):
                if rel in requested:
                    findings.append(common.Finding(
                        pass_name="schema", path=rel, line=line,
                        scope="stats_line",
                        detail=f"unknown-serving-key:{key}",
                        message=(
                            f"serving key {key!r} is stamped but no "
                            "SERVING_KEYS* tuple in "
                            "telemetry/schema.py declares it — bump "
                            "the schema before shipping the field"
                        ),
                    ))

    schema_rel = SCHEMA_FILE
    report_schema = schema_rel in requested
    for key, tup in sorted(declared.items()):
        if key not in stamps and report_schema:
            findings.append(common.Finding(
                pass_name="schema", path=schema_rel, line=1,
                scope=tup, detail=f"unstamped-schema-key:{key}",
                message=(
                    f"schema key {key!r} ({tup}) is declared but no "
                    "stamper (batcher/router/paged pool) writes it"
                ),
            ))
        # Backticked form only: schema keys that are ordinary English
        # words ("slots", "draining") appear all over the docs prose —
        # a bare substring test could never flag them. The catalog
        # documents keys as `key` rows.
        if f"`{key}`" not in docs_text and report_schema:
            findings.append(common.Finding(
                pass_name="schema", path=schema_rel, line=1,
                scope=tup, detail=f"undocumented-schema-key:{key}",
                message=(
                    f"schema key {key!r} ({tup}) appears in neither "
                    "docs/serving.md nor docs/observability.md"
                ),
            ))

    scan_dir = os.path.join(repo_root, COUNTER_SCAN_DIR)
    for path in common.iter_python_files([scan_dir]):
        src = common.load_source(path, repo_root)
        if src is None or src.rel not in requested:
            continue
        for name, line in sorted(
            registered_instruments(src, prefixes).items()
        ):
            if name not in docs_text and not src.ignored(line):
                findings.append(common.Finding(
                    pass_name="schema", path=src.rel, line=line,
                    scope="-", detail=f"undocumented-counter:{name}",
                    message=(
                        f"instrument {name!r} is registered but "
                        "documented in neither docs/serving.md nor "
                        "docs/observability.md (add it to the counter "
                        "catalog)"
                    ),
                ))
    findings.sort(key=lambda f: (f.path, f.line, f.detail))
    return findings
