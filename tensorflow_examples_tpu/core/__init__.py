"""Core layer: device mesh, sharding rules, precision policy, RNG.

Replaces the reference's ``tf.distribute`` strategy layer (BASELINE.json
north_star: MirroredStrategy / MultiWorkerMirroredStrategy + NCCL) with the
TPU-native equivalent: a ``jax.sharding.Mesh`` with named axes and
``NamedSharding`` annotations; XLA inserts the collectives over ICI/DCN.
"""

from tensorflow_examples_tpu.core.mesh import (
    AxisNames,
    MeshConfig,
    create_mesh,
    local_batch_size,
)
from tensorflow_examples_tpu.core.sharding import (
    ShardingRules,
    named_sharding,
    shard_params,
    shardings_for_params,
)
from tensorflow_examples_tpu.core.precision import Precision, PrecisionPolicy
from tensorflow_examples_tpu.core.rng import named_rngs, step_rng
