"""Rules-based parameter sharding.

The reference never sharded parameters (pure DP: every replica held a full
copy, NCCL all-reduced gradients — BASELINE.json:north_star). A TPU-native
framework shards by annotation instead: each model ships a small table of
``(param-path regex → PartitionSpec)`` rules; ``shard_params`` applies them
and ``jax.jit`` compiles the collectives. Unmatched params are replicated,
which reproduces the reference's DP behavior as the degenerate case.
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any


class ShardingRules:
    """Ordered (regex, PartitionSpec) table; first match wins.

    Paths are '/'-joined pytree key paths, e.g.
    ``"transformer/h_3/attn/c_attn/kernel"``.
    """

    def __init__(self, rules: Sequence[tuple[str, P]] = ()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, path: str) -> P:
        for pat, spec in self.rules:
            if pat.search(path):
                return spec
        return P()  # replicate

    def __add__(self, other: "ShardingRules") -> "ShardingRules":
        out = ShardingRules()
        out.rules = list(self.rules) + list(other.rules)
        return out


REPLICATED = ShardingRules()


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _filter_spec(spec: P, mesh: Mesh) -> P:
    """Drop mesh axes of size 1 from a spec (cheaper layouts, same math)."""

    def keep(axis):
        if axis is None:
            return None
        if isinstance(axis, (tuple, list)):
            kept = tuple(a for a in axis if mesh.shape[a] > 1)
            return kept if kept else None
        return axis if mesh.shape[axis] > 1 else None

    return P(*(keep(a) for a in spec))


def shardings_for_params(
    params: Pytree, mesh: Mesh, rules: ShardingRules | None = None
) -> Pytree:
    """Pytree of NamedSharding matching ``params``' structure."""
    rules = rules or REPLICATED

    def one(path, leaf):
        spec = _filter_spec(rules.spec_for(_path_str(path)), mesh)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, params)


def shard_params(
    params: Pytree, mesh: Mesh, rules: ShardingRules | None = None
) -> Pytree:
    """Place (device_put) a param pytree according to the rules."""
    shardings = shardings_for_params(params, mesh, rules)
    return jax.device_put(params, shardings)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, _filter_spec(P(*spec), mesh))


def batch_sharding(mesh: Mesh, axes=None) -> NamedSharding:
    """Sharding for a [global_batch, ...] array: batch over the given
    batch-like axes (default data+fsdp), size-1 axes filtered."""
    if axes is None:
        from tensorflow_examples_tpu.core.mesh import AxisNames

        axes = AxisNames.BATCH_AXES
    kept = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
    return NamedSharding(mesh, P(kept if kept else None))


def bundle_sharding(mesh: Mesh, axes=None) -> NamedSharding:
    """Sharding for a [k, global_batch, ...] step bundle: the scan axis
    (dim 0) is unsharded; the batch dim behind it shards exactly as
    ``batch_sharding`` does (derived from it, not re-filtered)."""
    return NamedSharding(mesh, P(None, *batch_sharding(mesh, axes).spec))
