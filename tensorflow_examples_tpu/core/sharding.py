"""Rules-based parameter sharding.

The reference never sharded parameters (pure DP: every replica held a full
copy, NCCL all-reduced gradients — BASELINE.json:north_star). A TPU-native
framework shards by annotation instead: each model ships a small table of
``(param-path regex → PartitionSpec)`` rules; ``shard_params`` applies them
and ``jax.jit`` compiles the collectives. Unmatched params are replicated,
which reproduces the reference's DP behavior as the degenerate case.
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any


class ShardingRules:
    """Ordered (regex, PartitionSpec) table; first match wins.

    Paths are '/'-joined pytree key paths, e.g.
    ``"transformer/h_3/attn/c_attn/kernel"``.
    """

    def __init__(self, rules: Sequence[tuple[str, P]] = ()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, path: str) -> P:
        for pat, spec in self.rules:
            if pat.search(path):
                return spec
        return P()  # replicate

    def __add__(self, other: "ShardingRules") -> "ShardingRules":
        out = ShardingRules()
        out.rules = list(self.rules) + list(other.rules)
        return out


REPLICATED = ShardingRules()


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _rule_path(path) -> str:
    """The path a RULES table matches against. A QuantizedWeight
    child (``core/precision``) resolves under its WEIGHT's path — the
    ``q``/``scale`` tail is stripped — so anchored patterns like
    ``"/kernel$"`` keep matching after quantization extends the leaf
    paths; otherwise an anchored table would silently replicate every
    quantized weight (the legal no-match fallback)."""
    if path:
        from tensorflow_examples_tpu.core.precision import QuantLeafKey

        if type(path[-1]) is QuantLeafKey:
            path = path[:-1]
    return _path_str(path)


def _clip_spec(spec: P, path, leaf) -> P:
    """Clip an over-ranked spec to the leaf's rank — ONLY for the
    ``scale`` child of a ``core/precision.QuantizedWeight`` (keyed on
    the key-path entry's TYPE, not its name: LayerNorm params are
    also literally named ``scale`` and must keep the loud rank
    failure). The scale lives under its weight's own path with one
    fewer dim (the scaled-over last axis), so the weight's rule
    places it by its LEADING dims — "scales sharded like their
    weights" without a second rules table. Every other leaf keeps an
    over-ranked spec untouched, so a mis-written rule still fails at
    placement instead of silently clipping to a different layout."""
    shape = getattr(leaf, "shape", None)
    if shape is None or len(spec) <= len(shape) or not path:
        return spec
    from tensorflow_examples_tpu.core.precision import QuantLeafKey

    if not (
        type(path[-1]) is QuantLeafKey and path[-1].key == "scale"
    ):
        return spec
    return P(*tuple(spec)[: len(shape)])


def _filter_spec(spec: P, mesh: Mesh) -> P:
    """Drop mesh axes of size 1 from a spec (cheaper layouts, same math)."""

    def keep(axis):
        if axis is None:
            return None
        if isinstance(axis, (tuple, list)):
            kept = tuple(a for a in axis if mesh.shape[a] > 1)
            return kept if kept else None
        return axis if mesh.shape[axis] > 1 else None

    return P(*(keep(a) for a in spec))


def shardings_for_params(
    params: Pytree, mesh: Mesh, rules: ShardingRules | None = None
) -> Pytree:
    """Pytree of NamedSharding matching ``params``' structure."""
    rules = rules or REPLICATED

    def one(path, leaf):
        spec = _filter_spec(
            _clip_spec(rules.spec_for(_rule_path(path)), path, leaf),
            mesh,
        )
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, params)


def shard_params(
    params: Pytree, mesh: Mesh, rules: ShardingRules | None = None
) -> Pytree:
    """Place (device_put) a param pytree according to the rules."""
    shardings = shardings_for_params(params, mesh, rules)
    return jax.device_put(params, shardings)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, _filter_spec(P(*spec), mesh))


def batch_sharding(mesh: Mesh, axes=None) -> NamedSharding:
    """Sharding for a [global_batch, ...] array: batch over the given
    batch-like axes (default data+fsdp), size-1 axes filtered."""
    if axes is None:
        from tensorflow_examples_tpu.core.mesh import AxisNames

        axes = AxisNames.BATCH_AXES
    kept = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
    return NamedSharding(mesh, P(kept if kept else None))


def bundle_sharding(mesh: Mesh, axes=None) -> NamedSharding:
    """Sharding for a [k, global_batch, ...] step bundle: the scan axis
    (dim 0) is unsharded; the batch dim behind it shards exactly as
    ``batch_sharding`` does (derived from it, not re-filtered)."""
    return NamedSharding(mesh, P(None, *batch_sharding(mesh, axes).spec))
