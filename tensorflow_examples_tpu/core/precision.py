"""Precision registry: training policy, KV quantization, and the
weight-quantization registry behind int8/fp8 end-to-end serving.

Three layers, grown in order:

* **PrecisionPolicy** (ISSUE 0 era) — bf16-compute/f32-params training
  casts. The MXU natively consumes bfloat16; keeping activations in
  bf16 roughly doubles arithmetic throughput versus f32 with f32
  accumulation inside the MXU.
* **Row quantization** (ISSUE 8) — symmetric per-row int8 (and now
  fp8) with f32 scales, originally for the paged KV cache: each row
  carries its own scale so rows append one decode step at a time
  without requantizing their block.
* **PrecisionConfig** (ISSUE 15 tentpole) — a serializable per-subtree
  dtype registry, the ``ShardingConfig``-rules-table shape applied to
  dtypes: ``[(path-regex, dtype)]``, first match wins.
  :func:`quantize_tree` applies it to a param tree **at load time** on
  the host (no device materialization — a model that only fits
  sharded must never land whole on device 0), replacing each matched
  ≥2-D floating leaf with a :class:`QuantizedWeight`: int8/fp8 payload
  plus per-row f32 scales over the last axis. The serving forward
  dequantizes **in the matmul** (:func:`materialize` /
  :func:`take_rows` inside the jitted step, where XLA fuses the
  scale-multiply into the consuming dot), so weights live in HBM at
  1 byte/element — the fleet-economics lever: HBM per replica bounds
  replicas per host. ``kv_dtype`` rides on the same config, unifying
  the cache and weight quantization paths (fp8 KV falls out for free).

Per-row-over-the-last-axis scales are what make the registry compose
with sharding (ISSUE 7): a ``QuantizedWeight`` flattens into two
ordinary leaves named ``q``/``scale`` under the weight's own path, so
the weight's PartitionSpec places ``q`` unchanged and, clipped to the
scale's rank, places the scale exactly like its weight's leading dims
(``core/sharding.shardings_for_params`` does the clipping).
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import json
import os
import re
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np


class Precision(str, enum.Enum):
    F32 = "f32"
    BF16 = "bf16"  # bf16 compute, f32 params ("mixed")
    BF16_FULL = "bf16_full"  # bf16 everything (memory-bound inference)


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    param_dtype: jnp.dtype
    compute_dtype: jnp.dtype

    @classmethod
    def create(cls, precision: Precision | str) -> "PrecisionPolicy":
        precision = Precision(precision)
        if precision == Precision.F32:
            return cls(jnp.float32, jnp.float32)
        if precision == Precision.BF16:
            return cls(jnp.float32, jnp.bfloat16)
        return cls(jnp.bfloat16, jnp.bfloat16)

    def cast_compute(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )


# ----------------------------------------------------------- int8 KV
#
# Symmetric per-row int8 quantization for the serving KV cache
# (serving/paged_kv.py): each cache row — one token's K or V for one
# head — carries its own f32 scale, stored blockwise alongside the
# int8 payload, so rows can be appended one decode step at a time
# without requantizing the rest of the block. Halving (vs bf16) or
# quartering (vs f32) KV bytes is the whole point: decode is
# memory-bandwidth bound, so cache bytes read per step is TPOT.

INT8_MAX = 127.0


def quantize_int8_rows(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """``x [..., D]`` -> (int8 values ``[..., D]``, f32 scales
    ``[...]``). Symmetric absmax over the last axis; an all-zero row
    gets scale 1 (dequantizes back to exact zeros)."""
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.where(amax > 0, amax / INT8_MAX, 1.0)
    q = jnp.clip(
        jnp.round(x / scale[..., None]), -INT8_MAX, INT8_MAX
    ).astype(jnp.int8)
    return q, scale


def dequantize_int8_rows(q: jax.Array, scale: jax.Array,
                         dtype=jnp.float32) -> jax.Array:
    """Inverse of :func:`quantize_int8_rows` (``scale`` broadcasts over
    the last axis of ``q``). Dtype-generic on the payload side — an
    fp8 ``q`` dequantizes through the same f32 multiply, so every
    int8 read path gained fp8 for free (:func:`dequantize_rows` is the
    honest alias)."""
    return (q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)
            ).astype(dtype)


# ------------------------------------------------------- fp8 + generic

# Largest finite float8_e4m3fn value — the fp8 twin of INT8_MAX.
FP8_MAX = 448.0

QUANT_DTYPES = ("int8", "fp8")
CAST_DTYPES = ("f32", "bf16")
_CASTS = {"f32": jnp.float32, "bf16": jnp.bfloat16}


def fp8_dtype():
    """``jnp.float8_e4m3fn`` when this jax build ships it, else None."""
    return getattr(jnp, "float8_e4m3fn", None)


@functools.lru_cache(maxsize=1)
def fp8_supported() -> bool:
    """Whether fp8 storage works end to end on this build/backend
    (dtype exists AND casts round-trip). The registry gates fp8 rules
    on this — absent support is a loud ValueError at load time, never
    a silently-f32 tree."""
    dt = fp8_dtype()
    if dt is None:
        return False
    try:
        roundtrip = jnp.ones((2,), jnp.float32).astype(dt).astype(
            jnp.float32
        )
        return bool(np.asarray(roundtrip)[0] == 1.0)
    except Exception:  # pragma: no cover - backend-specific failures
        return False


def _store_dtype(name: str):
    if name == "int8":
        return jnp.int8
    dt = fp8_dtype()
    if dt is None or not fp8_supported():
        raise ValueError(
            "dtype 'fp8' requested but this jax build/backend has no "
            "working float8_e4m3fn — use 'int8' here"
        )
    return dt


def quantize_rows(x: jax.Array, dtype) -> tuple[jax.Array, jax.Array]:
    """Per-row quantization to ``dtype`` (``jnp.int8`` or the fp8
    dtype): symmetric absmax over the last axis, f32 scales. The int8
    branch IS :func:`quantize_int8_rows` (the paged pool's contract);
    fp8 scales rows to the e4m3 range and relies on the cast's own
    rounding."""
    dtype = jnp.dtype(dtype)
    if dtype == jnp.int8:
        return quantize_int8_rows(x)
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.where(amax > 0, amax / FP8_MAX, 1.0)
    return (x / scale[..., None]).astype(dtype), scale


dequantize_rows = dequantize_int8_rows


def _quantize_rows_host(x: np.ndarray, name: str):
    """The load-time (host, numpy) twin of :func:`quantize_rows`: no
    jax dispatch, no device placement — the quantized tree is built
    before ``shard_params``/``asarray`` decides where leaves live."""
    x = np.asarray(x, np.float32)
    amax = np.max(np.abs(x), axis=-1)
    if name == "int8":
        scale = np.where(amax > 0, amax / INT8_MAX, 1.0).astype(
            np.float32
        )
        q = np.clip(
            np.rint(x / scale[..., None]), -INT8_MAX, INT8_MAX
        ).astype(np.int8)
        return q, scale
    scale = np.where(amax > 0, amax / FP8_MAX, 1.0).astype(np.float32)
    return (x / scale[..., None]).astype(
        np.dtype(_store_dtype("fp8"))
    ), scale


# ---------------------------------------------------- quantized leaves


class QuantLeafKey:
    """Key-path entry for a :class:`QuantizedWeight`'s children.
    Carries ``.key`` like a ``DictKey`` so every path renderer keeps
    producing ``.../kernel/q`` and ``.../kernel/scale``, but its
    distinct TYPE is what lets ``core/sharding._clip_spec`` recognize
    a quantization scale *structurally* — a LayerNorm param is also
    literally named ``scale``, and rank clipping must never apply to
    one (an over-ranked rule there must still fail loudly)."""

    __slots__ = ("key",)

    def __init__(self, key: str):
        self.key = key

    def __repr__(self):
        return f".{self.key}"

    def __eq__(self, other):
        return type(other) is QuantLeafKey and other.key == self.key

    def __hash__(self):
        return hash(("QuantLeafKey", self.key))


@jax.tree_util.register_pytree_with_keys_class
class QuantizedWeight:
    """One quantized param leaf: payload ``q`` (int8/fp8, the weight's
    own shape) + per-row f32 ``scale`` over the last axis
    (``scale.shape == q.shape[:-1]``).

    Registered as a pytree node whose children carry
    :class:`QuantLeafKey` keys ``q``/``scale``, so everything that
    walks param trees by path — sharding rules, byte accounting, jit
    tracing, ``asarray`` maps — sees two ordinary leaves under the
    weight's own path (``.../kernel/q``, ``.../kernel/scale``) and
    the weight's PartitionSpec places the scale via rank clipping
    (``core/sharding``, keyed on the key's type). Dequantization
    happens at the consuming matmul (:func:`materialize`), never at
    rest.
    """

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    def tree_flatten_with_keys(self):
        return (
            (QuantLeafKey("q"), self.q),
            (QuantLeafKey("scale"), self.scale),
        ), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    def dequantize(self, dtype=jnp.float32):
        return dequantize_rows(self.q, self.scale, dtype)

    def __repr__(self):
        return (
            f"QuantizedWeight(shape={tuple(self.q.shape)}, "
            f"store={jnp.dtype(self.q.dtype).name})"
        )


def materialize(w, dtype=jnp.float32):
    """The dequant-in-matmul access point: a :class:`QuantizedWeight`
    dequantizes HERE — called inside the jitted forward so XLA fuses
    the f32 scale-multiply into the consuming dot and the weight is
    read from HBM at 1 byte/element. Plain leaves pass through
    untouched (zero-cost when nothing is quantized)."""
    if isinstance(w, QuantizedWeight):
        return w.dequantize(dtype)
    return w


def take_rows(w, idx, dtype=jnp.float32):
    """Row gather for embedding tables: a quantized table gathers the
    int8 rows + their scales and dequantizes only what was taken (a
    full-table dequant per lookup would defeat the HBM story)."""
    if isinstance(w, QuantizedWeight):
        return dequantize_rows(w.q[idx], w.scale[idx], dtype)
    return w[idx]


# ------------------------------------------------- the dtype registry

# The on-disk format version of a precision.json (NOT telemetry schema).
PRECISION_JSON_VERSION = 1

_LEGAL_RULE_DTYPES = QUANT_DTYPES + CAST_DTYPES + ("",)

# weight_only(): quantize the tensors matmuls consume — kernels and
# embedding tables. Everything else (LayerNorm scale/bias, biases —
# additive paths where error accumulates and bytes are negligible)
# keeps its dtype.
WEIGHT_PATTERNS = (r"/kernel$", r"(^|/)embedding$")


@dataclasses.dataclass(frozen=True)
class PrecisionConfig:
    """Serializable per-subtree dtype registry (ISSUE 15): the
    ``ShardingConfig`` rules-table shape applied to dtypes.

    * ``rules`` — ``[(path-regex, dtype)]``, first match wins; dtype
      in ``int8``/``fp8`` (per-row quantization of ≥2-D floating
      leaves), ``f32``/``bf16`` (a plain cast), or ``""`` (leave the
      subtree untouched — the escape hatch an earlier rule carves out
      of a later blanket one).
    * ``default`` — dtype for unmatched leaves (``""`` = untouched).
    * ``kv_dtype`` — the unified cache side: ``""``/``int8``/``fp8``,
      consumed by ``ServeConfig``/``PagedKVPool`` so one registry
      object names both halves of the serving memory story.
    """

    rules: tuple = ()
    default: str = ""
    kv_dtype: str = ""

    def __post_init__(self):
        for entry in self.rules:
            if not isinstance(entry, (list, tuple)) or len(entry) != 2:
                raise ValueError(
                    f"precision rule {entry!r} must be (pattern, dtype)"
                )
        for name in [d for _, d in self.rules] + [self.default]:
            if name not in _LEGAL_RULE_DTYPES:
                raise ValueError(
                    f"precision dtype {name!r} not in "
                    f"{_LEGAL_RULE_DTYPES}"
                )
        if self.kv_dtype not in ("",) + QUANT_DTYPES:
            raise ValueError(
                f"kv_dtype={self.kv_dtype!r} not in "
                f"{('',) + QUANT_DTYPES}"
            )
        object.__setattr__(
            self,
            "rules",
            tuple((str(p), str(d)) for p, d in self.rules),
        )

    @classmethod
    def weight_only(cls, dtype: str, *,
                    kv_dtype: str = "") -> "PrecisionConfig":
        """The standard serving registry: quantize every matmul weight
        (kernels + embedding tables) to ``dtype``, leave norms/biases
        alone. ``dtype=""`` returns the identity config."""
        if not dtype:
            return cls(kv_dtype=kv_dtype)
        if dtype not in QUANT_DTYPES:
            raise ValueError(
                f"weight dtype {dtype!r} not in {QUANT_DTYPES}"
            )
        return cls(
            rules=tuple((p, dtype) for p in WEIGHT_PATTERNS),
            kv_dtype=kv_dtype,
        )

    def dtype_for(self, path: str) -> str:
        for pat, d in self.rules:
            if re.search(pat, path):
                return d
        return self.default

    @property
    def quantizes(self) -> bool:
        return any(
            d in QUANT_DTYPES
            for d in [self.default] + [d for _, d in self.rules]
        )

    # ---------------------------------------------------- serialization

    def to_json_dict(self) -> dict:
        return {
            "rules": [[p, d] for p, d in self.rules],
            "default": self.default,
            "kv_dtype": self.kv_dtype,
        }

    @classmethod
    def from_json_dict(cls, obj: Mapping) -> "PrecisionConfig":
        if not isinstance(obj, Mapping):
            raise ValueError(
                f"precision config must be a JSON object, got "
                f"{type(obj).__name__}"
            )
        unknown = set(obj) - {"rules", "default", "kv_dtype"}
        if unknown:
            raise ValueError(
                f"unknown precision config keys {sorted(unknown)}"
            )
        rules = obj.get("rules", ())
        if not isinstance(rules, (list, tuple)) or any(
            not isinstance(e, (list, tuple)) or len(e) != 2
            for e in rules
        ):
            # Every malformation is a ValueError (the documented loud
            # contract), never a TypeError from the unpack below.
            raise ValueError(
                f"precision rules must be [pattern, dtype] pairs, got "
                f"{rules!r}"
            )
        return cls(
            rules=tuple((str(p), str(d)) for p, d in rules),
            default=str(obj.get("default", "")),
            kv_dtype=str(obj.get("kv_dtype", "")),
        )

    def save(self, path: str) -> None:
        doc = {
            "version": PRECISION_JSON_VERSION,
            "config": self.to_json_dict(),
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "PrecisionConfig":
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"{path}: not a JSON object")
        if "config" in doc:
            version = doc.get("version")
            if version != PRECISION_JSON_VERSION:
                raise ValueError(
                    f"{path}: precision.json version {version!r} "
                    f"(this build reads {PRECISION_JSON_VERSION})"
                )
            return cls.from_json_dict(doc["config"])
        return cls.from_json_dict(doc)


def _tree_path_str(path) -> str:
    """The '/'-joined key-path rendering — THE one from
    ``core/sharding`` (deferred import: sharding's own lazy precision
    imports would otherwise race module init), so PrecisionConfig and
    ShardingConfig rules always match the same rendering of the same
    tree path."""
    from tensorflow_examples_tpu.core.sharding import _path_str

    return _path_str(path)


def quantize_tree(params, config: PrecisionConfig):
    """Apply the registry to a param tree AT LOAD TIME, on the host:
    matched ≥2-D floating leaves become :class:`QuantizedWeight`
    (int8/fp8 payload + per-row f32 scales), cast rules cast, the rest
    pass through. Runs in numpy — no device dispatch, so the sharded
    path still places every byte straight into its mesh layout.
    1-D floating leaves (biases, norms) are never quantized even under
    a blanket rule: per-row scales need a row axis, and their bytes
    are noise."""
    if config.quantizes and any(
        d == "fp8"
        for d in [config.default] + [d for _, d in config.rules]
    ) and not fp8_supported():
        raise ValueError(
            "precision config requests fp8 weights but this jax "
            "build/backend has no working float8_e4m3fn"
        )

    def one(path, leaf):
        dt = getattr(leaf, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.floating):
            return leaf
        name = config.dtype_for(_tree_path_str(path))
        if not name:
            return leaf
        if name in QUANT_DTYPES:
            if getattr(leaf, "ndim", 0) < 2:
                return leaf
            q, scale = _quantize_rows_host(np.asarray(leaf), name)
            return QuantizedWeight(q, scale)
        return np.asarray(leaf).astype(np.dtype(_CASTS[name]))

    return jax.tree_util.tree_map_with_path(
        one, params, is_leaf=lambda x: isinstance(x, QuantizedWeight)
    )


def tree_precision_stats(params) -> dict:
    """Numeric facts about a (possibly quantized) param tree — the
    ``precision/*`` gauges and the schema-v11 serving keys:
    ``param_bytes`` (as stored), ``param_bytes_f32`` (what the same
    logical tree would cost at 4 bytes/element), ``quantized_params``
    (QuantizedWeight leaf count) and ``weight_bits`` (payload bits of
    the quantized leaves; the floating itemsize when none are)."""
    stored = f32 = 0
    quantized = 0
    bits = None
    for leaf in jax.tree.leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedWeight)
    ):
        if isinstance(leaf, QuantizedWeight):
            quantized += 1
            size = int(np.prod(leaf.q.shape, dtype=np.int64))
            stored += size * jnp.dtype(leaf.q.dtype).itemsize
            stored += int(
                np.prod(leaf.scale.shape, dtype=np.int64)
            ) * 4
            f32 += size * 4
            bits = jnp.dtype(leaf.q.dtype).itemsize * 8
            continue
        shape = tuple(getattr(leaf, "shape", ()))
        itemsize = int(
            getattr(getattr(leaf, "dtype", None), "itemsize", 0) or 0
        )
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        stored += size * itemsize
        if itemsize and jnp.issubdtype(leaf.dtype, jnp.floating):
            f32 += size * 4
            if bits is None:
                bits = itemsize * 8
        else:
            f32 += size * itemsize
    return {
        "param_bytes": stored,
        "param_bytes_f32": f32,
        "quantized_params": quantized,
        "weight_bits": bits if bits is not None else 32,
    }
