"""Precision policy: bf16 compute, f32 master params.

The MXU natively consumes bfloat16; keeping activations/matmuls in bf16
roughly doubles arithmetic throughput and halves HBM traffic versus f32,
with f32 accumulation inside the MXU. The reference ran f32 (stock TF
examples); this is one of the places a TPU-first design beats a port.
"""

from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp


class Precision(str, enum.Enum):
    F32 = "f32"
    BF16 = "bf16"  # bf16 compute, f32 params ("mixed")
    BF16_FULL = "bf16_full"  # bf16 everything (memory-bound inference)


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    param_dtype: jnp.dtype
    compute_dtype: jnp.dtype

    @classmethod
    def create(cls, precision: Precision | str) -> "PrecisionPolicy":
        precision = Precision(precision)
        if precision == Precision.F32:
            return cls(jnp.float32, jnp.float32)
        if precision == Precision.BF16:
            return cls(jnp.float32, jnp.bfloat16)
        return cls(jnp.bfloat16, jnp.bfloat16)

    def cast_compute(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )


# ----------------------------------------------------------- int8 KV
#
# Symmetric per-row int8 quantization for the serving KV cache
# (serving/paged_kv.py): each cache row — one token's K or V for one
# head — carries its own f32 scale, stored blockwise alongside the
# int8 payload, so rows can be appended one decode step at a time
# without requantizing the rest of the block. Halving (vs bf16) or
# quartering (vs f32) KV bytes is the whole point: decode is
# memory-bandwidth bound, so cache bytes read per step is TPOT.

INT8_MAX = 127.0


def quantize_int8_rows(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """``x [..., D]`` -> (int8 values ``[..., D]``, f32 scales
    ``[...]``). Symmetric absmax over the last axis; an all-zero row
    gets scale 1 (dequantizes back to exact zeros)."""
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.where(amax > 0, amax / INT8_MAX, 1.0)
    q = jnp.clip(
        jnp.round(x / scale[..., None]), -INT8_MAX, INT8_MAX
    ).astype(jnp.int8)
    return q, scale


def dequantize_int8_rows(q: jax.Array, scale: jax.Array,
                         dtype=jnp.float32) -> jax.Array:
    """Inverse of :func:`quantize_int8_rows` (``scale`` broadcasts over
    the last axis of ``q``)."""
    return (q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)
            ).astype(dtype)
