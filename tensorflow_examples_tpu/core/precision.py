"""Precision policy: bf16 compute, f32 master params.

The MXU natively consumes bfloat16; keeping activations/matmuls in bf16
roughly doubles arithmetic throughput and halves HBM traffic versus f32,
with f32 accumulation inside the MXU. The reference ran f32 (stock TF
examples); this is one of the places a TPU-first design beats a port.
"""

from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp


class Precision(str, enum.Enum):
    F32 = "f32"
    BF16 = "bf16"  # bf16 compute, f32 params ("mixed")
    BF16_FULL = "bf16_full"  # bf16 everything (memory-bound inference)


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    param_dtype: jnp.dtype
    compute_dtype: jnp.dtype

    @classmethod
    def create(cls, precision: Precision | str) -> "PrecisionPolicy":
        precision = Precision(precision)
        if precision == Precision.F32:
            return cls(jnp.float32, jnp.float32)
        if precision == Precision.BF16:
            return cls(jnp.float32, jnp.bfloat16)
        return cls(jnp.bfloat16, jnp.bfloat16)

    def cast_compute(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )
