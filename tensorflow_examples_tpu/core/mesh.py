"""Device mesh construction and axis conventions.

The reference's distribution layer was per-example ``tf.distribute``
strategies (MirroredStrategy for single-host DP, MultiWorkerMirroredStrategy
for BERT's multi-host DP; BASELINE.json:north_star). TPU-native, all of that
collapses into ONE concept: a ``jax.sharding.Mesh`` with named axes. Data
parallelism is "shard the batch over the ``data`` axis"; tensor parallelism
is "shard weight matrices over ``model``"; sequence/context parallelism is
"shard the sequence over ``context``". XLA emits psum/all-gather/ppermute
over ICI for whatever sharding is requested — there is no user-space NCCL
equivalent to manage.

Axis conventions (used by every model and sharding rule in the framework):

- ``data``    — pure data parallelism (batch dim). Gradients are all-reduced
                over this axis by XLA when params are replicated across it.
- ``fsdp``    — batch AND parameter sharding (ZeRO-3 style). Params are
                sharded over this axis and all-gathered just-in-time.
- ``model``   — tensor parallelism (hidden/heads dims).
- ``context`` — sequence/context parallelism (ring attention).
- ``pipe``    — pipeline parallelism (layer stages, GPipe microbatching).

A single-chip run is simply a 1×1×1×1 mesh; code written against the mesh
runs unchanged from 1 chip to a multi-host slice.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh


class AxisNames:
    """Canonical mesh axis names."""

    DATA = "data"
    FSDP = "fsdp"
    MODEL = "model"
    CONTEXT = "context"
    PIPE = "pipe"

    ALL = (DATA, FSDP, MODEL, CONTEXT, PIPE)

    # The batch dimension of activations is sharded over every
    # batch-like axis.
    BATCH_AXES = (DATA, FSDP)


def token_partition_axes(
    mesh,
    batch_dim: int,
    seq_dim: int | None = None,
    *,
    include_model: bool = False,
) -> tuple[tuple, tuple]:
    """Shared axis-dropping policy for token-parallel shard_maps.

    Returns ``(batch_axes, seq_axes)`` for partitioning a ``[B, S, ...]``
    activation over the mesh: every nontrivial batch-like axis shards
    the batch dim (ALL dropped if their product doesn't divide it —
    jit in_specs must divide exactly, and decode-time batch=1 is the
    common non-dividing case), ``context`` shards the seq dim when it
    divides, and — when ``include_model`` — ``model`` joins the seq
    sharding if it also divides (token-independent ops like CE are
    replicated work under TP otherwise). Consumers: ``parallel/moe.py``
    (batch policy), ``ops/cross_entropy.py`` (batch + seq + model).
    Axes dropped here mean the tokens REPLICATE over that axis, which
    is always correct, just less parallel.
    """

    batch_axes = tuple(a for a in AxisNames.BATCH_AXES if mesh.shape[a] > 1)
    nb = math.prod(mesh.shape[a] for a in batch_axes) if batch_axes else 1
    if batch_dim % nb:
        batch_axes = ()
    seq_axes: tuple = ()
    if seq_dim is not None:
        c = mesh.shape[AxisNames.CONTEXT]
        if c > 1 and seq_dim % c == 0:
            seq_axes += (AxisNames.CONTEXT,)
        if include_model:
            m = mesh.shape[AxisNames.MODEL]
            denom = (c if seq_axes else 1) * m
            if m > 1 and seq_dim % denom == 0:
                seq_axes += (AxisNames.MODEL,)
    return batch_axes, seq_axes


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. -1 for ``data`` means "all remaining devices"."""

    data: int = -1
    fsdp: int = 1
    model: int = 1
    context: int = 1
    pipe: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int, int, int]:
        fixed = self.fsdp * self.model * self.context * self.pipe
        data = self.data
        if data == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"fsdp*model*context*pipe={fixed}"
                )
            data = n_devices // fixed
        total = data * fixed
        if total != n_devices:
            raise ValueError(
                f"mesh {data}x{self.fsdp}x{self.model}x{self.context}"
                f"x{self.pipe}={total} != available devices {n_devices}"
            )
        return (data, self.fsdp, self.model, self.context, self.pipe)


def create_mesh(
    config: MeshConfig | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build the framework-standard 5-axis mesh.

    ``jax.experimental.mesh_utils`` is used when available so the mesh
    layout follows the physical ICI topology (keeps the fastest-varying
    logical axis on the torus); on CPU / single chip it degenerates to a
    simple reshape.
    """
    if devices is None:
        devices = jax.devices()
    config = config or MeshConfig()
    shape = config.resolve(len(devices))
    try:
        from jax.experimental import mesh_utils

        device_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    except Exception:
        device_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(device_array, axis_names=AxisNames.ALL)


def local_batch_size(global_batch_size: int, mesh: Mesh) -> int:
    """Per-host batch size for input pipelines (tf.data ``shard()`` analogue).

    The reference sharded input per worker via
    ``dataset.shard(num_workers, index)`` inside
    MultiWorkerMirroredStrategy (SURVEY.md §3(5)); here each host feeds the
    slice of the global batch that lands on its addressable devices.
    """
    n_batch = math.prod(mesh.shape[a] for a in AxisNames.BATCH_AXES)
    if global_batch_size % n_batch:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by batch mesh size {n_batch}"
        )
    per_shard = global_batch_size // n_batch
    local_shards = max(1, n_batch // jax.process_count())
    return per_shard * local_shards
