"""Thin collective façade for ``shard_map`` code (SURVEY.md §5h).

The reference stack's communication backend was NCCL under
``tf.distribute`` cross-device ops; on TPU there is no user-space
transport to write — collectives are XLA HLO ops routed over ICI within
a slice and DCN across slices by the compiler. This module is the
framework's single naming point for them: ``shard_map`` code imports
from here, so grepping call sites answers "what does this program put on
the interconnect", and the bandwidth microbenchmark (``bench.py
--bench=collectives``, the NCCL-perf-test replacement) measures exactly
these ops.

All functions are ``jax.lax`` passthroughs with the framework's axis
conventions documented; they are valid only inside ``shard_map`` (or
``pmap``) over a mesh axis.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
from jax import lax

AxisName = str | Sequence[str]


def shard_map(
    f: Callable,
    *,
    mesh=None,
    in_specs,
    out_specs,
    axis_names: set | None = None,
    check_vma: bool = True,
):
    """Version-portable ``shard_map`` (the framework's single spelling).
    ``check_vma`` defaults to True to match ``jax.shard_map`` — callers
    that need it off (every Pallas-opaque site today) say so.

    Newer jax exposes ``jax.shard_map`` (manual axes named via
    ``axis_names``, replication checking via ``check_vma``); on older
    builds the same program spells ``jax.experimental.shard_map``
    (manual-set complement via ``auto``, checking via ``check_rep``).
    Every shard_map in the framework routes through here so the
    collectives layer — not each caller — owns the translation, and a
    jax upgrade/downgrade is one-file work.
    """
    if hasattr(jax, "shard_map"):
        kw: dict = {"check_vma": check_vma}
        if mesh is not None:
            kw["mesh"] = mesh
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return jax.shard_map(
            f, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    if mesh is None:
        # The meshless form (manual axes resolved from the enclosing
        # shard_map context) has no pre-jax.shard_map equivalent.
        raise NotImplementedError(
            "context-mesh shard_map (mesh=None) requires a jax build "
            "with jax.shard_map"
        )
    kw = {"check_rep": check_vma}
    if axis_names is not None:
        # jax.shard_map names the MANUAL axes; the experimental API
        # names the complement ("auto" axes).
        kw["auto"] = frozenset(mesh.axis_names) - set(axis_names)
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )


def psum(x: Any, axis: AxisName) -> Any:
    """All-reduce sum over a mesh axis (the DP gradient reduction;
    bidirectional-ring bandwidth 2(n-1)/n · payload over ICI)."""
    return lax.psum(x, axis)


def pmean(x: Any, axis: AxisName) -> Any:
    """All-reduce mean — metric aggregation across data shards."""
    return lax.pmean(x, axis)


def pmax(x: Any, axis: AxisName) -> Any:
    """All-reduce max — e.g. the global row max in vocab-parallel CE."""
    return lax.pmax(x, axis)


def all_gather(x: Any, axis: AxisName, *, axis_index_groups=None, tiled=True):
    """Gather shards along the axis ((n-1)/n · result bytes on the wire).
    ``tiled=True`` concatenates along dim 0 (the FSDP parameter
    un-shard); ``tiled=False`` stacks a new leading dim."""
    return lax.all_gather(
        x, axis, axis_index_groups=axis_index_groups, tiled=tiled
    )


def reduce_scatter(x: Any, axis: AxisName, *, scatter_dimension=0):
    """Sum-reduce then scatter shards — the ZeRO gradient primitive;
    half an all-reduce's traffic when each rank only needs its shard."""
    return lax.psum_scatter(
        x, axis, scatter_dimension=scatter_dimension, tiled=True
    )


def ppermute(x: Any, axis: AxisName, perm: Sequence[tuple[int, int]]):
    """Point-to-point permutation. With ``ring_perm`` this is the
    nearest-neighbor ICI hop ring attention and GPipe are built on."""
    return lax.ppermute(x, axis, perm)


def ring_perm(axis_size: int) -> list[tuple[int, int]]:
    """The (i → i+1 mod n) permutation: one ring hop."""
    return [(i, (i + 1) % axis_size) for i in range(axis_size)]


def all_to_all(
    x: Any, axis: AxisName, *, split_axis: int, concat_axis: int, tiled=True
):
    """Transpose shards across the axis — resharding one array dimension
    for another (Ulysses sequence↔heads, MoE token↔expert exchanges)."""
    return lax.all_to_all(
        x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


def axis_index(axis: AxisName) -> jax.Array:
    """This device's coordinate along the mesh axis."""
    return lax.axis_index(axis)


def axis_size(axis: AxisName) -> int:
    """Number of shards along the mesh axis. ``lax.axis_size`` where
    the jax build has it; ``psum(1, axis)`` — which jax constant-folds
    to the static size — on older builds."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)
