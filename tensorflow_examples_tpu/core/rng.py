"""RNG handling.

JAX's explicit threaded PRNG replaces TF's stateful global RNG. Step keys
are derived by folding the step count into a root key inside the compiled
step, so dropout etc. are deterministic given (seed, step) — which also
makes checkpoint resume bit-exact (the reference could not guarantee this
with stateful ``tf.random``).
"""

from __future__ import annotations

import jax


def step_rng(root_key: jax.Array, step: jax.Array) -> jax.Array:
    """Per-step key, usable inside jit (step may be traced)."""
    return jax.random.fold_in(root_key, step)


def named_rngs(
    key: jax.Array, names: tuple[str, ...] = ("dropout",)
) -> dict[str, jax.Array]:
    """Split one key into a flax ``rngs`` dict with stable per-name streams."""
    return {n: jax.random.fold_in(key, i) for i, n in enumerate(names)}
