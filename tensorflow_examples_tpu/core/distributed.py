"""Multi-host bootstrap.

The reference bootstrapped multi-worker training from a ``TF_CONFIG`` env
var through a cluster resolver and gRPC collective setup (SURVEY.md §3(5),
for BERT's MultiWorkerMirroredStrategy). The TPU-native equivalent is a
single call: ``jax.distributed.initialize()`` — on Cloud TPU the
coordinator address, process count, and process index are discovered from
the TPU metadata automatically; collectives then ride ICI within a slice
and DCN across slices with no user-space transport to configure.
"""

from __future__ import annotations

import logging
import os

import jax

log = logging.getLogger(__name__)

_INITIALIZED = False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Idempotent multi-host init. Safe to call in single-process runs.

    Explicit args (or JAX_COORDINATOR_ADDRESS etc.) are only needed
    off-cloud; on TPU VMs everything is auto-discovered.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    multi_process = (
        num_processes is not None
        or coordinator_address is not None
        or os.environ.get("JAX_NUM_PROCESSES")
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
    )
    if multi_process:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        log.info(
            "jax.distributed initialized: process %d/%d, %d local / %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.local_device_count(),
            jax.device_count(),
        )
    _INITIALIZED = True


def is_primary() -> bool:
    """True on the process that should write checkpoints/summaries."""
    return jax.process_index() == 0
