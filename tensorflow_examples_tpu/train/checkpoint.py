"""Checkpoint/resume on orbax.

Replaces the reference's per-example ``tf.train.CheckpointManager``
(SURVEY.md §2b/§5d) with orbax: async saves (the step never blocks on
filesystem IO), sharded arrays saved/restored directly to the live mesh
layout, and automatic latest-checkpoint resume.
"""

from __future__ import annotations

import logging
from typing import Any

import orbax.checkpoint as ocp

log = logging.getLogger(__name__)


class CheckpointManager:
    def __init__(self, workdir: str, *, max_to_keep: int = 3, async_save: bool = True):
        import os

        self._mngr = ocp.CheckpointManager(
            os.path.abspath(os.path.join(workdir, "checkpoints")),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, step: int, state: Any) -> None:
        self._mngr.save(step, args=ocp.args.StandardSave(_as_dict(state)))

    def restore_latest(self, state: Any) -> tuple[Any, int] | None:
        """Restore into ``state``'s structure/shardings; None if no ckpt."""
        step = self._mngr.latest_step()
        if step is None:
            return None
        target = _as_dict(state)
        restored = self._mngr.restore(step, args=ocp.args.StandardRestore(target))
        merged = _merge_arrays(state, restored)
        log.info("restored checkpoint at step %d", step)
        return merged, step

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()


def _as_dict(state: Any) -> dict:
    """Array-only view of TrainState (fns/optimizer objects are rebuilt by
    the caller, orbax stores just the arrays)."""
    return {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
        "model_state": state.model_state,
    }


def _merge_arrays(state: Any, restored: dict) -> Any:
    return state.replace(
        step=restored["step"],
        params=restored["params"],
        opt_state=restored["opt_state"],
        model_state=restored.get("model_state", state.model_state),
    )
