"""Checkpoint/resume on orbax.

Replaces the reference's per-example ``tf.train.CheckpointManager``
(SURVEY.md §2b/§5d) with orbax: async saves (the step never blocks on
filesystem IO), sharded arrays saved/restored directly to the live mesh
layout, and automatic latest-checkpoint resume.

Crash safety: ``CheckpointManager`` is a context manager; ``close()``
(which waits for any in-flight async save) runs on the exception path
out of ``Trainer.fit`` too, so a crash never abandons a half-written
async save as the torn "latest" checkpoint. ``restore_latest`` validates
the saved tree structure/shapes/dtypes against the live state up front
and names the mismatching paths, instead of failing deep inside orbax on
shape or dtype drift.
"""

from __future__ import annotations

import logging
from typing import Any

import orbax.checkpoint as ocp

from tensorflow_examples_tpu.telemetry.registry import default_registry
from tensorflow_examples_tpu.telemetry.spans import span as _trace_span

log = logging.getLogger(__name__)


class CheckpointManager:
    def __init__(self, workdir: str, *, max_to_keep: int = 3, async_save: bool = True):
        import os

        # item_handlers pre-registers the standard handler so a FRESH
        # manager (the resume path) can read item_metadata — without it
        # orbax returns None metadata until the first save, and
        # restore-time structure validation would silently skip.
        self._mngr = ocp.CheckpointManager(
            os.path.abspath(os.path.join(workdir, "checkpoints")),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
            item_handlers=ocp.StandardCheckpointHandler(),
        )

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        # Always wait+close — an async save abandoned on the exception
        # path would otherwise be a torn latest-checkpoint.
        self.close()
        return False

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def save(self, step: int, state: Any) -> None:
        # The span covers the ENQUEUE only under async_save (orbax copies
        # device->host then commits in the background); the commit wait
        # shows up in whichever span wraps wait()/close().
        with _trace_span("checkpoint_save", step=step):
            self._mngr.save(step, args=ocp.args.StandardSave(_as_dict(state)))
        default_registry().counter("checkpoint/saves").inc()

    def restore_latest(
        self, state: Any, *, validate: bool = True
    ) -> tuple[Any, int] | None:
        """Restore into ``state``'s structure/shardings; None if no ckpt.

        Abstract template leaves (``jax.eval_shape`` ShapeDtypeStructs,
        the restore-only consumers' path — sampling/serving CLIs) carry
        no sharding; orbax refuses them for checkpoints that were SAVED
        sharded (docs/sharding.md). Such leaves get a default
        single-device placement here, so any checkpoint — written on
        any mesh — restores through a shardings-free template onto the
        local default device (resharding on restore is the contract)."""
        step = self._mngr.latest_step()
        if step is None:
            return None
        with _trace_span("checkpoint_restore", step=step):
            target = _with_default_shardings(_as_dict(state))
            if validate:
                self._validate_structure(step, target)
            restored = self._mngr.restore(
                step, args=ocp.args.StandardRestore(target)
            )
            merged = _merge_arrays(state, restored)
        default_registry().counter("checkpoint/restores").inc()
        log.info("restored checkpoint at step %d", step)
        return merged, step

    def _validate_structure(self, step: int, target: dict) -> None:
        """Compare the saved tree against the live state; raise a clear
        error naming every drifted path (missing / unexpected / shape or
        dtype mismatch) instead of letting orbax fail deep inside its
        restore machinery."""
        import jax.tree_util as jtu

        try:
            meta = self._mngr.item_metadata(step)
        except Exception as e:  # metadata is best-effort across versions
            log.debug("checkpoint metadata unavailable (%s); skipping", e)
            return
        if not isinstance(meta, dict):
            return

        def norm(path) -> str:
            # Saved metadata renders optax NamedTuple nodes as dicts while
            # the live tree flattens them with attribute keys ([0].count
            # vs ['0']['count']); normalize every entry to its bare
            # key/index so the two spellings compare equal.
            parts = []
            for p in path:
                for attr in ("key", "name", "idx"):
                    if hasattr(p, attr):
                        parts.append(str(getattr(p, attr)))
                        break
                else:  # pragma: no cover - unknown key type
                    parts.append(str(p))
            return "/".join(parts)

        def by_path(tree):
            return {
                norm(path): leaf
                for path, leaf in jtu.tree_flatten_with_path(tree)[0]
            }

        saved, live = by_path(meta), by_path(target)
        problems = []
        for path in sorted(set(live) - set(saved)):
            problems.append(f"missing from checkpoint: {path}")
        for path in sorted(set(saved) - set(live)):
            problems.append(f"not in live state: {path}")
        for path in sorted(set(saved) & set(live)):
            m, x = saved[path], live[path]
            m_shape = getattr(m, "shape", None)
            m_dtype = getattr(m, "dtype", None)
            x_shape = tuple(getattr(x, "shape", ()))
            if m_shape is not None and tuple(m_shape) != x_shape:
                problems.append(
                    f"shape mismatch at {path}: checkpoint "
                    f"{tuple(m_shape)} vs live {x_shape}"
                )
            elif m_dtype is not None and str(m_dtype) != str(
                getattr(x, "dtype", m_dtype)
            ):
                problems.append(
                    f"dtype mismatch at {path}: checkpoint {m_dtype} vs "
                    f"live {x.dtype}"
                )
        if problems:
            shown = "\n  ".join(problems[:20])
            more = (
                f"\n  ... and {len(problems) - 20} more"
                if len(problems) > 20
                else ""
            )
            raise ValueError(
                f"checkpoint at step {step} does not match the live train "
                f"state ({len(problems)} path(s) drifted — wrong model "
                "config or optimizer for this workdir?):\n  "
                f"{shown}{more}"
            )

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()


def _with_default_shardings(tree: Any) -> Any:
    """Give sharding-less abstract leaves a concrete single-device
    placement (concrete arrays and sharding-carrying structs pass
    through untouched)."""
    import jax

    default = None

    def one(leaf):
        nonlocal default
        if (
            isinstance(leaf, jax.ShapeDtypeStruct)
            and getattr(leaf, "sharding", None) is None
        ):
            if default is None:
                default = jax.sharding.SingleDeviceSharding(
                    jax.local_devices()[0]
                )
            return jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=default
            )
        return leaf

    return jax.tree.map(one, tree)


def _as_dict(state: Any) -> dict:
    """Array-only view of TrainState (fns/optimizer objects are rebuilt by
    the caller, orbax stores just the arrays)."""
    return {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
        "model_state": state.model_state,
    }


def _merge_arrays(state: Any, restored: dict) -> Any:
    return state.replace(
        step=restored["step"],
        params=restored["params"],
        opt_state=restored["opt_state"],
        model_state=restored.get("model_state", state.model_state),
    )
