"""Checkpoint/resume on orbax.

Replaces the reference's per-example ``tf.train.CheckpointManager``
(SURVEY.md §2b/§5d) with orbax: async saves (the step never blocks on
filesystem IO), sharded arrays saved/restored directly to the live mesh
layout, and automatic latest-checkpoint resume.

Crash safety: ``CheckpointManager`` is a context manager; ``close()``
(which waits for any in-flight async save) runs on the exception path
out of ``Trainer.fit`` too, so a crash never abandons a half-written
async save as the torn "latest" checkpoint. ``restore_latest`` validates
the saved tree structure/shapes/dtypes against the live state up front
and names the mismatching paths, instead of failing deep inside orbax on
shape or dtype drift.

Integrity (ISSUE 10 satellite): every COMMITTED step directory gets a
``manifest.sha256.json`` sidecar (file -> sha256 over the whole step
dir, written right after the async commit lands — at the next ``save``
or at ``wait``/``close``). ``restore_latest`` verifies the manifest
before restoring: a torn or bit-flipped checkpoint (power loss,
flaky blob store) is skipped with a WARNING **naming the corrupt
file**, and the restore falls back to the newest intact step instead
of failing the run with an opaque orbax error. Checkpoints from
before this PR have no manifest and restore exactly as before.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Any

import orbax.checkpoint as ocp

from tensorflow_examples_tpu.telemetry.registry import default_registry
from tensorflow_examples_tpu.telemetry.spans import span as _trace_span

log = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.sha256.json"


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


class CheckpointManager:
    def __init__(self, workdir: str, *, max_to_keep: int = 3, async_save: bool = True):
        # item_handlers pre-registers the standard handler so a FRESH
        # manager (the resume path) can read item_metadata — without it
        # orbax returns None metadata until the first save, and
        # restore-time structure validation would silently skip.
        self._dir = os.path.abspath(os.path.join(workdir, "checkpoints"))
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
            item_handlers=ocp.StandardCheckpointHandler(),
        )
        # Manifest stamping runs off the training thread (sha256 over a
        # multi-GB step dir would otherwise stall the step loop — the
        # exact blocking cost async_save exists to avoid). The lock
        # serializes stampers; wait()/close() join the in-flight one.
        self._manifest_lock = threading.Lock()
        self._manifest_thread: threading.Thread | None = None

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        # Always wait+close — an async save abandoned on the exception
        # path would otherwise be a torn latest-checkpoint.
        self.close()
        return False

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def save(self, step: int, state: Any) -> None:
        # The span covers the ENQUEUE only under async_save (orbax copies
        # device->host then commits in the background); the commit wait
        # shows up in whichever span wraps wait()/close().
        with _trace_span("checkpoint_save", step=step):
            self._mngr.save(step, args=ocp.args.StandardSave(_as_dict(state)))
        default_registry().counter("checkpoint/saves").inc()
        # Every EARLIER step is committed by now (orbax serializes
        # async saves: a new save waits for the previous commit), so
        # any of them still missing an integrity manifest gets one —
        # hashed on a background thread, never the step loop. The
        # just-enqueued step may still be in flight — it is stamped by
        # a later save, or by wait()/close(). If the previous stamper
        # is still running, skip: stamping is idempotent and the next
        # trigger catches up.
        prev = self._manifest_thread
        if prev is None or not prev.is_alive():
            self._manifest_thread = threading.Thread(
                target=self._write_manifests,
                kwargs={"exclude_step": step},
                name="ckpt-manifest-stamp",
                daemon=True,
            )
            self._manifest_thread.start()

    def restore_latest(
        self, state: Any, *, validate: bool = True
    ) -> tuple[Any, int] | None:
        """Restore into ``state``'s structure/shardings; None if no ckpt.

        Abstract template leaves (``jax.eval_shape`` ShapeDtypeStructs,
        the restore-only consumers' path — sampling/serving CLIs) carry
        no sharding; orbax refuses them for checkpoints that were SAVED
        sharded (docs/sharding.md). Such leaves get a default
        single-device placement here, so any checkpoint — written on
        any mesh — restores through a shardings-free template onto the
        local default device (resharding on restore is the contract).

        Integrity fallback (ISSUE 10): steps whose sha256 manifest does
        not verify — and steps orbax itself fails to deserialize — are
        skipped with a WARNING naming the corrupt file, falling back to
        the newest intact step. Structure/shape drift found by
        ``validate`` still raises (that is a config mistake, not
        corruption — silently restoring an OLDER checkpoint with the
        same wrong config would mask it)."""
        steps = sorted(self._mngr.all_steps(), reverse=True)
        if not steps:
            return None
        target = _with_default_shardings(_as_dict(state))
        corrupt: list[str] = []
        for step in steps:
            problems = self.verify_step_integrity(step)
            if problems:
                shown = "; ".join(problems[:5])
                log.warning(
                    "checkpoint at step %d fails its integrity "
                    "manifest (%s)%s", step, shown,
                    " — falling back to an older checkpoint"
                    if step != steps[-1] else "",
                )
                default_registry().counter(
                    "checkpoint/corrupt_skipped"
                ).inc()
                corrupt.append(f"step {step}: {shown}")
                continue
            with _trace_span("checkpoint_restore", step=step):
                if validate:
                    self._validate_structure(step, target)
                try:
                    restored = self._mngr.restore(
                        step, args=ocp.args.StandardRestore(target)
                    )
                except Exception as e:  # noqa: BLE001 — a torn step
                    # that slipped past the manifest (or predates it)
                    # must not fail the run while an intact older
                    # step exists.
                    default_registry().counter(
                        "checkpoint/corrupt_skipped"
                    ).inc()
                    corrupt.append(
                        f"step {step}: {type(e).__name__}: {e}"
                    )
                    if step == steps[-1]:
                        break
                    log.warning(
                        "restore of step %d failed inside orbax "
                        "(%s: %s) — falling back to an older "
                        "checkpoint", step, type(e).__name__, e,
                    )
                    continue
                merged = _merge_arrays(state, restored)
            default_registry().counter("checkpoint/restores").inc()
            if corrupt:
                log.warning(
                    "restored checkpoint at step %d after skipping %d "
                    "corrupt newer step(s)", step, len(corrupt),
                )
            else:
                log.info("restored checkpoint at step %d", step)
            return merged, step
        raise RuntimeError(
            "every checkpoint in %s is corrupt:\n  %s"
            % (self._dir, "\n  ".join(corrupt))
        )

    # ------------------------------------------------------- integrity

    def _step_dir(self, step: int) -> str:
        return os.path.join(self._dir, str(step))

    def _write_manifests(self, exclude_step: int | None = None) -> None:
        """Stamp a sha256 manifest into every committed step dir that
        lacks one (idempotent; the manifest itself is excluded from its
        own hash set). Written atomically so a crash mid-stamp can
        never leave a torn manifest posing as a verdict. A step swept
        away by max_to_keep mid-stamp is skipped, not an error."""
        with self._manifest_lock:
            for step in self._mngr.all_steps():
                if step == exclude_step:
                    continue
                step_dir = self._step_dir(step)
                manifest = os.path.join(step_dir, MANIFEST_NAME)
                if not os.path.isdir(step_dir) \
                        or os.path.exists(manifest):
                    continue
                files = {}
                try:
                    for root, _, names in os.walk(step_dir):
                        for name in sorted(names):
                            if name == MANIFEST_NAME:
                                continue
                            full = os.path.join(root, name)
                            files[os.path.relpath(full, step_dir)] = (
                                _sha256_file(full)
                            )
                    tmp = manifest + ".tmp"
                    with open(tmp, "w") as f:
                        json.dump(
                            {"step": step, "files": files}, f, indent=1
                        )
                        f.write("\n")
                    os.replace(tmp, manifest)
                except FileNotFoundError:
                    continue  # rotated out from under us (max_to_keep)
                log.debug(
                    "stamped integrity manifest for step %d (%d files)",
                    step, len(files),
                )

    def verify_step_integrity(self, step: int) -> list[str]:
        """Problems with step's on-disk bytes vs its manifest (empty =
        intact, or the step predates manifests)."""
        step_dir = self._step_dir(step)
        manifest = os.path.join(step_dir, MANIFEST_NAME)
        if not os.path.exists(manifest):
            return []  # pre-ISSUE-10 checkpoint: nothing to verify
        try:
            with open(manifest) as f:
                doc = json.load(f)
            files = doc["files"]
        except (ValueError, KeyError, OSError) as e:
            return [f"unreadable manifest {manifest}: {e}"]
        problems = []
        for rel, digest in sorted(files.items()):
            full = os.path.join(step_dir, rel)
            if not os.path.isfile(full):
                problems.append(f"missing file {rel}")
            elif _sha256_file(full) != digest:
                problems.append(f"sha256 mismatch in {rel}")
        return problems

    def _validate_structure(self, step: int, target: dict) -> None:
        """Compare the saved tree against the live state; raise a clear
        error naming every drifted path (missing / unexpected / shape or
        dtype mismatch) instead of letting orbax fail deep inside its
        restore machinery."""
        import jax.tree_util as jtu

        try:
            meta = self._mngr.item_metadata(step)
        except Exception as e:  # metadata is best-effort across versions
            log.debug("checkpoint metadata unavailable (%s); skipping", e)
            return
        if not isinstance(meta, dict):
            return

        def norm(path) -> str:
            # Saved metadata renders optax NamedTuple nodes as dicts while
            # the live tree flattens them with attribute keys ([0].count
            # vs ['0']['count']); normalize every entry to its bare
            # key/index so the two spellings compare equal.
            parts = []
            for p in path:
                for attr in ("key", "name", "idx"):
                    if hasattr(p, attr):
                        parts.append(str(getattr(p, attr)))
                        break
                else:  # pragma: no cover - unknown key type
                    parts.append(str(p))
            return "/".join(parts)

        def by_path(tree):
            return {
                norm(path): leaf
                for path, leaf in jtu.tree_flatten_with_path(tree)[0]
            }

        saved, live = by_path(meta), by_path(target)
        problems = []
        for path in sorted(set(live) - set(saved)):
            problems.append(f"missing from checkpoint: {path}")
        for path in sorted(set(saved) - set(live)):
            problems.append(f"not in live state: {path}")
        for path in sorted(set(saved) & set(live)):
            m, x = saved[path], live[path]
            m_shape = getattr(m, "shape", None)
            m_dtype = getattr(m, "dtype", None)
            x_shape = tuple(getattr(x, "shape", ()))
            if m_shape is not None and tuple(m_shape) != x_shape:
                problems.append(
                    f"shape mismatch at {path}: checkpoint "
                    f"{tuple(m_shape)} vs live {x_shape}"
                )
            elif m_dtype is not None and str(m_dtype) != str(
                getattr(x, "dtype", m_dtype)
            ):
                problems.append(
                    f"dtype mismatch at {path}: checkpoint {m_dtype} vs "
                    f"live {x.dtype}"
                )
        if problems:
            shown = "\n  ".join(problems[:20])
            more = (
                f"\n  ... and {len(problems) - 20} more"
                if len(problems) > 20
                else ""
            )
            raise ValueError(
                f"checkpoint at step {step} does not match the live train "
                f"state ({len(problems)} path(s) drifted — wrong model "
                "config or optimizer for this workdir?):\n  "
                f"{shown}{more}"
            )

    def _join_manifest_thread(self) -> None:
        t = self._manifest_thread
        if t is not None and t is not threading.current_thread():
            t.join()

    def wait(self) -> None:
        self._mngr.wait_until_finished()
        self._join_manifest_thread()
        self._write_manifests()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._join_manifest_thread()
        self._write_manifests()
        self._mngr.close()


def _with_default_shardings(tree: Any) -> Any:
    """Give sharding-less abstract leaves a concrete single-device
    placement (concrete arrays and sharding-carrying structs pass
    through untouched)."""
    import jax

    default = None

    def one(leaf):
        nonlocal default
        if (
            isinstance(leaf, jax.ShapeDtypeStruct)
            and getattr(leaf, "sharding", None) is None
        ):
            if default is None:
                default = jax.sharding.SingleDeviceSharding(
                    jax.local_devices()[0]
                )
            return jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=default
            )
        return leaf

    return jax.tree.map(one, tree)


def _as_dict(state: Any) -> dict:
    """Array-only view of TrainState (fns/optimizer objects are rebuilt by
    the caller, orbax stores just the arrays)."""
    return {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
        "model_state": state.model_state,
    }


def _merge_arrays(state: Any, restored: dict) -> Any:
    return state.replace(
        step=restored["step"],
        params=restored["params"],
        opt_state=restored["opt_state"],
        model_state=restored.get("model_state", state.model_state),
    )
