"""The shared training loop.

TPU-native re-design of the reference's per-example loop (SURVEY.md §3(1)):

  reference                          | here
  -----------------------------------+----------------------------------
  strategy.scope() model build       | params init jitted with
                                     |   out_shardings from the rules
  strategy.experimental_distribute_  | host batch → jax.device_put with
    dataset + per-replica feeding    |   batch sharding on the mesh
  strategy.run(train_step) + NCCL    | ONE jax.jit program: fwd + bwd +
    all-reduce + optimizer.apply     |   XLA collectives + update, with
                                     |   donated state (no HBM copies)
  tf.summary / CheckpointManager     | Telemetry sinks (JSONL + clu/
                                     |   TensorBoard + console) / orbax

The whole step — including the gradient all-reduce and optimizer — is a
single XLA executable, so there is no per-op dispatch overhead and XLA
overlaps the collectives with backward compute.

Telemetry (ISSUE 2, docs/observability.md): each ``fit`` owns a
``Telemetry`` object — span-traced loop phases (data_fetch /
device_step / metric_flush / eval + checkpoint save/restore from the
manager), a per-window schema-versioned JSONL line carrying the metrics
registry's counters (resilience events, IO retries, batch skips) and
derived accounting (examples/sec, step-time percentiles, 6ND MFU,
goodput), flushed on EVERY exit path including preemption, bad-step
abort, and the watchdog's fatal exit.

Device-side observability (ISSUE 3): the jitted step fns run under a
recompilation sentinel (post-warmup aval changes warn, naming the
changed axis, and land as ``compile_warning`` JSONL lines); a fit-start
memory snapshot attributes live bytes to params/optimizer/other and a
peak watermark rides every window line; ``profile_start_step`` /
``profile_num_steps`` / ``profile_dir`` capture a programmable one-shot
``jax.profiler`` window cross-linked from the final line; and an OOM
dumps allocation forensics before re-raising.

Fleet observability (ISSUE 4): every cadenced window the hub allgathers
a per-host health vector and emits a ``kind="fleet"`` line with
slowest-host/skew attribution (``straggler_skew_factor``); with
``metrics_port`` set, each process serves live /metrics (Prometheus),
/health, and /window endpoints, shut down on every exit path.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import sys
import time
from typing import Any, Callable, Iterable, Iterator, Mapping

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from tensorflow_examples_tpu.core.precision import PrecisionPolicy
from tensorflow_examples_tpu.core.rng import step_rng
from tensorflow_examples_tpu.sharding import (
    ShardingConfig,
    ShardingMismatchError,
    resolve_params,
    state_shardings,
    verify_digest_agreement,
)
from tensorflow_examples_tpu.data.prefetch import (
    bundle_batches,
    device_prefetch,
    put_batch,
)
from tensorflow_examples_tpu.telemetry import Telemetry
from tensorflow_examples_tpu.telemetry import compilation as compilation_mod
from tensorflow_examples_tpu.telemetry import memory as memory_mod
from tensorflow_examples_tpu.telemetry import profiling as profiling_mod
from tensorflow_examples_tpu.train import resilience
from tensorflow_examples_tpu.train.checkpoint import CheckpointManager
from tensorflow_examples_tpu.train.config import TrainConfig
from tensorflow_examples_tpu.train.state import TrainState
from tensorflow_examples_tpu.train.task import Task
from tensorflow_examples_tpu.utils import faults as fault_inject

log = logging.getLogger(__name__)


def state_factory(task: Task, config: TrainConfig):
    """(make_state(rng) -> TrainState, tx). Shared by Trainer init and by
    restore-only consumers (e.g. sampling CLIs), which ``jax.eval_shape``
    the factory to get a checkpoint template without materializing params
    or optimizer state."""
    tx = task.make_optimizer(config)

    def make_state(rng):
        variables = dict(task.init_fn(rng))
        params = variables.pop("params")
        return TrainState.create(
            apply_fn=None, params=params, tx=tx, model_state=variables
        )

    return make_state, tx


class Trainer:
    """Runs a Task under a TrainConfig on a device mesh.

    Placement (ISSUE 7): one :class:`ShardingConfig` is the source of
    truth — pass one explicitly, point ``cfg.sharding_config`` at a
    JSON file, or let the trainer derive it from the legacy
    ``mesh_*``/``zero1`` knobs + the task's rules table. The mesh, the
    param/optimizer/batch shardings, and ZeRO-1 all resolve from it;
    ``fit`` persists it to ``workdir/sharding.json`` (so serving and a
    resumed run consume the SAME spec) and refuses a resume whose rules
    digest drifted (:class:`sharding.ShardingMismatchError`). Mesh
    SHAPE may differ on resume — checkpoints reshard bitwise.
    """

    def __init__(
        self,
        task: Task,
        config: TrainConfig,
        *,
        mesh=None,
        sharding: ShardingConfig | None = None,
    ):
        self.task = task
        self.config = config
        if sharding is None:
            path = getattr(config, "sharding_config", "")
            sharding = (
                ShardingConfig.load(path)
                if path
                else ShardingConfig.from_train_config(
                    config, rules=task.sharding_rules
                )
            )
        self.sharding = sharding
        self.mesh = mesh if mesh is not None else sharding.build_mesh()
        if mesh is not None:
            # Snapshot the explicit mesh's shape back into the config so
            # sharding.json / telemetry report what actually ran.
            self.sharding = dataclasses.replace(
                self.sharding,
                mesh={a: int(mesh.shape[a]) for a in mesh.axis_names},
            )
        # Rules resolve through the config (empty config rules inherit
        # the task's live table — the from_train_config path embeds it).
        self._rules = self.sharding.sharding_rules(
            default=task.sharding_rules
        )
        self.policy = PrecisionPolicy.create(config.precision)
        self._batch_sharding = self.sharding.batch_sharding(self.mesh)
        self._ckpt: CheckpointManager | None = None
        self._telemetry: Telemetry | None = None  # built per fit()
        self._guard: resilience.BadStepGuard | None = None
        # Recompilation sentinel (telemetry/compilation.py): every
        # jitted step fn this trainer builds is wrapped, so a post-
        # warmup aval change surfaces as a named warning instead of a
        # silent step-time cliff. Transparent to AOT consumers
        # (``trainer._train_step.lower(...)`` still works).
        self.sentinel = compilation_mod.CompilationSentinel.from_config(
            config
        )
        self.state = self._init_state()
        # The resolved param placement (sharding/resolve.py): drives the
        # sharding.json persisted next to checkpoints, the restore-time
        # rules-digest check, and the telemetry final-line digest.
        self._resolution = resolve_params(
            jax.eval_shape(lambda s: s, self.state).params,
            self.mesh,
            self._rules,
        )
        self._train_step = self.sentinel.wrap(
            self._build_train_step(), "train_step"
        )
        self._bundled_steps: dict[int, object] = {}
        self._eval_step = self.sentinel.wrap(
            self._build_eval_step(), "eval_step"
        )

    def sharding_digest(self) -> str:
        """Stable hash of the param → PartitionSpec table (mesh-shape
        independent: reshardable layouts compare equal, rule drift
        doesn't). Published on the final telemetry line and persisted
        in ``workdir/sharding.json``."""
        return self._resolution.digest()

    def _sync_sharding_json(self, workdir: str) -> None:
        """Validate against (then refresh) ``workdir/sharding.json``.

        A pre-existing file whose param digest differs from the live
        resolution means the rules table drifted since the checkpoints
        were written — restoring under different placement rules is a
        config error, named per-path, NOT a reshard (mesh-shape changes
        hash identically and restore fine)."""
        path = os.path.join(workdir, "sharding.json")
        if os.path.exists(path):
            try:
                saved_cfg, extra = ShardingConfig.load_with_extra(path)
            except (ValueError, OSError) as e:
                raise ShardingMismatchError(
                    f"unreadable sharding config at {path}: {e} — move it "
                    "aside if the workdir is being repurposed"
                ) from e
            saved_digest = extra.get("param_sharding_digest")
            live = self._resolution
            if saved_digest and saved_digest != live.digest():
                from tensorflow_examples_tpu.core.sharding import (
                    ShardingRules,
                )

                theirs = resolve_params(
                    jax.eval_shape(lambda s: s, self.state).params,
                    self.mesh,
                    saved_cfg.sharding_rules(default=ShardingRules()),
                ).spec_by_path()
                mine = live.spec_by_path()
                drifted = [
                    p
                    for p in sorted(set(mine) | set(theirs))
                    if mine.get(p) != theirs.get(p)
                ]
                shown = "\n  ".join(
                    f"{p}: saved {theirs.get(p)} vs live {mine.get(p)}"
                    for p in drifted[:10]
                ) or "(digest drift outside the resolvable param table)"
                more = (
                    f"\n  ... and {len(drifted) - 10} more"
                    if len(drifted) > 10
                    else ""
                )
                raise ShardingMismatchError(
                    f"sharding rules drifted vs {path} (saved digest "
                    f"{saved_digest}, live {live.digest()}): checkpoints "
                    "in this workdir were written under different "
                    "placement rules. Mesh-shape changes reshard fine; "
                    "rule changes need a fresh workdir (or delete "
                    f"sharding.json deliberately).\n  {shown}{more}"
                )
        if jax.process_index() == 0:
            try:
                from tensorflow_examples_tpu.sharding.config import (
                    rules_to_json,
                )

                # Persist the RESOLVED rules: a config that inherited
                # the task's live table writes it out, so the file is
                # self-contained for serving and for restore diffs.
                to_save = (
                    self.sharding
                    if self.sharding.rules
                    else dataclasses.replace(
                        self.sharding, rules=rules_to_json(self._rules)
                    )
                )
                to_save.save(
                    path,
                    extra={
                        "param_sharding_digest": self._resolution.digest(),
                        "mesh_shape": self.sharding.mesh_shape_dict(
                            self.mesh
                        ),
                    },
                )
            except OSError:
                # Metadata write — never kill a training job over it.
                log.warning(
                    "could not persist %s (continuing)", path, exc_info=True
                )

    # ------------------------------------------------------------- init

    def _init_state(self) -> TrainState:
        cfg = self.config
        rng = jax.random.PRNGKey(cfg.seed)
        make_state, tx = state_factory(self.task, cfg)

        # Evaluate shapes → shardings from the rules → jit-init directly
        # into the sharded layout (params never materialize unsharded).
        abstract = jax.eval_shape(make_state, rng)
        shardings = self._state_shardings(abstract)
        with self.mesh:
            state = jax.jit(make_state, out_shardings=shardings)(rng)
        state = state.replace(apply_fn=None, tx=tx)
        n_params = sum(x.size for x in jax.tree.leaves(state.params))
        self._n_params = n_params  # telemetry's 6ND MFU numerator
        log.info(
            "initialized %s: %.2fM params on mesh %s",
            self.task.name,
            n_params / 1e6,
            dict(self.mesh.shape),
        )
        return state

    def _state_shardings(self, abstract_state) -> Any:
        # Resolution lives in sharding/resolve.py (ISSUE 7): params by
        # the config's rules, optimizer moments inheriting their param's
        # sharding, ZeRO-1 escalation for replicated params' moments.
        return state_shardings(
            abstract_state,
            self.mesh,
            self._rules,
            zero1=self.sharding.zero1,
            batch_axes=self.sharding.batch_axes,
        )

    # ------------------------------------------------------------- steps

    def _make_train_step_fn(self):
        task, policy = self.task, self.policy
        seed_key = jax.random.PRNGKey(self.config.seed + 1)
        # Bad-step guard compiled INTO the step (train/resilience.py): a
        # non-finite loss or grad norm skips the update via jnp.where —
        # params/opt_state/model_state keep their old values while `step`
        # still advances (rng stream and data order move on) — and a 0/1
        # `bad_step` metric is emitted for the host guard to poll. No
        # host sync anywhere on the happy path.
        guard_on = (
            getattr(self.config, "bad_step_policy", "off") not in ("off", "")
        )

        def train_step(state: TrainState, batch):
            rng = step_rng(seed_key, state.step)

            def loss_fn(params):
                # Cast params AND batch: flax's dtype promotion computes in
                # result_type(input, kernel), so a f32 batch would silently
                # promote every matmul back to f32.
                compute_params = policy.cast_compute(params)
                compute_batch = policy.cast_compute(batch)
                loss, metrics, new_model_state = task.loss_fn(
                    compute_params,
                    state.model_state,
                    compute_batch,
                    rng=rng,
                    train=True,
                )
                return loss, (metrics, new_model_state)

            (loss, (metrics, new_model_state)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            new_state = state.apply_gradients(grads).replace(
                model_state=new_model_state
            )
            metrics = dict(metrics)
            metrics["loss"] = loss
            metrics["grad_norm"] = optax.global_norm(
                jax.tree.map(lambda x: x.astype(jnp.float32), grads)
            )
            if guard_on:
                bad = jnp.logical_not(
                    jnp.isfinite(loss) & jnp.isfinite(metrics["grad_norm"])
                )

                def keep_old(new, old):
                    return jnp.where(bad, old, new)

                new_state = new_state.replace(
                    params=jax.tree.map(keep_old, new_state.params, state.params),
                    opt_state=jax.tree.map(
                        keep_old, new_state.opt_state, state.opt_state
                    ),
                    model_state=jax.tree.map(
                        keep_old, new_state.model_state, state.model_state
                    ),
                )
                metrics["bad_step"] = bad.astype(jnp.float32)
            return new_state, metrics

        return train_step

    def _build_train_step(self):
        state_sh = self._state_shardings(jax.eval_shape(lambda s: s, self.state))
        return jax.jit(
            self._make_train_step_fn(),
            in_shardings=(state_sh, self._batch_sharding),
            out_shardings=(state_sh, NamedSharding(self.mesh, P())),
            donate_argnums=(0,),
        )

    def _build_bundled_step(self, k: int):
        """K train steps in ONE device launch: ``lax.scan`` over a
        ``[k, batch, ...]`` bundle (the TPU-native equivalent of Keras's
        ``steps_per_execution``). The per-step program is the same
        ``train_step`` the unbundled path jits — same RNG stream (keyed
        off ``state.step``, which the scan carry advances), same
        optimizer (``optax.MultiSteps`` grad accumulation ticks per scan
        iteration) — so K scanned steps match K separate launches; only
        the host dispatch cost is amortized K-fold. Metrics come back
        stacked ``[k]`` per key.

        Cached per ``k`` (like ``self._train_step``) so repeated
        ``fit()`` calls on one Trainer don't pay a fresh trace+compile
        each time."""
        cached = self._bundled_steps.get(k)
        if cached is not None:
            return cached
        train_step = self._make_train_step_fn()

        def bundled(state: TrainState, batches):
            return jax.lax.scan(train_step, state, batches)

        state_sh = self._state_shardings(jax.eval_shape(lambda s: s, self.state))
        step = self.sentinel.wrap(
            jax.jit(
                bundled,
                in_shardings=(state_sh, self.sharding.bundle_sharding(self.mesh)),
                out_shardings=(state_sh, NamedSharding(self.mesh, P())),
                donate_argnums=(0,),
            ),
            f"train_step[k={k}]",
        )
        self._bundled_steps[k] = step
        return step

    def _build_eval_step(self):
        if self.task.eval_fn is None:
            return None
        task, policy = self.task, self.policy

        def eval_step(params, model_state, batch):
            return task.eval_fn(
                policy.cast_compute(params), model_state, policy.cast_compute(batch)
            )

        return jax.jit(
            eval_step,
            in_shardings=(None, None, self._batch_sharding),
            out_shardings=NamedSharding(self.mesh, P()),
        )

    # ------------------------------------------------------------- loop

    def _put_batch(self, batch):
        return put_batch(batch, self._batch_sharding)

    def fit(
        self,
        train_data: Iterator[Mapping[str, np.ndarray]]
        | Callable[[int], Iterator[Mapping[str, np.ndarray]]],
        *,
        eval_iter_fn: Callable[[], Iterable] | None = None,
        num_steps: int | None = None,
        local_batches: bool = False,
        eval_per_host: bool | None = None,
    ) -> dict[str, float]:
        """Run the training loop; returns final logged metrics.

        ``train_data`` may be an iterator, or — for exact resume — a
        callable ``(start_step) -> iterator`` invoked after checkpoint
        restore, so a resumed run consumes exactly the batches the
        uninterrupted run would have.

        ``local_batches``: the iterator yields THIS process's
        ``global_batch / process_count`` rows (per-host data sources
        like TFRecord shards) assembled via ``put_local_batch``; False
        (default) = global-view batches identical on every process.

        ``eval_per_host``: semantics of ``eval_iter_fn``'s batches,
        passed through to :meth:`evaluate`. ``None`` (default) keeps
        evaluate's own default — per-host whenever process_count > 1,
        which matches every in-repo pairing (the CLI's in-memory path
        feeds a GLOBAL-view train iterator but a PER-HOST eval slice,
        ``train/cli.py:_host_eval_batches``, so eval semantics are a
        property of the eval iterator, NOT of ``local_batches``). Pass
        False explicitly for a genuinely global-view eval iterator in a
        multi-process run.

        Resilience (docs/resilience.md): SIGTERM/SIGINT checkpoint at
        the next step boundary and raise :class:`resilience.Preempted`
        (exit code 0); bad steps are skipped/rolled back/aborted per
        ``cfg.bad_step_policy``; a stalled step or input fetch trips the
        watchdog (``cfg.watchdog_secs`` dump, ``cfg.watchdog_fatal_secs``
        fail-fast). The checkpoint manager is closed — waiting out any
        in-flight async save — on ALL exit paths, including exceptions.
        """
        cfg = self.config
        num_steps = num_steps or cfg.train_steps
        start_step = int(self.state.step)

        # Config validation (bad_step_policy) happens BEFORE any thread or
        # handler is created, so a bad config can't leak a watchdog.
        faults_engine = fault_inject.active()
        guard = resilience.BadStepGuard.from_config(cfg)
        self._guard = guard  # introspectable by tests/tools

        # Telemetry next (an unknown sink name must also fail before any
        # thread/handler exists); one object per fit — sinks may be
        # workdir-backed and multiple fits on one Trainer are legal.
        telemetry = Telemetry.from_config(cfg, n_params=self._n_params)
        # Placement provenance on the kind="final" line (ISSUE 7
        # satellite, schema v5): which mesh this run actually used and
        # the param-sharding digest a reader can diff across runs.
        telemetry.sharding_info = {
            "mesh_shape": self.sharding.mesh_shape_dict(self.mesh),
            "param_sharding_digest": self._resolution.digest(),
            "zero1": bool(self.sharding.zero1),
        }
        self._telemetry = telemetry
        # Post-warmup recompiles now land as JSONL warning lines.
        self.sentinel.bind(telemetry)
        emit_final: Callable[..., None] | None = None  # bound in the try
        prof: profiling_mod.ProfilerWindow | None = None

        watchdog = None
        if cfg.watchdog_secs > 0 or cfg.watchdog_fatal_secs > 0:
            from tensorflow_examples_tpu.utils.diagnostics import Watchdog

            # Start paused: restore + first-step compile are legitimately
            # slow. Detection arms at the first completed step's ping.
            # flush_fn: the fatal exit-87 path pushes sinks + trace to
            # disk from the watchdog thread before os._exit.
            watchdog = Watchdog(
                cfg.watchdog_secs or cfg.watchdog_fatal_secs,
                fatal_timeout_s=cfg.watchdog_fatal_secs,
                flush_fn=telemetry.emergency_flush,
            ).start()
            watchdog.pause()

        preempt = (
            resilience.PreemptionGuard().install()
            if cfg.preempt_checkpoint
            else None
        )

        try:
            # Live observability endpoints (ISSUE 4): opt-in per-process
            # /metrics + /health + /window server. Attached to the hub
            # so BOTH teardown paths reach it: telemetry.close() in the
            # finally below (complete/preempt/error) and the watchdog-
            # fatal emergency flush (exit 87). Inside the try so a bind
            # failure (port in use) still unwinds the watchdog/handlers.
            if getattr(cfg, "metrics_port", 0):
                from tensorflow_examples_tpu.telemetry import (
                    serve as serve_mod,
                )

                server = serve_mod.MetricsServer.from_config(
                    cfg, telemetry=telemetry, watchdog=watchdog
                )
                if server is not None:
                    try:
                        telemetry.server = server.start()
                    except OSError as e:
                        # A taken port (stale process, two runs on one
                        # box) must not kill the training job over a
                        # read-only diagnostics endpoint.
                        log.warning(
                            "metrics server failed to bind port %d (%s) "
                            "— continuing without live endpoints",
                            server.requested_port,
                            e,
                        )

            # Cross-host digest agreement BEFORE anything else touches
            # state (ISSUE 8 satellite, ROADMAP 1d): sharding.json is
            # written by process 0 only and _sync_sharding_json
            # validates per-process — a host running drifted rules
            # would pass its own check and diverge at the first
            # collective. The allgather fails fast NAMING the host.
            verify_digest_agreement(self.sharding_digest())

            if cfg.workdir:
                self._ckpt = CheckpointManager(cfg.workdir)
                # Rules-digest check BEFORE any restore (a checkpoint
                # must never load under drifted placement rules), then
                # persist the live config for serving/resume consumers.
                self._sync_sharding_json(cfg.workdir)
                if cfg.resume:
                    restored = self._ckpt.restore_latest(self.state)
                    if restored is not None:
                        self.state, start_step = restored[0], int(restored[1])

            # Fit-start memory snapshot (post-restore: the restored
            # state is what actually occupies the device): params vs.
            # optimizer vs. other breakdown as a kind="memory" line,
            # and the watermark gauge starts ticking.
            telemetry.note_memory_init(self.state, step=start_step)

            k = max(int(getattr(cfg, "steps_per_launch", 1) or 1), 1)
            if k > 1:
                cadences = {
                    # Cadences fire on (step+1) % cadence == 0 and step+1
                    # only takes values start_step + i*k, so BOTH the
                    # phase (start_step) and each period must divide by k
                    # or periodic events silently never fire.
                    "start step (resume phase)": start_step,
                    "train step span": num_steps - start_step,
                    "log_every": cfg.log_every,
                    "eval_every": cfg.eval_every if eval_iter_fn else 0,
                    "checkpoint_every": cfg.checkpoint_every
                    if self._ckpt
                    else 0,
                }
                bad = {n: v for n, v in cadences.items() if v and v % k}
                if bad:
                    raise ValueError(
                        f"steps_per_launch={k} requires every active loop "
                        f"cadence to be a multiple of it; offending: {bad} "
                        "(a resumed checkpoint from an unbundled run may "
                        "leave the step span unaligned)"
                    )
            step_fn = self._train_step if k == 1 else self._build_bundled_step(k)

            # Async look-ahead transfer: batch N+1 streams into HBM while
            # step N runs (the reference's prefetch-to-device equivalent).
            # For bundles, K host batches stack before the (single) put.
            # Rebuilt from a new start step on bad-step rollback — exact
            # batch replay needs the callable form of ``train_data``.
            resumable = callable(train_data) and not hasattr(
                train_data, "__next__"
            )

            def build_iter(start: int):
                src = train_data(start) if resumable else train_data
                return device_prefetch(
                    src if k == 1 else bundle_batches(src, k),
                    self._batch_sharding
                    if k == 1
                    else self.sharding.bundle_sharding(self.mesh),
                    local_batches=local_batches and jax.process_count() > 1,
                    max_skips=cfg.max_skipped_batches,
                    depth=max(
                        int(getattr(cfg, "prefetch_depth", 2) or 2), 1
                    ),
                    depth_max=int(
                        getattr(cfg, "prefetch_depth_max", 0) or 0
                    ),
                )

            train_iter = build_iter(start_step)

            # Programmable one-shot device-trace window (ISSUE 3):
            # cfg.profile_start_step/num_steps/dir, with the legacy
            # --profile flag mapping to the historical steps-10..20.
            prof = profiling_mod.ProfilerWindow.from_config(cfg, telemetry)
            evaluated_now = False
            stepped_once = False  # first step_fn call pays jit compile
            window: list[Mapping[str, jax.Array]] = []
            last: dict[str, float] = {}
            t_window = time.perf_counter()
            t_iter = t_window  # per-chunk wall clock -> step_time hist
            chunk = start_step

            def window_means() -> dict[str, float]:
                """Window-mean each metric. Bundled metrics are
                [k]-vectors per key; scalars and vectors average
                identically through ravel+concat. With the guard active,
                means are over FINITE values only (a skipped bad step's
                NaN loss must not poison the window); with the guard
                OFF, a NaN window mean is the divergence signal — don't
                mask it."""
                if not window:
                    return {}
                mean_fn = (
                    _finite_mean
                    if guard is not None
                    else lambda v: float(np.mean(v))
                )
                return {
                    key: mean_fn(
                        np.concatenate(
                            [
                                np.ravel(np.asarray(m[key], np.float32))
                                for m in window
                            ]
                        )
                    )
                    for key in window[0]
                }

            def emit_final(reason: str, done_step: int | None = None) -> None:
                """Exit marker + the partial in-flight window: every exit
                path (normal, preempt, abort) lands a ``kind="final"``
                JSONL line so the run's tail is never silently lost."""
                if window:
                    telemetry.note_steps(len(window) * k)
                means = window_means()
                window.clear()
                telemetry.final_window(
                    chunk if done_step is None else done_step,
                    means,
                    exit_reason=reason,
                )
                telemetry.flush()
            while True:
                if guard is not None:
                    # Non-blocking: consumes only already-finished step
                    # metrics (drained once the loop is done). Raises
                    # BadStepError for the abort outcomes.
                    if guard.poll(drain=chunk >= num_steps) == "rollback":
                        if watchdog is not None:
                            watchdog.pause()
                        chunk, train_iter = self._rollback_to_checkpoint(
                            guard, build_iter if resumable else None, train_iter
                        )
                        # The discarded window's executions were real
                        # work: they belong in goodput's denominator
                        # (steps_lost carries the replay cost).
                        telemetry.note_steps(len(window) * k)
                        window.clear()
                        t_window = time.perf_counter()
                        t_iter = t_window
                        continue
                if chunk >= num_steps:
                    break
                # step = index of the chunk's LAST train step; with k == 1
                # this loop is exactly the historical per-step loop.
                step = chunk + k - 1
                self.sentinel.step = step  # labels recompile warnings
                if faults_engine is not None:
                    faults_engine.step_hook(chunk, k)
                if prof is not None:
                    prof.maybe_start(chunk - start_step)
                # StepTraceAnnotation marks step boundaries in the
                # profiler timeline (SURVEY §5a); next() sits INSIDE it
                # so host input-wait shows up in the per-step
                # input/compute breakdown. A no-op when no trace is
                # active. NB with steps_per_launch=k>1 one annotation
                # spans the whole k-step bundle (step_num advances by
                # k): divide trace step times by k when comparing
                # against unbundled runs.
                with jax.profiler.StepTraceAnnotation(
                    "train", step_num=step
                ):
                    if watchdog is not None:
                        # Arm for the fetch even before the first step:
                        # a wedged input pipeline at job start must trip
                        # the watchdog too, and a host fetch is never
                        # legitimately compile-slow.
                        watchdog.enter("input_fetch")
                        watchdog.resume()
                    with telemetry.span("data_fetch"):
                        batch = next(train_iter)
                    if faults_engine is not None:
                        batch = faults_engine.nan_hook(chunk, k, batch)
                    if watchdog is not None:
                        watchdog.enter("device_step")
                        if not stepped_once:
                            watchdog.pause()  # first step pays jit compile
                    with telemetry.span("device_step"):
                        self.state, metrics = step_fn(self.state, batch)
                # Host-observed chunk time into the step_time histogram
                # (p50/p95 in every window). Steady state is accurate —
                # the prefetch queue back-pressures the host to device
                # speed; the first chunk (jit compile) is excluded.
                now = time.perf_counter()
                if stepped_once:
                    telemetry.record_step_time(now - t_iter, k)
                stepped_once = True
                if watchdog is not None:
                    # Dispatch is async; sync points (log flushes) bound
                    # how stale this is — good enough for hang detection.
                    watchdog.resume()
                    watchdog.ping(step)
                window.append(metrics)
                if guard is not None:
                    guard.observe(step, metrics)
                if prof is not None:
                    prof.maybe_stop(
                        chunk + k - start_step, block_on=self.state.params
                    )

                if (cfg.log_every and (step + 1) % cfg.log_every == 0) or (
                    step + 1 == num_steps
                ):
                    if watchdog is not None:
                        # Fresh heartbeat + named phase: this wait is up
                        # to a full log window of queued device work, so
                        # it gets its own full timeout budget — but stays
                        # ARMED, because a device hang surfaces exactly
                        # here. Size watchdog(_fatal)_secs above the
                        # worst-case log window.
                        watchdog.enter("log_flush")
                    # The span covers the device-work wait AND the sink
                    # writes: both are "time not spent stepping".
                    with telemetry.span("metric_flush"):
                        jax.block_until_ready(metrics)
                        dt = time.perf_counter() - t_window
                        last = window_means()
                        steps_done = len(window) * k
                        last["steps_per_sec"] = steps_done / dt
                        last["examples_per_sec"] = (
                            steps_done * cfg.global_batch_size / dt
                        )
                        window.clear()
                        t_window = time.perf_counter()
                        telemetry.note_steps(steps_done)
                        telemetry.log_window(step + 1, last, prefix="train")

                if preempt is not None and preempt.requested:
                    # Checked BEFORE the periodic eval: a pending SIGTERM
                    # must not burn the scheduler's kill grace window on
                    # a full evaluation before the checkpoint lands.
                    if prof is not None:
                        prof.finish()
                    self._preempt_exit(step + 1, preempt, watchdog, emit_final)

                evaluated_now = False
                if (
                    cfg.eval_every
                    and (step + 1) % cfg.eval_every == 0
                    and eval_iter_fn
                ):
                    if watchdog is not None:
                        watchdog.pause()  # eval length ≠ step cadence
                    with telemetry.span("eval"):
                        eval_metrics = self.evaluate(
                            eval_iter_fn(), per_host=eval_per_host
                        )
                    if watchdog is not None:
                        watchdog.resume()
                    telemetry.log_window(
                        step + 1, eval_metrics, prefix="eval", kind="eval"
                    )
                    evaluated_now = step + 1 == num_steps
                    if evaluated_now:
                        last.update(
                            {f"eval_{k}": v for k, v in eval_metrics.items()}
                        )

                if (
                    self._ckpt
                    and cfg.checkpoint_every
                    and (step + 1) % cfg.checkpoint_every == 0
                ):
                    if watchdog is not None:
                        # Save time (device->host copy + waiting out the
                        # previous async commit) is storage-bound, not a
                        # hang — don't let the fatal watchdog kill it.
                        watchdog.pause()
                    self._ckpt.save(step + 1, self.state)
                    if watchdog is not None:
                        watchdog.resume()

                if preempt is not None and preempt.requested:
                    if prof is not None:
                        prof.finish()
                    self._preempt_exit(step + 1, preempt, watchdog, emit_final)
                chunk += k
                # Step-time clock excludes this chunk's cadence work
                # (flush/eval/checkpoint have their own spans).
                t_iter = time.perf_counter()

            if prof is not None:
                prof.finish(block_on=self.state.params)
            if watchdog is not None:
                watchdog.pause()  # final eval + checkpoint close
            if preempt is not None and preempt.requested:
                # Signal arrived between the last chunk's check and here:
                # skip the final eval (the scheduler's grace window is
                # ticking), checkpoint, and exit cleanly.
                self._preempt_exit(num_steps, preempt, watchdog, emit_final)
            if eval_iter_fn is not None and not evaluated_now:
                with telemetry.span("eval"):
                    final_eval = self.evaluate(
                        eval_iter_fn(), per_host=eval_per_host
                    )
                last.update({f"eval_{k}": v for k, v in final_eval.items()})
            if self._ckpt and self._ckpt.latest_step() != num_steps:
                self._ckpt.save(num_steps, self.state)
            # Normal-completion exit marker: the JSONL tail says the run
            # ENDED (vs. died between windows) and carries final counters.
            emit_final("complete", num_steps)
            return last
        finally:
            # Crash-safe teardown (ISSUE 1 satellite): the checkpoint
            # manager waits out any in-flight async save and closes on
            # EVERY exit path — success, preemption, or exception — so a
            # crash can't abandon a torn latest-checkpoint. The watchdog
            # stops FIRST: on the exception path it may still be armed,
            # and a fatal timeout firing mid-close would kill the very
            # commit the close protects. Signal handlers are restored so
            # fit() doesn't leak process state.
            if watchdog is not None:
                watchdog.stop()
            if preempt is not None:
                preempt.uninstall()
            # Telemetry teardown (ISSUE 2 satellite): an exception that
            # is not the (already-emitted) preemption still lands a
            # final JSONL line — bad-step aborts included — then sinks
            # close and the span timeline is written. ``emit_final`` is
            # None if the failure happened before the loop was set up.
            if prof is not None:
                try:
                    # An exception with an open window must not leave
                    # the process-global profiler armed (the next fit's
                    # start_trace would fail); no-op when already done.
                    prof.finish()
                except Exception:  # pragma: no cover - profiler races
                    log.exception("profiler window teardown failed")
            try:
                exc = sys.exc_info()[1]
                # OOM allocation forensics (ISSUE 3): who held the
                # memory, logged BEFORE the exception re-raises.
                memory_mod.maybe_log_oom_report(exc, telemetry.memory)
                if (
                    exc is not None
                    and not isinstance(exc, resilience.Preempted)
                    and emit_final is not None
                ):
                    emit_final(f"error:{type(exc).__name__}")
            except Exception:  # pragma: no cover - telemetry best effort
                log.exception("final telemetry window failed")
            self.sentinel.unbind()
            telemetry.close()
            if self._ckpt is not None:
                try:
                    self._ckpt.close()
                finally:
                    self._ckpt = None

    def _preempt_exit(
        self, done_step: int, preempt, watchdog, final_emit=None
    ) -> None:
        """Synchronous checkpoint + clean exit at a step boundary.

        The checkpoint lands FIRST (the scheduler's kill grace window is
        ticking and the checkpoint is the thing that must survive), then
        telemetry emits the partial window as a ``kind="final"`` line
        with ``exit_reason="preempt"`` and flushes every sink.
        """
        if watchdog is not None:
            watchdog.pause()
        if self._ckpt is not None:
            # Quiesce any in-flight cadence save first: saving the same
            # step twice (or racing an uncommitted save) is an error.
            self._ckpt.wait()
            if self._ckpt.latest_step() != done_step:
                self._ckpt.save(done_step, self.state)
            self._ckpt.wait()  # the save must be durable BEFORE we exit
            log.warning(
                "preemption: synchronous checkpoint at step %d saved; "
                "exiting cleanly",
                done_step,
            )
        else:
            log.warning(
                "preemption at step %d with no workdir: nothing to "
                "checkpoint; exiting cleanly",
                done_step,
            )
        if self._telemetry is not None:
            # Counted here, NOT in the signal handler (a locked counter
            # inside a handler can deadlock the interrupted main thread).
            self._telemetry.registry.counter("resilience/preemptions").inc()
        if final_emit is not None:
            final_emit("preempt", done_step)
        raise resilience.Preempted(done_step, preempt.signum)

    def _rollback_to_checkpoint(self, guard, build_iter, train_iter):
        """Bad-step rollback: restore the latest checkpoint and replay."""
        if self._ckpt is not None:
            self._ckpt.wait()  # only committed steps are restorable
        if self._ckpt is None or self._ckpt.latest_step() is None:
            raise resilience.BadStepError(
                "bad_step_policy=rollback needs a checkpoint to restore, "
                f"but none exists under workdir={self.config.workdir!r}. "
                f"{guard.status()}"
            )
        restored = self._ckpt.restore_latest(self.state)
        state, step = restored[0], int(restored[1])
        guard.note_rollback(step)  # raises BadStepError on a repeat
        log.warning(
            "bad-step rollback: restored checkpoint at step %d (%s)",
            step,
            guard.status(),
        )
        self.state = state
        if build_iter is not None:
            train_iter = build_iter(step)
        else:
            log.warning(
                "train iterator is not resumable (pass a callable "
                "(start)->iterator for exact replay); continuing on the "
                "live stream after rollback"
            )
        return step, train_iter

    def evaluate(
        self, eval_iter: Iterable, *, per_host: bool | None = None
    ) -> dict[str, float]:
        """Metric-accumulating eval pass (SURVEY.md §3(3)).

        ``per_host``: treat ``eval_iter`` as THIS process's shard of the
        eval set — each batch ``global_batch / process_count`` rows of
        data only this host read (e.g. per-host TFRecord shards). Hosts
        may hold differing numbers of batches: shorter hosts feed
        zero-weight padding until the longest is exhausted, and because
        the jitted eval step reduces its weighted sums over the GLOBAL
        batch, every host returns the identical merged metric — the
        cross-process reduction the reference got from NCCL metric
        all-reduce (SURVEY.md §3(3)). Defaults to True when
        ``jax.process_count() > 1``.
        """
        if self._eval_step is None:
            return {}
        if per_host is None:
            per_host = jax.process_count() > 1
        per_host = per_host and jax.process_count() > 1
        batches = (
            _pad_per_host_batches(iter(eval_iter))
            if per_host
            else iter(eval_iter)
        )
        # Accumulate on device; convert to host floats once at the end so
        # eval steps pipeline instead of syncing per batch.
        totals: dict[str, jax.Array] = {}
        count = None
        for batch in device_prefetch(
            batches,
            self._batch_sharding,
            local_batches=per_host,
            fault_hooks=False,  # slow@N/badbatch@N index TRAIN fetches
        ):
            m = dict(
                self._eval_step(self.state.params, self.state.model_state, batch)
            )
            weight = m.pop("weight", None)
            w = weight if weight is not None else jnp.float32(1.0)
            for k, v in m.items():
                acc = v * w
                totals[k] = totals[k] + acc if k in totals else acc
            count = w if count is None else count + w
        if count is None:
            return {}
        means = {k: float(v) / max(float(count), 1.0) for k, v in totals.items()}
        if self.task.eval_finalize is not None:
            means = dict(self.task.eval_finalize(means))
        return means


def _pad_per_host_batches(it: Iterator) -> Iterator:
    """Equalize per-host eval streams: every host yields batches until
    the longest host's stream is exhausted, padding with zero-weight
    copies — STREAMING, one batch resident at a time (a buffered
    formulation would hold a host's whole decoded eval shard in RAM).

    Per batch, hosts allgather a have-more flag (a scalar host-level
    sync — negligible next to the eval step itself). Each real batch
    gets an explicit per-row ``mask`` (ones if absent) so a padding
    batch — mask of zeros — contributes zero weight to the jitted
    step's global weighted sums. A host with ZERO local batches cannot
    fabricate a padding template, so that condition raises the same
    error on every host at the first flag exchange — a clean collective
    failure instead of peers deadlocking in the next collective.

    FIXED SHAPES REQUIRED: every batch a host yields must share one
    shape (pad ragged finals to the batch size with zero ``mask`` rows,
    as ``data/sources.eval_batches`` does). The padding template is the
    most recent real batch, and ``make_array_from_process_local_data``
    needs shape-identical per-host pieces — a ragged batch on ANY host
    therefore fails ALL hosts: the per-batch flag exchange carries a
    ragged-detected status, so every host raises the same error at the
    same point instead of the peers hanging in the next collective.
    """
    from jax.experimental import multihost_utils

    pad = None
    first = True
    while True:
        batch = next(it, None)
        ragged = (
            batch is not None
            and pad is not None
            and any(
                k in pad and np.shape(v) != pad[k].shape
                for k, v in batch.items()
            )
        )
        # Status collective: 0 = exhausted, 1 = have batch, 2 = ragged.
        flags = multihost_utils.process_allgather(
            np.asarray(0 if batch is None else (2 if ragged else 1))
        )
        if (flags == 2).max():
            raise ValueError(
                "per-host eval batches must share one shape; a host "
                "yielded a differently-shaped batch (pad ragged final "
                f"batches with zero-mask rows); status flags: {flags}"
            )
        if first and flags.min() != flags.max():
            raise ValueError(
                "per-host eval requires at least one local batch on "
                "every host (needed as the zero-weight padding "
                f"template); have-batch flags across hosts: {flags}"
            )
        first = False
        if flags.max() == 0:
            return
        if batch is None:
            yield pad
            continue
        batch = dict(batch)
        if "mask" not in batch:
            rows = len(next(iter(batch.values())))
            batch["mask"] = np.ones(rows, np.float32)
        pad = {k: np.zeros_like(v) for k, v in batch.items()}
        yield batch


def _finite_mean(vals: np.ndarray) -> float:
    """Mean over finite entries (a skipped bad step's NaN loss must not
    poison the whole logging window); NaN only if NOTHING was finite."""
    finite = vals[np.isfinite(vals)]
    return float(np.mean(finite)) if finite.size else float("nan")


