"""TrainState: the complete training-step state as one pytree.

Equivalent of the reference's ``tf.train.Checkpoint(model=…, optimizer=…)``
object graph (SURVEY.md §2b), but as an immutable pytree so the whole state
threads through ``jax.jit`` and shards with ``NamedSharding`` like any
other array tree.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import optax
from flax import struct


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any
    # Non-trainable model collections (BatchNorm running stats, …) — the
    # ``tf.keras`` non-trainable-variables analogue. ``{}`` when stateless.
    model_state: Any
    # Non-pytree leaves:
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    @classmethod
    def create(cls, *, apply_fn, params, tx, model_state=None) -> "TrainState":
        import jax.numpy as jnp

        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            model_state={} if model_state is None else model_state,
            apply_fn=apply_fn,
            tx=tx,
        )

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1, params=new_params, opt_state=new_opt_state
        )

    def byte_breakdown(self, *, per_device: bool = False) -> dict[str, int]:
        """Array bytes per state component — the memory-accounting
        attribution (telemetry/memory.py): params vs. optimizer moments
        vs. non-trainable collections. Works on concrete and abstract
        (eval_shape) trees alike, since both carry size/dtype.

        ``per_device=True`` counts one device's share of each sharded
        leaf instead of global bytes — the unit ZeRO-1's optimizer
        memory claim is measured in (docs/sharding.md)."""
        from tensorflow_examples_tpu.telemetry.memory import tree_bytes

        return {
            "params": tree_bytes(self.params, per_device=per_device),
            "opt_state": tree_bytes(self.opt_state, per_device=per_device),
            "model_state": tree_bytes(
                self.model_state, per_device=per_device
            ),
        }
