"""Shared CLI runner for example entrypoints.

Each ``examples/<name>/train.py`` in the reference was a full copy-pasted
script; here it is a thin shim over this module, preserving the CLI
contract ``python <example>/train.py --device=tpu --flag=...``
(BASELINE.json:north_star) while the actual loop lives in the framework.

A workload module plugs in via a small protocol:
  - ``make_task(cfg) -> Task``          (required)
  - ``datasets(cfg) -> (train, eval)``  (required; InMemoryDataset pair or
                                         iterator factories)
  - ``eval_dataset(cfg) -> eval``       (optional; lets eval.py skip
                                         loading the train split)
  - ``train_augment(cfg) -> fn | None`` (optional)
  - ``make_train_iter(cfg, start) / make_eval_iter(cfg)`` (optional full
     override for streaming pipelines like ImageNet)
"""

from __future__ import annotations

from absl import app, logging

from tensorflow_examples_tpu.core import distributed
from tensorflow_examples_tpu.core.mesh import create_mesh
from tensorflow_examples_tpu.data.memory import eval_batches, train_iterator
from tensorflow_examples_tpu.train.checkpoint import CheckpointManager
from tensorflow_examples_tpu.train.config import (
    apply_device_flag,
    config_from_flags,
    define_flags_from_config,
)
from tensorflow_examples_tpu.train.loop import Trainer


def _setup(workload, default_cfg):
    logging.set_verbosity(logging.INFO)
    cfg = config_from_flags(default_cfg)
    apply_device_flag(cfg.device, debug_nans=cfg.debug_nans)
    from tensorflow_examples_tpu.utils.diagnostics import install_crash_handlers
    from tensorflow_examples_tpu.utils.faults import configure_io_retry

    install_crash_handlers(cfg.workdir)
    # Flaky-input-store policy for every file reader (data/sources.py).
    configure_io_retry(cfg.io_retries, cfg.io_backoff_secs)
    distributed.initialize()
    return cfg


def _build_trainer(workload, cfg):
    """Create the mesh once and hand it to both the task and the Trainer
    (models that pin activation shardings or run shard_map'd attention
    need the concrete mesh at trace time). With ``--sharding_config``
    the mesh comes from the config file (docs/sharding.md) — the one
    spec that also drives serving — and the Trainer inherits its rules
    and ZeRO-1 policy too."""
    sharding = None
    if getattr(cfg, "sharding_config", ""):
        from tensorflow_examples_tpu.sharding import ShardingConfig

        sharding = ShardingConfig.load(cfg.sharding_config)
        mesh = sharding.build_mesh()
    else:
        mesh = create_mesh(cfg.mesh_config())
    return Trainer(
        workload.make_task(cfg, mesh=mesh), cfg, mesh=mesh,
        sharding=sharding,
    )


def _host_eval_batches(test_ds, eval_bs):
    """Per-host eval slice: host h evaluates rows h::P at batch B/P.

    Matches Trainer.evaluate's multi-process default (per_host=True):
    hosts read disjoint shards, the jitted step's global weighted sums
    merge them, padding equalizes differing per-host batch counts.
    Single-process: the identity (full set, full batch size).
    """
    import jax

    from tensorflow_examples_tpu.data.memory import InMemoryDataset

    nproc = jax.process_count()
    if nproc == 1:
        return eval_batches(test_ds, eval_bs)
    local = InMemoryDataset(
        {k: v[jax.process_index()::nproc] for k, v in test_ds.arrays.items()}
    )
    return eval_batches(local, max(eval_bs // nproc, 1))


def _iterators(workload, cfg):
    """Resolve (train_iter_fn(start), eval_iter_fn()) from the protocol."""
    eval_bs = cfg.eval_batch_size or cfg.global_batch_size
    if hasattr(workload, "make_train_iter"):
        train_fn = lambda start: workload.make_train_iter(cfg, start)
        eval_fn = (
            (lambda: workload.make_eval_iter(cfg))
            if hasattr(workload, "make_eval_iter")
            else None
        )
        local = getattr(workload, "train_iter_is_per_host", lambda c: False)(cfg)
        return train_fn, eval_fn, local
    train_ds, test_ds = workload.datasets(cfg)
    augment = (
        workload.train_augment(cfg) if hasattr(workload, "train_augment") else None
    )
    train_fn = lambda start: train_iterator(
        train_ds,
        cfg.global_batch_size,
        seed=cfg.seed,
        start_step=start,
        augment=augment,
    )
    eval_fn = lambda: _host_eval_batches(test_ds, eval_bs)
    return train_fn, eval_fn, False  # in-memory iterators are global-view


def _eval_iterator(workload, cfg):
    """Eval-only resolver: never loads the training split."""
    eval_bs = cfg.eval_batch_size or cfg.global_batch_size
    if hasattr(workload, "make_eval_iter"):
        return lambda: workload.make_eval_iter(cfg)
    if hasattr(workload, "eval_dataset"):
        test_ds = workload.eval_dataset(cfg)
    elif hasattr(workload, "make_train_iter"):
        return None
    else:
        _, test_ds = workload.datasets(cfg)
    return lambda: _host_eval_batches(test_ds, eval_bs)


def train_main(workload, default_cfg):
    """Build the absl main() for a workload's train.py."""
    define_flags_from_config(default_cfg)

    def main(argv):
        del argv
        cfg = _setup(workload, default_cfg)
        train_fn, eval_fn, local = _iterators(workload, cfg)
        trainer = _build_trainer(workload, cfg)
        metrics = trainer.fit(
            train_fn, eval_iter_fn=eval_fn, local_batches=local
        )
        print({k: round(v, 4) for k, v in metrics.items()})

    return main


def eval_main(workload, default_cfg):
    """Build the absl main() for a workload's eval.py."""
    define_flags_from_config(default_cfg)

    def main(argv):
        del argv
        cfg = _setup(workload, default_cfg)
        if not cfg.workdir:
            raise app.UsageError("--workdir is required for eval")
        eval_fn = _eval_iterator(workload, cfg)
        if eval_fn is None:
            raise app.UsageError(
                f"workload {workload.__name__} defines no eval pipeline"
            )
        trainer = _build_trainer(workload, cfg)
        restored = CheckpointManager(cfg.workdir).restore_latest(trainer.state)
        if restored is None:
            raise SystemExit(f"no checkpoint under {cfg.workdir}")
        trainer.state = restored[0]
        metrics = trainer.evaluate(eval_fn())
        print({k: round(v, 4) for k, v in metrics.items()})

    return main
