"""Resilience layer: preemption safety + bad-step guards (ISSUE 1).

The failure modes this handles are the pod-scale routine ones:

* **Preemption** (``PreemptionGuard``): SIGTERM/SIGINT set a flag; the
  training loop notices at the next step boundary, synchronously
  checkpoints, and raises :class:`Preempted` — a ``SystemExit`` subclass
  with exit code 0, so a preempted CLI run exits cleanly and the next
  run resumes bitwise-identically (stateless-resumable input order +
  step-keyed rng, see tests/test_resilience.py).

* **Bad steps** (``BadStepGuard``): NaN/Inf losses or gradients and loss
  spikes. Detection is split so the happy path adds NO host sync:

  - non-finite loss/grad_norm is caught ON DEVICE inside the jitted
    train step (train/loop.py): the update is skipped via ``jnp.where``
    (params/opt_state/model_state keep their old values, ``step`` still
    advances so the rng stream and data order move on) and a
    ``bad_step`` 0/1 metric is emitted;
  - the host guard POLLS those metrics without blocking (``is_ready``)
    a few steps behind the device, counts consecutive bad steps, tracks
    a loss EMA for spike detection, and escalates per
    ``TrainConfig.bad_step_policy``:

      ``skip``      keep skipping on device; abort only after
                    ``bad_step_patience`` consecutive bad steps (pure
                    skipping forever would be a silent hang).
      ``rollback``  after ``bad_step_patience`` consecutive bad steps,
                    restore the latest checkpoint and replay (the loop
                    rebuilds the input iterator at the restored step).
                    A second rollback landing on the same checkpoint
                    aborts — the fault is evidently not transient.
      ``abort``     raise on the first bad step observed.
      ``off``       no device guard compiled in, no host polling.

Watchdog / hung-step handling lives in utils/diagnostics.py; IO retry
and fault injection in utils/faults.py.

Telemetry (ISSUE 2): the guard publishes its formerly write-only
counts into the default metrics registry — ``resilience/bad_steps``,
``resilience/rollbacks``, and ``resilience/steps_lost`` (replayed work,
goodput's loss term) — so they appear in every JSONL window and the run
report. ``resilience/preemptions`` is counted by the training loop's
preempt-exit path, NOT in the signal handler: incrementing a locked
counter from a handler could deadlock against a main thread interrupted
while holding the registry lock.
"""

from __future__ import annotations

import collections
import logging
import signal
import threading
from typing import Any

import numpy as np

from tensorflow_examples_tpu.telemetry.registry import default_registry

log = logging.getLogger(__name__)

POLICIES = ("off", "skip", "rollback", "abort")


class Preempted(SystemExit):
    """Clean-exit signal: checkpoint saved, process should stop (code 0)."""

    def __init__(self, step: int, signum: int | None = None):
        super().__init__(0)
        self.step = step
        self.signum = signum

    def __str__(self):
        name = signal.Signals(self.signum).name if self.signum else "request"
        return f"preempted by {name}; resumable checkpoint at step {self.step}"


class BadStepError(RuntimeError):
    """The bad-step policy decided the run cannot continue."""


class PreemptionGuard:
    """SIGTERM/SIGINT -> 'checkpoint at the next step boundary' flag.

    Installable only from the main thread (signal module restriction);
    elsewhere it degrades to an inert guard. A second signal while one
    is already pending restores the original handler and re-raises, so a
    wedged run can still be force-killed.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self._requested = False
        self._signum: int | None = None
        self._old: dict[int, Any] = {}

    @property
    def requested(self) -> bool:
        return self._requested

    @property
    def signum(self) -> int | None:
        return self._signum

    def install(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            log.warning(
                "preemption guard not installed (not on the main thread)"
            )
            return self
        for sig in self.SIGNALS:
            self._old[sig] = signal.signal(sig, self._handle)
        return self

    def uninstall(self) -> None:
        for sig, old in self._old.items():
            try:
                signal.signal(sig, old)
            except (ValueError, TypeError):  # pragma: no cover - teardown
                pass
        self._old.clear()

    def _handle(self, signum, frame):
        if self._requested:
            # Second signal: the operator means it. Restore + re-raise.
            import os

            self.uninstall()
            if signal.getsignal(signum) in (self._handle, None):
                # The saved handler could not be restored (e.g. it was
                # C-installed and getsignal() gave None): fall back to
                # SIG_DFL so the re-raise terminates instead of looping
                # straight back into this handler.
                signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        self._requested = True
        self._signum = signum
        log.warning(
            "%s received: will checkpoint at the next step boundary and "
            "exit cleanly (send again to force-quit)",
            signal.Signals(signum).name,
        )


def _is_ready(x) -> bool:
    ready = getattr(x, "is_ready", None)
    if ready is None:
        return True  # numpy / python scalars are always ready
    try:
        return bool(ready())
    except Exception:  # pragma: no cover - deleted/donated array edge
        return True


class BadStepGuard:
    """Host-side divergence monitor over the device-emitted step metrics.

    ``observe()`` enqueues each step's (loss, bad_step) device scalars;
    ``poll()`` consumes only entries whose computation already finished
    (zero block on the happy path; the device runs a few steps ahead of
    the host thanks to async dispatch). The queue is force-drained when
    it exceeds ``max_pending`` — by then the oldest entry is long done —
    and at end of training via ``poll(drain=True)``.
    """

    def __init__(
        self,
        policy: str,
        *,
        patience: int = 5,
        spike_factor: float = 0.0,
        ema_decay: float = 0.9,
        max_pending: int = 64,
    ):
        if policy not in POLICIES:
            raise ValueError(
                f"bad_step_policy={policy!r}; expected one of {POLICIES}"
            )
        self.policy = policy
        self.patience = max(int(patience), 1)
        self.spike_factor = float(spike_factor)
        self._ema_decay = float(ema_decay)
        self._max_pending = max_pending
        self._pending: collections.deque = collections.deque()
        self._consecutive = 0
        self._ema: float | None = None
        self.rollbacks = 0
        self.bad_steps_seen = 0
        self._last_rollback_step: int | None = None
        self._last_bad: tuple[int, float] | None = None  # (step, loss)

    @classmethod
    def from_config(cls, cfg) -> "BadStepGuard | None":
        policy = getattr(cfg, "bad_step_policy", "off")
        if policy in ("off", "", None):
            return None
        return cls(
            policy,
            patience=getattr(cfg, "bad_step_patience", 5),
            spike_factor=getattr(cfg, "loss_spike_factor", 0.0),
        )

    # ------------------------------------------------------------- intake

    def observe(self, last_step: int, metrics) -> None:
        """Enqueue a chunk's metrics; ``last_step`` is the chunk's final
        step index. Bundled chunks carry [k]-vector metrics."""
        self._pending.append(
            (last_step, metrics.get("loss"), metrics.get("bad_step"))
        )

    def poll(self, *, drain: bool = False) -> str | None:
        """Inspect completed entries; returns None, "rollback", or raises
        :class:`BadStepError` for the abort outcomes."""
        while self._pending:
            step, loss, bad = self._pending[0]
            forced = drain or len(self._pending) > self._max_pending
            if not forced and not (_is_ready(loss) and _is_ready(bad)):
                break
            self._pending.popleft()
            action = self._inspect(step, loss, bad)
            if action is not None:
                return action
        return None

    def reset(self) -> None:
        """Post-rollback: stale pending entries refer to replayed steps."""
        self._pending.clear()
        self._consecutive = 0
        self._ema = None

    def note_rollback(self, restored_step: int) -> None:
        if self._last_rollback_step == restored_step:
            raise BadStepError(
                f"bad steps recurred after rolling back to step "
                f"{restored_step} twice — fault is not transient; aborting. "
                f"{self.status()}"
            )
        self._last_rollback_step = restored_step
        self.rollbacks += 1
        default_registry().counter("resilience/rollbacks").inc()
        # Replayed work = steps past the restored checkpoint that now run
        # twice; the last observed bad step bounds how far we had gotten.
        # The consecutive bad steps inside that span are already debited
        # via resilience/bad_steps — subtract them so goodput's loss
        # terms don't overlap (earlier non-consecutive bad steps in the
        # span are a tolerated approximation).
        if self._last_bad is not None:
            lost = self._last_bad[0] - restored_step - self._consecutive
            if lost > 0:
                default_registry().counter("resilience/steps_lost").inc(lost)
        self.reset()

    def status(self) -> str:
        where = (
            f"last bad step {self._last_bad[0]} (loss={self._last_bad[1]:g})"
            if self._last_bad
            else "no bad step recorded"
        )
        return (
            f"policy={self.policy} patience={self.patience} "
            f"bad_steps_seen={self.bad_steps_seen} "
            f"consecutive={self._consecutive} rollbacks={self.rollbacks}; "
            f"{where}"
        )

    # ----------------------------------------------------------- decision

    def _inspect(self, last_step: int, loss, bad) -> str | None:
        losses = np.ravel(np.asarray(loss, np.float64))
        bads = (
            np.ravel(np.asarray(bad, np.float64))
            if bad is not None
            else np.zeros_like(losses)
        )
        k = len(losses)
        for i, (lv, bv) in enumerate(zip(losses, bads)):
            step = last_step - (k - 1) + i
            is_bad = bv > 0 or not np.isfinite(lv)
            if not is_bad and self.spike_factor > 0 and self._ema is not None:
                is_bad = lv > self.spike_factor * max(abs(self._ema), 1e-8)
            if is_bad:
                self.bad_steps_seen += 1
                default_registry().counter("resilience/bad_steps").inc()
                self._consecutive += 1
                self._last_bad = (step, float(lv))
                if self.policy == "abort":
                    raise BadStepError(
                        f"bad train step {step} (loss={lv:g}) with "
                        f"policy=abort. {self.status()}"
                    )
                if self._consecutive >= self.patience:
                    if self.policy == "rollback":
                        return "rollback"
                    raise BadStepError(
                        f"{self._consecutive} consecutive bad steps ending "
                        f"at {step} exceeded patience={self.patience} with "
                        f"policy=skip. {self.status()}"
                    )
            else:
                self._consecutive = 0
                if np.isfinite(lv):
                    self._ema = (
                        lv
                        if self._ema is None
                        else self._ema_decay * self._ema
                        + (1 - self._ema_decay) * lv
                    )
        return None
