"""Config system: one dataclass per workload + an absl-flags CLI bridge.

Contract preserved from the reference (BASELINE.json:north_star): each
example keeps a ``python <example>/train.py --device=tpu`` CLI. Flags are
generated from the dataclass fields, so every config knob is a CLI flag.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from tensorflow_examples_tpu.core.mesh import MeshConfig


@dataclasses.dataclass
class TrainConfig:
    # Device / distribution
    device: str = "tpu"  # tpu | cpu — reference contract flag
    mesh_data: int = -1  # -1: all remaining devices on the data axis
    mesh_fsdp: int = 1
    mesh_model: int = 1
    mesh_context: int = 1
    mesh_pipe: int = 1

    # Optimization
    global_batch_size: int = 128
    eval_batch_size: int = 0  # 0 → global_batch_size
    train_steps: int = 1000
    warmup_steps: int = 0
    learning_rate: float = 1e-3
    weight_decay: float = 0.0
    grad_clip_norm: float = 0.0  # 0 disables
    grad_accum_steps: int = 1
    steps_per_launch: int = 1  # run K train steps per device launch via
    #   lax.scan (the Keras steps_per_execution equivalent): amortizes
    #   per-launch dispatch cost for small steps. Cadences (log/eval/
    #   checkpoint) and the step span must be multiples of K.
    precision: str = "bf16"  # f32 | bf16 | bf16_full
    remat: bool = False  # jax.checkpoint the model apply
    zero1: bool = False  # shard optimizer state over the batch axes even
    #   for replicated params (ZeRO-1 / weight-update sharding)
    sharding_config: str = ""  # path to a ShardingConfig JSON
    #   (tensorflow_examples_tpu/sharding/; docs/sharding.md): when set,
    #   it is the single source of truth for mesh shape, param rules,
    #   batch axes, and ZeRO-1 — the mesh_*/zero1 knobs above are
    #   ignored. Training persists the active config (from whichever
    #   source) to workdir/sharding.json; serving auto-loads it.

    # Loop cadence
    log_every: int = 100
    eval_every: int = 0  # 0 disables periodic eval
    checkpoint_every: int = 1000
    seed: int = 42

    # IO
    workdir: str = ""  # checkpoints + tensorboard; "" disables
    data_dir: str = ""  # dataset location; "" → synthetic data
    resume: bool = True  # restore latest checkpoint from workdir

    # Profiling / sanitizers
    profile: bool = False  # legacy sugar: capture a profiler trace
    #   around run-relative steps 10-20 (= profile_start_step=10,
    #   profile_num_steps=10)
    profile_start_step: int = 0  # with profile_num_steps > 0: first
    #   run-relative step of the windowed jax.profiler device trace
    #   (telemetry/profiling.py); the window is one-shot per fit
    profile_num_steps: int = 0  # steps the profiler window covers;
    #   0 disables (unless legacy --profile is set)
    profile_dir: str = ""  # trace output dir; "" → <workdir>/profile
    #   (or /tmp/tpu_profile without a workdir). The final JSONL line
    #   cross-links the captured window under "profile".
    debug_nans: bool = False  # jax_debug_nans: fail fast at the op that
    #   produced a NaN (SURVEY.md §5b — the functional model removes data
    #   races by construction; NaN tracing is the remaining sanitizer)
    watchdog_secs: float = 600.0  # hang detector: dump all thread stacks
    #   if no step completes for this long (0 disables; SURVEY.md §5c)

    # Resilience (train/resilience.py; docs/resilience.md)
    preempt_checkpoint: bool = True  # SIGTERM/SIGINT: checkpoint at the
    #   next step boundary, then exit cleanly (code 0) — the resumed run
    #   is bitwise-identical to an uninterrupted one
    bad_step_policy: str = "skip"  # off | skip | rollback | abort —
    #   what to do about NaN/Inf losses/grads and loss spikes. "skip"
    #   drops the bad update ON DEVICE (no host sync on the happy path)
    #   and aborts after bad_step_patience consecutive bad steps;
    #   "rollback" instead restores the latest checkpoint there
    bad_step_patience: int = 5  # consecutive bad steps before the
    #   skip->abort / rollback escalation
    loss_spike_factor: float = 0.0  # >0: a loss above factor*EMA(loss)
    #   also counts as a bad step (host-side, detection lags a few steps)
    watchdog_fatal_secs: float = 0.0  # >0: if a step/input stall lasts
    #   this long, dump diagnostics and fail fast (exit 87) instead of
    #   hanging the slice; 0 keeps the watchdog detection-only
    io_retries: int = 3  # bounded retries for flaky file reads
    #   (data/sources.py) with exponential backoff
    io_backoff_secs: float = 0.25  # initial backoff; doubles per retry
    max_skipped_batches: int = 0  # poisoned-batch skip budget in the
    #   prefetch pipeline: corrupt host batches are skipped (and counted)
    #   up to this many times before the run errors out; 0 = fail fast

    # Input pipeline (data/; docs/data.md)
    prefetch_depth: int = 2  # device-prefetch look-ahead: batches held
    #   host→device ahead of the consuming step (the floor when the
    #   adaptive controller is armed)
    prefetch_depth_max: int = 0  # > prefetch_depth arms depth-adaptive
    #   double buffering (data/prefetch.DepthController): the queue
    #   deepens toward this bound while the observed data_fetch p95
    #   dominates the device_step p95 and decays back when the input
    #   side is comfortably ahead; 0 keeps the fixed depth
    input_workers: int = 0  # background decode/augment worker threads
    #   (data/workers.py): > 0 moves the ImageNet TFRecord hot path onto
    #   the sharded-parallel python pipeline (N readers + this many
    #   decode workers, deterministic and exactly resumable); 0 keeps
    #   the inline tf.data/native path
    input_readers: int = 2  # parallel shard-reader threads of the
    #   python TFRecord pipeline (only meaningful with input_workers>0);
    #   1 = the literal sequential reference stream

    # Telemetry (tensorflow_examples_tpu/telemetry/; docs/observability.md)
    telemetry_sinks: str = "jsonl,tensorboard,console"  # comma list of
    #   metric sinks per log window: "jsonl" (schema-versioned
    #   workdir/telemetry/metrics.jsonl, crash-safe append, process 0),
    #   "tensorboard" (clu writer with explicit null-writer fallback),
    #   "console" (the classic step log line). File sinks need --workdir.
    telemetry_trace: bool = True  # export the host span timeline as
    #   Chrome-trace JSON (workdir/telemetry/trace.json) on exit — load
    #   in chrome://tracing or ui.perfetto.dev
    telemetry_flush_every: int = 1  # flush sinks every N log windows
    #   (1 = per window; the JSONL sink additionally flushes per line)
    telemetry_peak_tflops: float = 0.0  # per-device peak TFLOP/s for the
    #   MFU estimate; 0 = auto from the PJRT device kind (unknown kinds
    #   fall back to a labeled 1 TFLOP/s so the pipeline stays live)
    metrics_port: int = 0  # >0: serve live observability endpoints on
    #   this port from every process (telemetry/serve.py): /metrics
    #   (Prometheus text from the registry), /health (watchdog phase +
    #   last-window age; 503 on a stall), /window (latest JSONL line).
    #   Closed on every exit path including watchdog-fatal. 0 disables.
    straggler_skew_factor: float = 2.0  # fleet straggler threshold
    #   (telemetry/fleet.py): when the slowest host's step-time p95
    #   exceeds this multiple of the fleet median, the kind="fleet"
    #   line flags it and a WARNING names the host and whether the skew
    #   is compute- or input-side. 0 disables the warning (fleet lines
    #   still emit).
    compile_warmup: int = 1  # expected compilations per jitted step fn
    #   (telemetry/compilation.py): the first N distinct input
    #   signatures are normal jit warmup; any compile beyond that is a
    #   RECOMPILATION — logged at WARNING naming the shape/dtype delta
    #   and emitted as a kind="compile_warning" JSONL line

    def mesh_config(self) -> MeshConfig:
        return MeshConfig(
            data=self.mesh_data,
            fsdp=self.mesh_fsdp,
            model=self.mesh_model,
            context=self.mesh_context,
            pipe=self.mesh_pipe,
        )

    def replace(self, **kw) -> "TrainConfig":
        return dataclasses.replace(self, **kw)


def define_flags_from_config(config: Any, flags_module=None) -> None:
    """Register one absl flag per dataclass field (name, default, type)."""
    from absl import flags as absl_flags

    fl = flags_module or absl_flags
    for f in dataclasses.fields(config):
        default = getattr(config, f.name)
        if f.name in fl.FLAGS:
            continue
        if isinstance(default, bool):
            fl.DEFINE_boolean(f.name, default, f.name)
        elif isinstance(default, int):
            fl.DEFINE_integer(f.name, default, f.name)
        elif isinstance(default, float):
            fl.DEFINE_float(f.name, default, f.name)
        else:
            fl.DEFINE_string(f.name, str(default), f.name)


def config_from_flags(config: Any, flags_values=None) -> Any:
    """Overlay parsed absl flag values onto a config instance."""
    from absl import flags as absl_flags

    fv = flags_values or absl_flags.FLAGS
    updates = {}
    for f in dataclasses.fields(config):
        if f.name in fv:
            updates[f.name] = getattr(fv, f.name)
    return dataclasses.replace(config, **updates)


def apply_device_flag(device: str, *, debug_nans: bool = False) -> None:
    """Honor the reference's ``--device`` contract.

    ``--device=tpu`` is the default JAX platform selection; ``--device=cpu``
    forces the CPU backend (useful for tests and the §7 fallback given the
    experimental axon PJRT plugin).
    """
    import jax

    if device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    if debug_nans:
        jax.config.update("jax_debug_nans", True)
